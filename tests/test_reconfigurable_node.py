"""Deployable reconfigurable node over real sockets — loopback_rc_simple
parity (ref: ``tests/loopback_rc_simple/testing.properties`` +
``ReconfigurableNode.java:223-300``): boot 3 actives + 3 reconfigurators
as socket servers from properties config, then drive create -> requests ->
migrate -> delete through the reconfiguration-aware client
(``ReconfigurableAppClientAsync`` analog), including a request served
from a stale actives cache mid-migration."""

import socket
import time

from gigapaxos_tpu.testing.ports import free_ports

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.utils.config import Config


@pytest.fixture(scope="module")
def cluster():
    ports = free_ports(6)
    Config.clear()
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", HashChainApp, ar_cfg=ar_cfg, rc_cfg=rc_cfg)
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", HashChainApp, ar_cfg=ar_cfg, rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    client = ReconfigurableAppClient.from_properties()
    yield nodes, client
    client.close()
    for n in nodes:
        n.stop()
    Config.clear()


def ar_server(nodes, i):
    return nodes[i].servers[0]


def test_create_request_migrate_delete_over_sockets(cluster):
    nodes, client = cluster

    # --- create through the RCs --------------------------------------
    ack = client.create_name("svc", actives=[0, 1, 2], timeout=30)
    assert ack and ack.get("ok"), ack
    assert sorted(ack["actives"]) == [0, 1, 2]

    # --- resolve + app requests through epoch 0 ----------------------
    # under a loaded box the 6 in-process nodes can stall tens of seconds
    # on cold jax compiles; wait on the record itself before resolving
    deadline = time.time() + 90
    while time.time() < deadline:
        rec = nodes[3].servers[0].rc_app.get_record("svc")
        if rec is not None and rec.actives:
            break
        time.sleep(0.25)
    acts = None
    for _ in range(6):
        acts = client.request_actives("svc", timeout=10, force=True)
        if acts:
            break
    assert acts is not None and sorted(acts) == [0, 1, 2]
    for i in range(5):
        resp = client.send_request_sync("svc", f"r{i}", timeout=20)
        assert resp is not None, f"request r{i} timed out"

    apps = [ar_server(nodes, i).manager.app for i in range(3)]
    deadline = time.time() + 10
    while time.time() < deadline:
        states = [a.state.get("svc") for a in apps]
        if states[0] is not None and states[0] == states[1] == states[2]:
            break
        time.sleep(0.1)
    assert states[0] == states[1] == states[2], states

    # --- migrate [0,1,2] -> [1,2] (node 0 leaves) ---------------------
    ack = client.reconfigure("svc", [1, 2], timeout=40)
    assert ack and ack.get("ok"), ack
    assert sorted(ack["actives"]) == [1, 2] and ack["epoch"] == 1

    # old epoch drops off node 0 (best-effort; bounded wait)
    deadline = time.time() + 20
    while time.time() < deadline:
        if ar_server(nodes, 0).manager.names.get("svc") is None:
            break
        time.sleep(0.1)
    assert ar_server(nodes, 0).manager.names.get("svc") is None

    # --- stale-cache request lands at the departed active ------------
    # poison the cache so the next request targets node 0, which no
    # longer hosts the name: unknown_name -> invalidate -> re-resolve
    with client._lock:
        client._actives_cache["svc"] = (time.time() + 60, [0])
    resp = client.send_request_sync("svc", "post-migration", timeout=20)
    assert resp is not None, "mid-migration request did not recover"
    acts = None
    for _ in range(3):
        acts = client.request_actives("svc", force=True)
        if acts:
            break
    assert acts is not None and sorted(acts) == [1, 2]

    # state continuity on the new epoch
    a1 = ar_server(nodes, 1).manager.app
    a2 = ar_server(nodes, 2).manager.app
    deadline = time.time() + 10
    while time.time() < deadline:
        if a1.state.get("svc") == a2.state.get("svc") and \
                a1.n_executed.get("svc", 0) >= 6:
            break
        time.sleep(0.1)
    assert a1.state.get("svc") == a2.state.get("svc")
    assert a1.n_executed.get("svc", 0) >= 6  # 5 pre + 1 post migration

    # --- delete -------------------------------------------------------
    ack = client.delete_name("svc", timeout=40)
    assert ack and ack.get("ok"), ack
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(ar_server(nodes, i).manager.names.get("svc") is None
               for i in (1, 2)):
            break
        time.sleep(0.1)
    for i in (1, 2):
        assert ar_server(nodes, i).manager.names.get("svc") is None
    # record purged on every reconfigurator (DELETE_FINAL application may
    # lag the client ack by a few ticks on non-primary RCs)
    deadline = time.time() + 20
    while time.time() < deadline:
        if all(nodes[i].servers[0].rc_app.get_record("svc") is None
               for i in (3, 4, 5)):
            break
        time.sleep(0.1)
    for i in (3, 4, 5):
        assert nodes[i].servers[0].rc_app.get_record("svc") is None


def test_http_front_ends(cluster):
    """REST parity: create/resolve via the reconfigurator's HTTP API and
    execute an app request via an active's HTTP API (HttpReconfigurator
    .java:79 / HttpActiveReplica.java:29 analogs)."""
    import json as _json
    import urllib.request

    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.utils.config import Config

    nodes, client = cluster
    off = Config.get_int(PC.HTTP_PORT_OFFSET)
    rc = nodes[3].servers[0]
    ar = nodes[0].servers[0]
    assert rc._http is not None and ar._http is not None
    rc_url = f"http://127.0.0.1:{rc.transport.listen_port + off}"
    ar_url = f"http://127.0.0.1:{ar.transport.listen_port + off}"

    def post(url, payload, timeout=30):
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, _json.loads(r.read())

    code, body = post(rc_url, {
        "type": "CREATE", "name": "httpsvc", "actives": [0, 1, 2],
    })
    assert code == 200 and body["ok"], body

    with urllib.request.urlopen(
        f"{rc_url}/?name=httpsvc", timeout=20
    ) as r:
        resolved = _json.loads(r.read())
    assert resolved["ok"] and sorted(resolved["actives"]) == [0, 1, 2]

    code, body = post(ar_url, {"name": "httpsvc", "request": "via-http"})
    assert code == 200 and body["response"] is not None, body

    code, body = post(rc_url, {"type": "DELETE", "name": "httpsvc"})
    assert code == 200 and body["ok"], body
