"""Density-at-scale smoke (slow): a population of names several times
larger than the engine boots through the batched create + hibernate
path, churns a rotating hot window through the packed spill store, and
converges.  Asserts residency/correctness facts only — never wall-clock
(the 1M-name numbers live in ``scripts/density_probe.py`` output,
committed as DENSITY_r01.json)."""

import numpy as np
import pytest

from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.utils.config import Config

G = 1024
N_NAMES = 8192  # 8x the engine: most of the population is always asleep
WINDOW = 256  # awake working set per churn round


def _ticks(m, n=3):
    for _ in range(n):
        vec, _st = m.publish_snapshot()
        m.tick_host(np.stack([vec]), np.array([True]))


@pytest.mark.slow
def test_population_exceeds_engine_and_churns(tmp_path):
    from gigapaxos_tpu.manager import PaxosManager

    Config.set("PACKED_SPILL", "true")
    Config.set("PAUSE_BATCH_SIZE", "64")  # store RAM tier = 256 records
    Config.set("SPILL_SEGMENT_BYTES", "65536")
    cfg = EngineConfig(n_groups=G, window=8, req_lanes=4, n_replicas=1)
    names = [f"d{i:05d}" for i in range(N_NAMES)]
    m = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9, sync_journal=False,
    )
    try:
        # boot: the population never fits — every chunk sleeps on creation
        for lo in range(0, N_NAMES, G):
            chunk = names[lo:lo + G]
            m.create_paxos_batch(chunk, [0])
            assert m.hibernate_batch(chunk) == len(chunk)
        res = m.residency_stats()
        assert res["paused_names"] == N_NAMES
        assert res["active_names"] == 0
        # the RAM tier is capacity-bounded regardless of population
        assert res["paused_in_memory"] <= 4 * 64
        assert (res["paused_in_memory"] + res["paused_on_disk"]
                == N_NAMES)
        assert res["store"]["kind"] == "packed"
        assert res["store"]["segments"] > 1

        # churn: a rotating window wakes batched, proposes, sleeps again
        expected = {}
        for rnd in range(6):
            lo = rnd * WINDOW * 3  # strided heads: every round mostly cold
            window = [names[(lo + i) % N_NAMES] for i in range(WINDOW)]
            cold = [nm for nm in window if nm not in m.names]
            assert m.restore_batch(cold) == len(cold)
            for i, nm in enumerate(window[: 64]):
                m.propose(nm, str(rnd + i + 1))
                expected[nm] = expected.get(nm, 0) + rnd + i + 1
            _ticks(m, 4)
            fell_out = [nm for nm in list(m.names) if nm not in set(window)]
            m.hibernate_batch(fell_out)
            assert len(m.names) <= WINDOW
        _ticks(m, 6)

        # convergence: wake everything that saw traffic; totals exact
        touched = sorted(expected)
        cold = [nm for nm in touched if nm not in m.names]
        assert m.restore_batch(cold) == len(cold)
        _ticks(m, 6)
        bad = {nm: (m.app.totals.get(nm), expected[nm])
               for nm in touched if m.app.totals.get(nm) != expected[nm]}
        assert not bad, f"lost/duplicated traffic across churn: {bad}"

        # conservation still holds at the end
        res = m.residency_stats()
        assert res["active_names"] + res["paused_names"] == N_NAMES
    finally:
        Config.clear()
        m.close()


@pytest.mark.slow
def test_batched_wake_burst_matches_sequential_at_scale(tmp_path):
    """A >=512-name wake burst through ``restore_batch`` lands the same
    awake set and app state as the per-name loop (scale companion to
    the bit-exact leaf parity in test_batched_unpause)."""
    from gigapaxos_tpu.manager import PaxosManager

    Config.set("PACKED_SPILL", "true")
    cfg = EngineConfig(n_groups=2048, window=8, req_lanes=4, n_replicas=1)
    names = [f"b{i:04d}" for i in range(1024)]
    m = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9, sync_journal=False,
    )
    try:
        m.create_paxos_batch(names, [0])
        for i, nm in enumerate(names[:128]):
            m.propose(nm, str(i + 1))
        _ticks(m, 6)
        want = dict(m.app.totals)
        assert m.hibernate_batch(names) == len(names)

        burst = names[: 512]
        assert m.restore_batch(burst) == len(burst)
        assert m.hibernate_batch(burst) == len(burst)
        for nm in burst:  # the N=1 path over the same set
            assert m.restore(nm)
        _ticks(m, 4)
        assert set(m.names) == set(burst)
        for nm in burst:
            assert m.app.totals.get(nm, 0) == want.get(nm, 0)
    finally:
        Config.clear()
        m.close()
