"""Unified-step parity suite (the make_step factory).

The mesh-parameterized, N-steps-resident step must be BIT-EXACT against
the pre-refactor program: every state leaf and every StepOutputs field,
across mesh shapes (single device, the 1-D ('g',) group shard, the
(g, r) acceptor-per-chip mesh), steps_per_dispatch N in {1, 4}, and a
non-divisible group count.  The packed_host flavor must implement the
frozen-peer dispatch semantics (N serial ticks during which no new peer
frame lands, self row refreshed from the advancing state).  And the
pinned chaos seeds must stay green with ENGINE_STEPS_PER_DISPATCH > 1 —
the full deployed runtime (manager ring staging, post-step slab
requeue, journal-before-send) on the multi-step path.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL, ballot_coord
from gigapaxos_tpu.ops.engine import (
    EngineConfig,
    make_blob,
    pack_blob,
    split_out_vec,
    step,
    unpack_gathered,
)
from gigapaxos_tpu.parallel.mesh import make_group_mesh, make_mesh
from gigapaxos_tpu.parallel.spmd import build_replica_states, make_step
from gigapaxos_tpu.utils.config import Config


def golden_step(cfg, states, req, want):
    """The pre-refactor single-chip program, written out longhand: an
    eager per-replica loop over the pure engine step with the stacked
    compact blobs as the gather — no vmap, no jit, no factory code
    shared with the implementation under test."""
    R = cfg.n_replicas
    per = [jax.tree.map(lambda x: x[r], states) for r in range(R)]
    blobs = jax.tree.map(lambda *xs: jnp.stack(xs), *[make_blob(s) for s in per])
    heard = jnp.ones((R,), bool)
    news, outs = [], []
    for r, s in enumerate(per):
        ns, o = step(s, blobs, heard, req[r], want[r], jnp.int32(r), cfg)
        news.append(ns)
        outs.append(o)
    stack = lambda xs: jax.tree.map(lambda *ys: jnp.stack(ys), *xs)
    return stack(news), stack(outs)


def _coord_routed_requests(cfg, states, n_steps, vid0=1):
    """One request per group per step, routed at the (static) initial
    coordinator row — precomputed so the N>1 ring can stage the exact
    same schedule ahead of time."""
    R, G, K = cfg.n_replicas, cfg.n_groups, cfg.req_lanes
    coord = ballot_coord(np.asarray(states.bal)[0])
    reqs = []
    vid = vid0
    for _ in range(n_steps):
        req = np.full((R, G, K), NULL, np.int32)
        for g in range(G):
            req[int(coord[g]), g, 0] = vid
            vid += 1
        reqs.append(req)
    return reqs


def _assert_trees_equal(a, b, what):
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=f"{what}: {name}",
        )


MESHES = {
    "single_device": lambda: None,
    "gshard8": lambda: make_group_mesh(8),
    "gr_mesh": lambda: make_mesh(n_replicas=3, n_group_shards=2),
}

GOLDEN_CFG = EngineConfig(n_groups=13, window=8, req_lanes=4, n_replicas=3)
GOLDEN_STEPS = 8  # total engine steps (two dispatches at N=4)


@functools.lru_cache(maxsize=1)
def _golden_trajectory():
    """The longhand trajectory, computed ONCE for every (mesh, N) cell:
    the schedule is fixed, so the golden is mesh- and N-independent by
    definition — that IS the claim under test."""
    cfg, S = GOLDEN_CFG, GOLDEN_STEPS
    states = build_replica_states(cfg)
    reqs = _coord_routed_requests(cfg, states, S)
    # an election pulse at step 0 only: want_coord fires at substep 0 of
    # a dispatch by design, so a mid-ring pulse has no N=1 equivalent
    wants = [np.zeros((3, 13), bool) for _ in range(S)]
    wants[0][0, 0] = True
    outs = []
    for t in range(S):
        states, o = golden_step(
            cfg, states, jnp.asarray(reqs[t]), jnp.asarray(wants[t])
        )
        outs.append(o)
    return states, outs, reqs, wants


@pytest.mark.parametrize("mesh_key", sorted(MESHES))
@pytest.mark.parametrize("n", [1, 4])
def test_unified_step_matches_golden(mesh_key, n):
    """make_step == the longhand pre-refactor program, for every state
    leaf and every per-substep StepOutputs field — across mesh shapes,
    N in {1, 4}, and a NON-divisible G (13 over 8 and over 2 shards:
    GSPMD pads internally; the old shard_map path never could)."""
    cfg, S = GOLDEN_CFG, GOLDEN_STEPS
    mesh = MESHES[mesh_key]()
    fn = make_step(cfg, mesh, n, donate=False)
    states_g, golden_outs, reqs, wants = _golden_trajectory()
    states_u = build_replica_states(cfg)

    unified_outs = []
    for d in range(S // n):
        sl = slice(d * n, (d + 1) * n)
        if n == 1:
            req = jnp.asarray(reqs[d])
        else:
            req = jnp.asarray(np.stack(reqs[sl]))
        states_u, out = fn(states_u, req, jnp.asarray(wants[d * n]))
        if n == 1:
            unified_outs.append(out)
        else:
            unified_outs.extend(
                jax.tree.map(lambda x: x[i], out) for i in range(n)
            )

    _assert_trees_equal(states_g, states_u, f"state[{mesh_key},N={n}]")
    for t, (a, b) in enumerate(zip(golden_outs, unified_outs)):
        _assert_trees_equal(a, b, f"outs[{mesh_key},N={n},t={t}]")
    # the schedule did real work (not vacuous parity)
    assert int(np.asarray(states_u.exec_slot).min()) >= S - 4


def test_stacked_multistep_equals_sequential():
    """N=4 residency == 4 sequential N=1 dispatches from the same
    states: the fori_loop body IS the single-step program."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    fn1 = make_step(cfg, None, 1, donate=False)
    fn4 = make_step(cfg, None, 4, donate=False)
    s1 = build_replica_states(cfg)
    s4 = build_replica_states(cfg)
    reqs = _coord_routed_requests(cfg, s1, 4)
    want = jnp.zeros((3, 8), bool)
    outs1 = []
    for t in range(4):
        s1, o = fn1(s1, jnp.asarray(reqs[t]), want)
        outs1.append(o)
    s4, o4 = fn4(s4, jnp.asarray(np.stack(reqs)), want)
    _assert_trees_equal(s1, s4, "state")
    for i, o in enumerate(outs1):
        _assert_trees_equal(o, jax.tree.map(lambda x: x[i], o4), f"t={i}")


def test_packed_flavor_frozen_peer_parity():
    """packed_host at N=4 == 4 serial legacy host ticks during which no
    peer frame lands: substep 0 consumes the gathered matrix verbatim,
    substeps >= 1 refresh only MY row from the advancing state.  Checks
    the final state, every per-substep out-ring row (field-by-field via
    split_out_vec), and the returned blob_vec."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    N, my_id = 4, 0
    states = build_replica_states(cfg)
    per = [jax.tree.map(lambda x: x[r], states) for r in range(3)]
    gvec = jnp.stack([pack_blob(make_blob(s)) for s in per])
    heard = jnp.ones((3,), bool)
    reqs = [
        np.full((8, 4), NULL, np.int32) for _ in range(N)
    ]
    coord = ballot_coord(np.asarray(states.bal)[0])
    vid = 1
    for t in range(N):
        for g in range(8):
            if int(coord[g]) == my_id:
                reqs[t][g, 0] = vid
            vid += 1
    want = jnp.zeros((8,), bool)

    # golden: serial single-step host ticks with frozen peer rows
    st = per[my_id]
    g0 = unpack_gathered(gvec, cfg)
    golden_rows = []
    for i in range(N):
        g = g0 if i == 0 else jax.tree.map(
            lambda gl, bl: gl.at[my_id].set(bl), g0, make_blob(st)
        )
        st, out = step(st, g, heard, jnp.asarray(reqs[i]), want,
                       jnp.int32(my_id), cfg=cfg)
        golden_rows.append(out)
    golden_blob = np.asarray(pack_blob(make_blob(st)))

    fn = make_step(cfg, None, N, donate=False, io="packed_host")
    st_u, out_rings, blob_vec = fn(
        per[my_id], gvec, heard, jnp.asarray(np.stack(reqs)), want,
        jnp.int32(my_id),
    )
    _assert_trees_equal(st, st_u, "state")
    rows = np.asarray(out_rings)
    assert rows.shape[0] == N
    for i, g_out in enumerate(golden_rows):
        u_out = split_out_vec(rows[i], cfg)
        _assert_trees_equal(g_out, u_out, f"out_ring[{i}]")
    np.testing.assert_array_equal(golden_blob, np.asarray(blob_vec))
    # peers are frozen for the whole dispatch, so commits need a later
    # exchange — ADMISSION is the local progress that proves the ring
    # slabs actually fed the substeps
    admitted = sum(int(np.asarray(o.n_admitted).sum()) for o in golden_rows)
    assert admitted > 0


def test_make_step_validates_and_memoizes():
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    with pytest.raises(ValueError):
        make_step(cfg, None, 0)
    with pytest.raises(ValueError):
        make_step(cfg, None, 1, io="nope")
    assert make_step(cfg, None, 2) is make_step(cfg, None, 2)


# the deployed-runtime gate: the recorded chaos schedules (traffic +
# loss + duplicate retransmits + migrations + pauses) must settle and
# pass the exactly-once audit when every manager runs the multi-step
# dispatch path.  Each pinned seed runs through the harness where its
# schedule was RECORDED green: 662625602 (the PR-2 unpaired-dedup-
# install breach shape, also the PR-8 ballot-cache wedge witness) is a
# run_soak shape; 20260804 is the worker-shard family's schedule
# (test_serving_workers.py) — through plain run_soak it is wall-clock
# flaky even at N=1, so that pairing would gate on timing, not on the
# multistep path.
def test_chaos_pinned_seed_multistep_662625602():
    from gigapaxos_tpu.testing.chaos import run_soak

    Config.set("ENGINE_STEPS_PER_DISPATCH", "4")
    # run_soak's finally clears Config (including the key set above)
    run_soak(662625602, rounds=30)


def test_chaos_pinned_seed_multistep_20260804_sharded():
    from gigapaxos_tpu.testing.chaos import run_sharded_soak

    Config.set("ENGINE_STEPS_PER_DISPATCH", "4")
    # run_sharded_soak's finally clears Config (including the key above)
    out = run_sharded_soak(20260804, workers=2, rounds=30, n_names=6)
    assert out["workers"] == 2
