"""Recovery-plane tests: sharded checkpoints (manifest hashes, torn-shard
fallback, kill mid-shard-write), segmented parallel replay parity,
mid-replay crash idempotence, and the lazy-hydration gates — run against
both the native journal path and ``GP_NO_NATIVE=1``."""

import os

import numpy as np
import pytest

from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig, init_state
from gigapaxos_tpu.storage import BlockType, Journal, PaxosLogger
from gigapaxos_tpu.storage.checkpoint import (
    MANIFEST,
    load_checkpoint_view,
    save_checkpoint,
)
from gigapaxos_tpu.utils.config import Config

CFG = EngineConfig(n_groups=8, window=4, req_lanes=2, n_replicas=3)


@pytest.fixture(params=["native", "python"])
def native_mode(request, monkeypatch):
    """Run journal-touching tests under both CRC/append paths."""
    import gigapaxos_tpu.native as nat

    if request.param == "python":
        monkeypatch.setenv("GP_NO_NATIVE", "1")
    nat._lib = None
    nat._tried = False
    yield request.param
    nat._lib = None
    nat._tried = False


def _state_arrays(cfg):
    return {
        k: np.asarray(v).copy() for k, v in init_state(cfg)._asdict().items()
    }


def _logger(tmp_path, shards=4, **kw):
    Config.set("RECOVERY_CHECKPOINT_SHARDS", str(shards))
    return PaxosLogger(0, str(tmp_path), **kw)


def _seed_groups(lg, n=4):
    lg.log_create(
        np.arange(n), np.full(n, 0b111), np.zeros(n, np.int64),
        np.zeros(n, np.int64),
    )


# ---------------------------------------------------------------------------
# sharded checkpoints
# ---------------------------------------------------------------------------

def test_sharded_recover_matches_legacy(tmp_path, native_mode):
    """The same history recovered through a sharded checkpoint and a
    legacy single-pair checkpoint must be identical."""
    dirs = {}
    for mode, shards in (("sharded", 4), ("legacy", 1)):
        d = tmp_path / mode
        lg = _logger(d, shards=shards)
        _seed_groups(lg)
        lg.log_accepts(
            np.array([0, 1]), np.array([0, 0]),
            np.array([32, 32]), np.array([100, 200]),
        )
        rec = lg.recover(CFG.window, seed_arrays=_state_arrays(CFG))
        lg.checkpoint(
            rec.arrays, {"svc0": "s0", "svc1": "s1"},
            {"names": {"svc0": 0, "svc1": 1}},
        )
        lg.log_decisions(np.array([0]), np.array([0]), np.array([100]))
        lg.close()
        lg2 = _logger(d, shards=shards)
        dirs[mode] = lg2.recover(CFG.window)
        lg2.close()
    a, b = dirs["sharded"], dirs["legacy"]
    for k in a.arrays:
        assert (a.arrays[k] == b.arrays[k]).all(), k
    assert a.meta["app_states"] == b.meta["app_states"]
    assert a.decisions == b.decisions


def test_torn_shard_falls_back_to_prev_anchor(tmp_path, native_mode):
    """Corrupting one shard of the newest generation must fail its
    manifest hash; recovery falls back to the previous generation's
    anchor and REPLAYS the journal gap — end state identical."""
    lg = _logger(tmp_path, shards=4)
    _seed_groups(lg)
    rec0 = lg.recover(CFG.window, seed_arrays=_state_arrays(CFG))
    lg.checkpoint(rec0.arrays, {"svc": "gen1"}, {"names": {"svc": 0}})
    # post-gen1 history, then a second checkpoint covering it
    lg.log_decisions(np.array([0, 0]), np.array([0, 1]), np.array([7, 8]))
    lg.log_payloads({7: "p7", 8: "p8"})
    rec1 = lg.recover(CFG.window)
    lg.checkpoint(rec1.arrays, {"svc": "gen2"}, {"names": {"svc": 0}})
    lg.close()

    # tear a generation-2 shard mid-body (simulated partial write)
    view = load_checkpoint_view(str(tmp_path))
    assert view.generation == 2
    import json

    with open(os.path.join(str(tmp_path), MANIFEST)) as f:
        man = json.load(f)
    victim = os.path.join(str(tmp_path), man["shards"][0]["file"])
    with open(victim, "r+b") as f:
        f.seek(40)
        f.write(b"TORNTORN")

    lg2 = _logger(tmp_path, shards=4)
    rec2 = lg2.recover(CFG.window)
    # fell back to generation 1 ... (earlier anchor)
    assert rec2.stats["checkpoint_generation"] == 1
    assert rec2.meta["app_states"] == {"svc": "gen1"}
    # ... and the journal replay closed the gap: both decisions are back
    assert rec2.decisions[0] == {0: 7, 1: 8}
    assert rec2.payloads == {7: "p7", 8: "p8"}
    lg2.close()


def test_kill_mid_checkpoint_shard_write(tmp_path, native_mode, monkeypatch):
    """A crash AFTER some shards are written but BEFORE the manifest
    lands must leave the previous generation fully loadable (the orphan
    shards are invisible without their manifest)."""
    lg = _logger(tmp_path, shards=4)
    _seed_groups(lg)
    rec0 = lg.recover(CFG.window, seed_arrays=_state_arrays(CFG))
    lg.checkpoint(rec0.arrays, {"svc": "gen1"}, {"names": {"svc": 0}})

    import gigapaxos_tpu.storage.checkpoint as ck

    real_write = ck._fsync_write

    def die_at_manifest(path, data):
        if MANIFEST in path:
            raise OSError("simulated crash mid-checkpoint")
        real_write(path, data)

    monkeypatch.setattr(ck, "_fsync_write", die_at_manifest)
    with pytest.raises(OSError):
        lg.checkpoint(rec0.arrays, {"svc": "gen2"}, {"names": {"svc": 0}})
    monkeypatch.setattr(ck, "_fsync_write", real_write)
    lg.close()

    lg2 = _logger(tmp_path, shards=4)
    rec = lg2.recover(CFG.window)
    assert rec.stats["checkpoint_generation"] == 1
    assert rec.meta["app_states"] == {"svc": "gen1"}
    lg2.close()


def test_gc_preserves_prev_manifest_shards_after_rename_crash(tmp_path):
    """A crash BETWEEN the manifest demote and promote renames leaves
    only PREV_MANIFEST on disk; the next save's shard GC must keep that
    generation's shards — they are the torn-shard fallback target."""
    import numpy as np

    from gigapaxos_tpu.storage.checkpoint import (
        PREV_MANIFEST,
        load_checkpoint_view,
        save_checkpoint_sharded,
    )

    d = str(tmp_path)
    arrays = {"a": np.arange(8)}
    meta = {"names": {}, "app_states": {}}
    save_checkpoint_sharded(d, arrays, meta, 2)                   # gen 1
    save_checkpoint_sharded(d, {"a": np.arange(8) + 1}, meta, 2)  # gen 2
    # simulate the crash window: demote done, promote never happened
    os.replace(os.path.join(d, MANIFEST), os.path.join(d, PREV_MANIFEST))
    save_checkpoint_sharded(d, {"a": np.arange(8) + 2}, meta, 2)  # gen 3
    # tear generation 3: the fallback must still find gen 2's shards
    view = load_checkpoint_view(d)
    assert view.generation == 3
    import json

    with open(os.path.join(d, MANIFEST)) as f:
        victim = json.load(f)["shards"][0]["file"]
    with open(os.path.join(d, victim), "r+b") as f:
        f.seek(30)
        f.write(b"XXXX")
    fb = load_checkpoint_view(d)
    assert fb is not None and fb.generation == 2
    assert (fb.arrays["a"] == np.arange(8) + 1).all()


# ---------------------------------------------------------------------------
# segmented replay
# ---------------------------------------------------------------------------

def _multi_file_history(tmp_path, shards, workers):
    Config.set("RECOVERY_REPLAY_WORKERS", str(workers))
    lg = _logger(tmp_path, shards=shards, max_file_size=512)
    _seed_groups(lg, n=6)
    for i in range(40):
        g = i % 6
        lg.log_accepts(
            np.array([g]), np.array([i // 6]),
            np.array([32 + i]), np.array([1000 + i]),
        )
        lg.log_decisions(
            np.array([g]), np.array([i // 6]), np.array([1000 + i])
        )
        lg.log_payloads({1000 + i: f"req{i}"})
    return lg


def test_segmented_replay_parity(tmp_path, native_mode):
    """Parallel segmented replay must produce byte-identical recovered
    state to the sequential scan, across a multi-file journal."""
    recs = {}
    for label, workers in (("seq", 1), ("par", 4)):
        d = tmp_path / label
        lg = _multi_file_history(d, shards=4, workers=workers)
        assert len(lg.journal.file_indices()) > 3, "wants many segments"
        rec = lg.recover(CFG.window, seed_arrays=_state_arrays(CFG))
        recs[label] = rec
        lg.close()
    a, b = recs["seq"], recs["par"]
    for k in a.arrays:
        assert (a.arrays[k] == b.arrays[k]).all(), k
    assert a.payloads == b.payloads
    assert a.decisions == b.decisions
    assert b.stats["segments"] > 3


def test_mid_replay_crash_is_idempotent(tmp_path, native_mode):
    """Replay mutates nothing durable: recovering, 'crashing' (just
    abandoning the result), and recovering again must agree — and a torn
    journal tail mid-segment stops the scan cleanly at the tear."""
    lg = _multi_file_history(tmp_path, shards=4, workers=4)
    first = lg.recover(CFG.window, seed_arrays=_state_arrays(CFG))
    lg.close()

    # torn tail: truncate into the middle of the last file's last block
    idxs = sorted(
        f for f in os.listdir(tmp_path) if f.startswith("journal_")
    )
    last = os.path.join(str(tmp_path), idxs[-1])
    size = os.path.getsize(last)
    with open(last, "r+b") as f:
        f.truncate(size - 3)

    lg2 = _logger(tmp_path, shards=4)
    again = lg2.recover(CFG.window, seed_arrays=_state_arrays(CFG))
    third = lg2.recover(CFG.window, seed_arrays=_state_arrays(CFG))
    lg2.close()
    # idempotent across repeated replays of the same (torn) journal
    for k in again.arrays:
        assert (again.arrays[k] == third.arrays[k]).all(), k
    assert again.payloads == third.payloads
    # the tear cost exactly the blocks at/after it, nothing else: the
    # re-scan reached every payload the first scan saw except the tail
    assert set(again.payloads) <= set(first.payloads)
    assert len(first.payloads) - len(again.payloads) <= 1


# ---------------------------------------------------------------------------
# lazy hydration (manager level, deterministic: background worker off)
# ---------------------------------------------------------------------------

def _ticks(m, n=6):
    for _ in range(n):
        vec, _st = m.publish_snapshot()
        m.tick_host(np.stack([vec]), np.array([True]))


@pytest.fixture
def no_background(monkeypatch):
    from gigapaxos_tpu.recovery.hydration import Hydrator

    monkeypatch.setattr(Hydrator, "start_background", lambda self: None)


def _restartable_manager(tmp_path, n_names=10):
    from gigapaxos_tpu.manager import PaxosManager

    cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=1)
    m = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9,
    )
    names = [f"svc{i}" for i in range(n_names)]
    m.create_paxos_batch(names, [0])
    for i, nm in enumerate(names):
        m.propose(nm, str(i + 1))
        _ticks(m)
    return m, cfg, names


def test_lazy_restart_serves_hot_gates_cold(tmp_path, no_background):
    from gigapaxos_tpu.manager import PaxosManager

    Config.set("RECOVERY_CHECKPOINT_SHARDS", "4")
    Config.set("RECOVERY_HOT_NAMES", "3")
    Config.set("RECOVERY_HYDRATION_BATCH", "2")
    m, cfg, names = _restartable_manager(tmp_path)
    m.checkpoint_now()
    m.logger.drain_checkpoints()
    m.propose("svc0", "100")  # post-checkpoint journal tail
    _ticks(m)
    m.close()

    m2 = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9,
    )
    try:
        assert m2.recovery_phase == "recovering"
        st = m2.recovery_stats()
        assert st["hydration_backlog"] == 10 - 3
        assert st["hot_hydrated"] == 3
        hot = [n for n in names if m2.names[n] not in m2.hydrating_rows]
        cold = [n for n in names if m2.names[n] in m2.hydrating_rows]
        assert len(hot) == 3 and len(cold) == 7
        # hot names carry correct state NOW; cold are not restored yet
        for nm in hot:
            exp = 101 if nm == "svc0" else int(nm[3:]) + 1
            assert m2.app.totals.get(nm) == exp, (nm, m2.app.totals)
        for nm in cold:
            assert nm not in m2.app.totals
        # a cold name's request queues but does NOT execute while cold
        got = {}
        m2.propose(cold[0], "1000", callback=lambda r, v: got.update(v=v))
        _ticks(m2, 3)
        assert not got
        # pause/donor/read surfaces refuse un-hydrated names
        epoch = m2.current_epoch(cold[0])
        assert m2.pause_group(cold[0], epoch) == "busy"
        assert not m2.app_caught_up(cold[0])
        assert not m2.local_read_ok(cold[0])
        assert m2.local_read_ok(hot[0])
        # checkpointing is deferred while recovering (a snapshot now
        # would persist blank cold states as a newer generation)
        m2.checkpoint_now()
        assert m2.metrics.get("recovery_checkpoint_deferred") == 1
        # the queued request promoted its name: it hydrates first
        assert m2.hydrator.hydrate_batch() > 0
        assert m2.names[cold[0]] not in m2.hydrating_rows
        # drain fully: phase flips, held traffic executes, totals agree
        assert m2.hydrate_all(60)
        assert m2.recovery_phase == "serving"
        _ticks(m2)
        for nm in names:
            exp = 101 if nm == "svc0" else int(nm[3:]) + 1
            if nm == cold[0]:
                exp += 1000
            assert m2.app.totals.get(nm) == exp, (nm, m2.app.totals)
        assert got.get("v") is not None
    finally:
        m2.close()


def test_eager_mode_restores_everything_up_front(tmp_path):
    from gigapaxos_tpu.manager import PaxosManager

    Config.set("RECOVERY_CHECKPOINT_SHARDS", "4")
    Config.set("RECOVERY_LAZY_HYDRATION", "false")
    m, cfg, names = _restartable_manager(tmp_path, n_names=6)
    m.checkpoint_now()
    m.logger.drain_checkpoints()
    m.close()
    m2 = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9,
    )
    try:
        assert m2.recovery_phase == "serving"
        assert not m2.hydrating_rows and m2.hydrator is None
        for i, nm in enumerate(names):
            assert m2.app.totals.get(nm) == i + 1
    finally:
        m2.close()


def test_background_hydration_drains(tmp_path):
    """Liveness: with the background worker ON, a lazy restart reaches
    phase=serving on its own (generous deadline, no hard wall-clock)."""
    import time

    from gigapaxos_tpu.manager import PaxosManager

    Config.set("RECOVERY_CHECKPOINT_SHARDS", "4")
    Config.set("RECOVERY_HOT_NAMES", "2")
    Config.set("RECOVERY_HYDRATION_BATCH", "1")
    m, cfg, names = _restartable_manager(tmp_path)
    m.checkpoint_now()
    m.logger.drain_checkpoints()
    m.close()
    m2 = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9,
    )
    try:
        deadline = time.time() + 60
        while m2.recovery_phase != "serving" and time.time() < deadline:
            time.sleep(0.02)
        assert m2.recovery_phase == "serving"
        for i, nm in enumerate(names):
            assert m2.app.totals.get(nm) == i + 1
    finally:
        m2.close()
