"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding paths are exercised without TPU hardware (the bench and
driver use the real chip; tests never should)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_config():
    from gigapaxos_tpu.utils.config import Config

    yield
    Config.clear()
