"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding paths are exercised without TPU hardware (the bench and
driver use the real chip; tests never should).

Note: a site hook may register a TPU-proxy backend and override
``jax_platforms`` via ``jax.config`` at interpreter startup, so setting the
``JAX_PLATFORMS`` env var alone is NOT enough — we must also write the
config back to "cpu" after importing jax and before any backend init."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-run soak tests excluded from the tier-1 gate "
        "(-m 'not slow')",
    )


@pytest.fixture(autouse=True)
def _clear_config():
    from gigapaxos_tpu.utils.config import Config

    yield
    Config.clear()
