"""SPMD sharding tests on the virtual 8-device CPU mesh: the multi-chip
replica-axis path must produce bit-identical results to the host-simulated
cluster, and commits must flow end-to-end through shard_map + all_gather."""

import jax
import jax.numpy as jnp
import numpy as np

from gigapaxos_tpu.ops.ballot import NULL, ballot_coord
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.parallel.mesh import make_mesh, pick_mesh_shape
from gigapaxos_tpu.parallel.spmd import (
    build_replica_states,
    replicate_inputs,
    single_chip_step,
    spmd_step,
)

build_states = build_replica_states


def drive(step_fn, states, cfg, n_steps, vid0=1):
    """Feed one request per group per step to the right coordinator row."""
    R, G, K = cfg.n_replicas, cfg.n_groups, cfg.req_lanes
    vid = vid0
    total = 0
    for _ in range(n_steps):
        req = np.full((R, G, K), NULL, np.int32)
        coord = ballot_coord(np.asarray(states.bal)[0])  # coord of each group
        for g in range(G):
            req[int(coord[g]), g, 0] = vid
            vid += 1
        want = np.zeros((R, G), bool)
        states, out = step_fn(states, jnp.asarray(req), jnp.asarray(want))
        total += int(np.asarray(out.n_committed)[0].sum())
    return states, total


def test_pick_mesh_shape():
    assert pick_mesh_shape(8) == (4, 2)
    assert pick_mesh_shape(6) == (2, 3)
    assert pick_mesh_shape(3) == (1, 3)
    assert pick_mesh_shape(1) == (1, 1)
    assert pick_mesh_shape(8, n_replicas=2) == (4, 2)


def test_single_chip_vmap_commits():
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    states = build_states(cfg)
    fn = single_chip_step(cfg)
    states, total = drive(fn, states, cfg, 12)
    fr = np.asarray(states.exec_slot)
    assert (fr == fr[0]).all()
    assert fr.min() >= 8  # 12 injected minus pipeline latency
    h = np.asarray(states.app_hash)
    assert (h == h[0]).all() and (h[0] != 0).all()


def test_spmd_matches_single_chip():
    """shard_map over (g=2, r=3) must produce identical state to vmap."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    mesh = make_mesh(n_replicas=3, n_group_shards=2)
    vm = single_chip_step(cfg)
    sm = spmd_step(cfg, mesh)

    states_v = build_states(cfg)
    states_s = build_states(cfg)
    req = np.full((3, 8, 4), NULL, np.int32)
    req[0, 0, :2] = [5, 6]
    req[1, 1, 0] = 7
    want = np.zeros((3, 8), bool)

    states_s, req_s, want_s = replicate_inputs(
        mesh, states_s, jnp.asarray(req), jnp.asarray(want)
    )
    for t in range(6):
        r = jnp.asarray(req) if t == 0 else jnp.full((3, 8, 4), NULL, jnp.int32)
        w = jnp.asarray(want)
        states_v, out_v = vm(states_v, r, w)
        states_s, out_s = sm(states_s, r, w)
    for name in states_v._fields:
        a = np.asarray(getattr(states_v, name))
        b = np.asarray(getattr(states_s, name))
        np.testing.assert_array_equal(a, b, err_msg=name)
    fr = np.asarray(states_s.exec_slot)
    assert fr[0, 0] == 2 and fr[0, 1] == 1  # the injected requests committed


def test_spmd_8dev_2replica_mesh():
    """8 devices -> (g=4, r=2) mesh: 2-replica groups, majority 2."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=2, n_replicas=2)
    mesh = make_mesh(n_replicas=2, n_group_shards=4)
    fn = spmd_step(cfg, mesh)
    states = build_states(cfg)
    states, total = drive(fn, states, cfg, 10)
    fr = np.asarray(states.exec_slot)
    assert (fr == fr[0]).all() and fr.min() >= 6
