"""Lifecycle THROUGH the shard_map path (VERDICT r4 weak #3): the spmd
equivalence and fault tests drove only static full-membership groups —
no reconfiguration, residency, or tag-guard behavior had ever executed
through the sharded deployment shape.  These tests run the lifecycle
primitives (kill/create at a new epoch, the per-row instance tag guard
against stale holdouts, and the pause/resume jump) between shard_map
steps on the virtual 8-device mesh, asserting the same isolation and
agreement invariants the host-sim cluster enforces.

Lifecycle ops are HOST-side by design (the deployed manager applies
them between ticks under its lock); what must work on the sharded path
is stepping THROUGH consensus correctly before and after the surgery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.ops.lifecycle import create_groups, jump_rows, kill_groups
from gigapaxos_tpu.parallel.mesh import make_mesh
from gigapaxos_tpu.parallel.spmd import build_replica_states, spmd_step

R, G, K, W = 4, 8, 4, 8
CFG = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)


def _mesh_or_skip():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh (conftest XLA_FLAGS)")
    return make_mesh(n_replicas=R, n_group_shards=2)


def _apply_per_replica(states, fn):
    """Unstack [R, ...] -> apply a lifecycle op per replica -> restack."""
    per = [jax.tree.map(lambda x: x[r], states) for r in range(R)]
    per = [fn(r, s) for r, s in enumerate(per)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per)


def _np_leaf(states, leaf):
    return np.asarray(getattr(states, leaf))


def _drive(step_fn, states, row, vids, n_steps=8):
    """Offer `vids` at `row` on every replica's lanes for n_steps."""
    for i in range(n_steps):
        req = np.full((R, G, K), NULL, np.int32)
        for j, v in enumerate(vids[: K]):
            req[:, row, j] = v
        want = np.zeros((R, G), bool)
        states, out = step_fn(states, jnp.asarray(req), jnp.asarray(want))
    return states


def test_epoch_upgrade_and_tag_guard_through_shard_map():
    """Kill+re-create a row at a NEW epoch on 3 of 4 replicas (members
    [0,1,2]); replica 3 keeps the OLD tenant untouched (a stale holdout).
    The new group must reach consensus among its members through
    shard_map, and the holdout's stale row must neither advance with the
    new tenant's decisions nor contaminate them (the per-row instance
    tag guard, a chaos-soak find on the host path)."""
    mesh = _mesh_or_skip()
    states = build_replica_states(CFG)
    step_fn = spmd_step(CFG, mesh)
    row = 3

    # epoch 0: everyone commits something on the row
    states = _drive(step_fn, states, row, [11, 12, 13])
    exec0 = _np_leaf(states, "exec_slot")[:, row]
    assert (exec0 > 0).all(), exec0
    hash0 = _np_leaf(states, "app_hash")[:, row]
    assert len(set(hash0.tolist())) == 1

    # reconfigure on replicas 0..2 only: new epoch 1, members [0,1,2],
    # a fresh instance tag; replica 3 is a stale holdout of epoch 0
    new_tag = 777

    def surgery(rid, s):
        if rid == 3:
            return s
        s = kill_groups(s, jnp.array([row]))
        return create_groups(
            s, jnp.array([row]), jnp.array([0b0111]), jnp.array([0]),
            my_id=rid, version=1, tag=new_tag,
        )
    states = _apply_per_replica(states, surgery)

    # epoch 1 traffic: members 0-2 must commit; the holdout must not move
    hold_exec_before = int(_np_leaf(states, "exec_slot")[3, row])
    states = _drive(step_fn, states, row, [21, 22], n_steps=10)
    exec1 = _np_leaf(states, "exec_slot")[:, row]
    hash1 = _np_leaf(states, "app_hash")[:, row]
    assert (exec1[:3] >= 2).all(), exec1          # new epoch progressed
    assert len(set(hash1[:3].tolist())) == 1       # members agree
    # the stale holdout neither advanced nor adopted the new tenant
    assert int(exec1[3]) == hold_exec_before
    assert int(_np_leaf(states, "version")[3, row]) == 0
    assert int(_np_leaf(states, "tag")[3, row]) != new_tag
    # other rows were untouched by the surgery and still work
    states = _drive(step_fn, states, 5, [31], n_steps=6)
    assert (_np_leaf(states, "exec_slot")[:, 5] > 0).all()


def test_pause_resume_jump_through_shard_map():
    """Residency through the sharded path: pause (kill) a row on EVERY
    replica mid-run, verify it is inert, then resume (re-create + jump
    to the paused frontier) and continue committing from exactly there
    with full agreement."""
    mesh = _mesh_or_skip()
    states = build_replica_states(CFG)
    step_fn = spmd_step(CFG, mesh)
    row = 2

    states = _drive(step_fn, states, row, [41, 42, 43])
    exec0 = _np_leaf(states, "exec_slot")[:, row]
    hash0 = _np_leaf(states, "app_hash")[:, row]
    nexec0 = _np_leaf(states, "n_execd")[:, row]
    bal0 = _np_leaf(states, "bal")[:, row]
    assert (exec0 > 0).all() and len(set(hash0.tolist())) == 1

    # pause: row freed on every replica (the record would hold the arrays)
    states = _apply_per_replica(
        states, lambda rid, s: kill_groups(s, jnp.array([row]))
    )
    frozen = _np_leaf(states, "exec_slot")[:, row].copy()
    states = _drive(step_fn, states, row, [51], n_steps=4)
    assert (_np_leaf(states, "member_mask")[:, row] == 0).all()
    # inert: offered traffic makes NO progress on a killed row
    assert (_np_leaf(states, "exec_slot")[:, row] == frozen).all()

    # resume: re-create with the SAME epoch/tag and jump to the paused
    # frontier (what resume_group's array restore does per node)
    def resume(rid, s):
        s = create_groups(
            s, jnp.array([row]), jnp.array([(1 << R) - 1]),
            jnp.array([int(row % R)]), my_id=rid, version=0, tag=0,
        )
        return jump_rows(
            s, np.array([row]), np.array([int(exec0[rid])]),
            np.array([int(bal0[rid])]), np.array([int(hash0[rid])]),
            np.array([int(nexec0[rid])]), np.array([0]),
        )
    states = _apply_per_replica(states, resume)

    states = _drive(step_fn, states, row, [61, 62], n_steps=10)
    exec1 = _np_leaf(states, "exec_slot")[:, row]
    hash1 = _np_leaf(states, "app_hash")[:, row]
    assert (exec1 >= exec0 + 2).all(), (exec0, exec1)  # resumed AND advanced
    assert len(set(hash1.tolist())) == 1               # agreement preserved
