"""Multi-process-shape loopback tests: 3 PaxosServers on real sockets +
async client — parity with the reference's ``tests/loopback_1_group``
smoke (3 actives on 127.0.0.1, client drives requests) and the failover
scenario (BASELINE config 5)."""

import time

import numpy as np
import pytest

from gigapaxos_tpu.clients import PaxosClientAsync
from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.net.node_config import NodeConfig
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.server import PaxosServer

CFG = EngineConfig(n_groups=6, window=8, req_lanes=4, n_replicas=3)


from gigapaxos_tpu.testing.ports import free_ports  # noqa: E402 (headroom
# for derived ports: client-plane offset / HTTP front ends)


def boot_cluster(fd_timeout_s=2.0):
    ports = free_ports(3)
    nc = NodeConfig({i: ("127.0.0.1", p) for i, p in enumerate(ports)})
    servers = [
        PaxosServer(i, nc, StatefulAdderApp(), CFG,
                    tick_interval=0.01, fd_timeout_s=fd_timeout_s)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    client = PaxosClientAsync([("127.0.0.1", p) for p in ports])
    return servers, client, ports


def wait_until(cond, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.mark.timeout(120)
def test_loopback_1_group_end_to_end():
    servers, client, _ = boot_cluster()
    try:
        assert client.create_paxos_instance("svc", [0, 1, 2], timeout=30)
        total = 0
        for i in range(5):
            resp = client.send_request_sync("svc", str(i + 1), timeout=30)
            total += i + 1
            assert resp == str(total), (resp, total)
        # all replicas converge to the same app state
        assert wait_until(lambda: all(
            s.manager.app.totals.get("svc") == total for s in servers
        ))
        # duplicate request id answered from cache, not re-executed
        rid = client.send_request("svc", "999")
        time.sleep(1.0)
        resp = client.send_request_sync("svc", "999")  # fresh id, executes
        assert wait_until(lambda: all(
            s.manager.app.totals.get("svc") == total + 999 + 999
            for s in servers
        ))
    finally:
        client.close()
        for s in servers:
            s.stop()


@pytest.mark.timeout(180)
def test_hibernate_restore_over_sockets():
    """hibernate/restore as deployed admin ops: checkpoint-and-sleep on
    every node over the wire, wake locally, traffic resumes on the
    restored state (PaxosManager.java:2209-2252 reachable end-to-end)."""
    servers, client, _ = boot_cluster()
    try:
        assert client.create_paxos_instance("hib", [0, 1, 2], timeout=30)
        assert client.send_request_sync("hib", "5", timeout=30) == "5"
        for s in range(3):
            r = client.admin_sync(
                s, {"op": "hibernate", "name": "hib"}, timeout=30
            )
            assert r and r.get("ok"), r
        assert all(srv.manager.names.get("hib") is None for srv in servers)
        for s in range(3):
            r = client.admin_sync(
                s, {"op": "restore", "name": "hib"}, timeout=30
            )
            assert r and r.get("ok"), r
        assert client.send_request_sync("hib", "2", timeout=30) == "7"
        assert wait_until(lambda: all(
            srv.manager.app.totals.get("hib") == 7 for srv in servers
        ))
    finally:
        client.close()
        for s in servers:
            s.stop()


@pytest.mark.timeout(180)
def test_coordinator_failover_over_sockets():
    servers, client, ports = boot_cluster(fd_timeout_s=1.0)
    try:
        assert client.create_paxos_instance("ha", [0, 1, 2], timeout=30)
        assert client.send_request_sync("ha", "7", timeout=30) == "7"
        row = servers[0].manager.names["ha"]
        coord = servers[0].manager.coordinator_of_row(row)
        # kill the coordinator server outright
        servers[coord].stop()
        alive = [s for i, s in enumerate(servers) if i != coord]
        alive_idx = [i for i in range(3) if i != coord]
        # the failure detector must elect a new coordinator and clients
        # (retransmitting the SAME request id, rotating servers) keep
        # getting answers; under full-suite load FD convergence can take
        # several seconds, so allow a long window — retransmission is
        # exactly-once by request id, so the total stays correct
        resp = client.send_request_sync(
            "ha", "3", timeout=90, server=alive_idx[0]
        )
        assert resp == "10", resp
        new_coord = alive[0].manager.coordinator_of_row(row)
        assert new_coord != coord
        assert wait_until(lambda: all(
            s.manager.app.totals.get("ha") == 10 for s in alive
        ))
    finally:
        client.close()
        for i, s in enumerate(servers):
            try:
                s.stop()
            except Exception:
                pass


def test_delay_emulator_adds_link_latency():
    """JSONDelayEmulator analog: per-link artificial delay on the socket
    transport (WAN emulation in one process)."""
    import time as _time

    servers, client, ports = boot_cluster()
    try:
        client.create_paxos_instance("lag", [0, 1, 2])
        r0 = client.send_request_sync("lag", "fast", timeout=15)
        assert r0 is not None
        # 150ms on every inter-server link; client links unaffected
        server_ports = {s.transport.listen_port for s in servers}
        for s in servers:
            s.transport.delay_fn = (
                lambda addr, sp=server_ports: 0.15 if addr[1] in sp else 0.0
            )
        t0 = _time.time()
        r1 = client.send_request_sync("lag", "slow", timeout=30)
        dt = _time.time() - t0
        assert r1 is not None
        assert dt > 0.15, f"emulated link delay not observed ({dt * 1000:.0f}ms)"
    finally:
        for s in servers:
            s.stop()
        client.close()


def test_overload_backpressure():
    """MAX_OUTSTANDING_REQUESTS shedding (PaxosConfig.java:537): past the
    in-flight cap the entry answers 'overload' instead of queueing
    unboundedly; answered retransmits still hit the response cache."""
    from gigapaxos_tpu.utils.config import Config

    Config.set("MAX_OUTSTANDING_REQUESTS", 4)
    try:
        servers, client, ports = boot_cluster()
        try:
            client.create_paxos_instance("bp", [0, 1, 2])
            r = client.send_request_sync("bp", "warm", timeout=15)
            assert r is not None
            # flood one entry far past the cap without stepping time for
            # the cluster to drain: some requests must be shed
            mgr = servers[0].manager
            assert not mgr.overloaded()
            # pause the drain so the flood observation is deterministic
            # (no-op the tick body; the loop keeps its short cadence so
            # restoring resumes immediately)
            saved_ticks = [s_.tick_once for s_ in servers]
            for s_ in servers:
                s_.tick_once = lambda: None
            time.sleep(0.15)  # let in-flight ticks finish
            for i in range(20):
                mgr.propose("bp", f"flood{i}")
            assert mgr.overloaded(), "cap never reached under flood"
            # shed path answers 'overload' while saturated
            raw_reply = []
            servers[0]._on_json(
                "client_request", -1,
                {"request_id": 999999999, "name": "bp", "value": "x"},
                lambda frame: raw_reply.append(frame),
            )
            assert raw_reply, "no shed reply"
            from gigapaxos_tpu.net.codec import decode_json

            _k, _s2, body = decode_json(raw_reply[0])
            assert body.get("error") == "overload", body
            # resume draining; the queued flood completes
            for s_, t_ in zip(servers, saved_ticks):
                s_.tick_once = t_
            deadline = time.time() + 30
            while time.time() < deadline and mgr.overloaded():
                time.sleep(0.05)
            assert not mgr.overloaded(), "cluster never drained"
        finally:
            for s in servers:
                s.stop()
            client.close()
    finally:
        Config.clear()
