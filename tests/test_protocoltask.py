"""Protocol-task runtime tests (ProtocolExecutor.java / ThresholdProtocolTask
analog): keyed routing, idempotent spawn, threshold acks with laggard-only
retransmit, restarts, expiry."""

from gigapaxos_tpu.protocoltask import (
    ProtocolExecutor,
    ProtocolTask,
    ThresholdProtocolTask,
)


class PingTask(ProtocolTask):
    restart_period_s = 1.0
    max_lifetime_s = 10.0

    def __init__(self, key, dsts):
        super().__init__(key)
        self.dsts = dsts
        self.expired = False

    def start(self):
        return [(d, "ping", {"key": self.key}) for d in self.dsts]

    def handle_event(self, kind, body):
        if kind == "pong":
            self.done = True
        return ()

    def on_expire(self):
        self.expired = True


class MajorityAck(ThresholdProtocolTask):
    restart_period_s = 1.0

    def __init__(self, key, nodes):
        super().__init__(key, nodes)
        self.fired = []

    def send_to(self, node):
        return (node, "req", {"key": self.key})

    def is_ack(self, kind, body):
        return body.get("from") if kind == "ack" else None

    def on_threshold(self):
        self.fired.append(tuple(sorted(self.acked)))
        return [("done-dst", "complete", {"key": self.key})]


def test_spawn_routes_and_completes():
    ex = ProtocolExecutor()
    t = PingTask("k1", [1, 2])
    assert ex.spawn(t, now=0.0)
    assert [m[1] for m in ex.outbox] == ["ping", "ping"]
    assert ex.is_running("k1")
    # unknown key: not consumed
    assert not ex.handle_event("zzz", "pong", {})
    assert ex.handle_event("k1", "pong", {})
    assert not ex.is_running("k1")  # done -> reaped


def test_spawn_if_not_running_idempotent():
    ex = ProtocolExecutor()
    assert ex.spawn_if_not_running("k", lambda: PingTask("k", [1]), now=0.0)
    assert not ex.spawn_if_not_running("k", lambda: PingTask("k", [1]), now=0.0)
    assert len(ex) == 1


def test_threshold_laggard_retransmit():
    ex = ProtocolExecutor()
    t = MajorityAck("m", [10, 11, 12])
    ex.spawn(t, now=0.0)
    assert len(ex.outbox) == 3  # initial sends to all
    ex.outbox.clear()
    ex.handle_event("m", "ack", {"from": 10})
    # restart retransmits ONLY to laggards 11, 12
    ex.tick(now=1.5)
    assert sorted(m[0] for m in ex.outbox) == [11, 12]
    ex.outbox.clear()
    # non-member ack ignored
    ex.handle_event("m", "ack", {"from": 99})
    assert not t.done
    ex.handle_event("m", "ack", {"from": 12})
    # majority (2/3) -> on_threshold fired once, task reaped
    assert t.fired == [(10, 12)]
    assert ex.outbox == [("done-dst", "complete", {"key": "m"})]
    assert not ex.is_running("m")


def test_restart_period_and_expiry():
    ex = ProtocolExecutor()
    t = PingTask("p", [7])
    ex.spawn(t, now=0.0)
    ex.outbox.clear()
    ex.tick(now=0.5)          # before period: nothing
    assert ex.outbox == []
    ex.tick(now=1.1)          # past period: retransmit
    assert len(ex.outbox) == 1
    ex.tick(now=11.0)         # past lifetime: expired + dropped
    assert t.expired and not ex.is_running("p")


def test_send_fn_direct_delivery():
    sent = []
    ex = ProtocolExecutor(send=sent.append)
    ex.spawn(PingTask("k", [3]), now=0.0)
    assert sent == [(3, "ping", {"key": "k"})]
    assert ex.outbox == []
