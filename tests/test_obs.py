"""Observability plane tests: tracer ring semantics, DEBUG gating, lazy
logging, GP_LOG grammar, the metrics registry, the ``stats`` admin op
over a live loopback cluster, the unknown-admin-op reply, the chaos-diag
trace ride-along, and the obs-hygiene static gate."""

import io
import logging
import subprocess
import sys
import time
from pathlib import Path

from gigapaxos_tpu.obs import gplog
from gigapaxos_tpu.obs.metrics import Histogram, MetricsRegistry
from gigapaxos_tpu.obs.reqtrace import RequestTracer

REPO = Path(__file__).resolve().parent.parent


# ---- tracer ----------------------------------------------------------
def test_tracer_ring_bound_and_fifo_eviction():
    t = RequestTracer(0, capacity=4, enabled=True)
    for rid in range(10):
        t.note(rid, "recv", name="svc", node=0)
        t.note(rid, "execute", slot=rid)
    assert len(t) == 4
    # FIFO: only the newest 4 keys survive
    assert all(rid in t for rid in range(6, 10))
    assert all(rid not in t for rid in range(6))
    # a new event on a surviving key appends, not re-inserts
    t.note(7, "respond-flush")
    assert [e[1] for e in t.events(7)] == ["recv", "execute", "respond-flush"]


def test_tracer_disabled_records_nothing():
    t = RequestTracer(1, capacity=16, enabled=False)
    t.note(42, "recv", name="svc", node=1)
    t.note(42, "execute", slot=3)
    assert len(t) == 0
    assert t.events(42) == []
    assert "no trace" in t.dump(42)
    assert "no traces" in t.dump_name("svc")


def test_tracer_dump_timeline_and_name_index():
    t = RequestTracer(2, enabled=True)
    t.note(7, "recv", name="a", node=2)
    t.note(7, "propose", name="a", vid=99, row=3)
    t.note(8, "recv", name="a", node=2)
    t.note(9, "recv", name="b", node=2)
    d = t.dump(7)
    assert "request 7 @ node 2" in d
    assert "recv" in d and "propose" in d and "vid=99" in d
    assert "ms" in d  # relative-timestamp lines
    assert t.keys_for_name("a") == [7, 8]
    nd = t.dump_name("a")
    assert "request 7" in nd and "request 8" in nd and "request 9" not in nd


def test_tracer_default_gate_follows_gp_log(monkeypatch):
    gplog.reset_for_tests()
    try:
        monkeypatch.delenv("GP_TRACE", raising=False)
        monkeypatch.setenv("GP_LOG", "")
        assert RequestTracer(0).enabled is False
        monkeypatch.setenv("GP_LOG", "trace:DEBUG")
        gplog.configure(stream=io.StringIO(), force=True)
        assert RequestTracer(0).enabled is True
        monkeypatch.setenv("GP_LOG", "")
        monkeypatch.setenv("GP_TRACE", "1")
        gplog.reset_for_tests()
        assert RequestTracer(0).enabled is True
    finally:
        gplog.reset_for_tests()


# ---- logging ---------------------------------------------------------
class _Sentinel:
    """__str__ counter: proves %-args only format past the level check."""

    def __init__(self):
        self.n = 0

    def __str__(self):
        self.n += 1
        return "S"


def test_gplog_lazy_formatting_below_level():
    gplog.reset_for_tests()
    try:
        sink = io.StringIO()
        gplog.configure(stream=sink, force=True)  # default WARNING
        log = gplog.node_logger("lazytest", 7)
        s = _Sentinel()
        log.debug("value=%s", s)
        log.info("value=%s", s)
        assert s.n == 0, "args formatted below the enabled level"
        log.warning("value=%s", s)
        assert s.n == 1
        out = sink.getvalue()
        assert "[node 7]" in out and "value=S" in out
        assert "gp.lazytest" in out
    finally:
        gplog.reset_for_tests()


def test_gplog_env_grammar():
    gplog.reset_for_tests()
    try:
        gplog.configure(stream=io.StringIO(), force=True)
        gplog.apply_env_levels("INFO,server:DEBUG, rc:ERROR")
        assert logging.getLogger("gp").level == logging.INFO
        assert logging.getLogger("gp.server").level == logging.DEBUG
        assert logging.getLogger("gp.rc").level == logging.ERROR
        # unparseable fragments are skipped, never raise
        gplog.apply_env_levels("server:NOTALEVEL,garbage")
        assert logging.getLogger("gp.server").level == logging.DEBUG
    finally:
        gplog.reset_for_tests()


def test_warn_once_dedup():
    gplog.reset_for_tests()
    try:
        sink = io.StringIO()
        gplog.configure(stream=sink, force=True)
        log = gplog.node_logger("oncetest", 3)
        for _ in range(5):
            gplog.warn_once(log, "kindX", "dropping frame of kind %s", "X")
        gplog.warn_once(log, "kindY", "dropping frame of kind %s", "Y")
        out = sink.getvalue()
        assert out.count("kind X") == 1
        assert out.count("kind Y") == 1
    finally:
        gplog.reset_for_tests()


# ---- metrics ---------------------------------------------------------
def test_histogram_buckets_and_stats():
    h = Histogram(bounds=(1.0, 10.0, 100.0))
    for x in (0.5, 5, 5, 50, 500):
        h.observe(x)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["min"] == 0.5 and snap["max"] == 500
    assert snap["buckets"] == [
        [1.0, 1], [10.0, 2], [100.0, 1], ["+inf", 1]
    ]


def test_histogram_always_ships_inf_bucket():
    # no overflow observed: the terminal bucket must still render (with
    # the Prometheus "+Inf" spelling) or histogram_quantile returns NaN
    m = MetricsRegistry(node=1)
    m.observe("lat_s", 0.5, bounds=(1.0, 10.0))
    snap = m.snapshot()["hists"]["lat_s"]
    assert snap["buckets"] == [[1.0, 1], [10.0, 0], ["+inf", 0]]
    text = m.render()
    assert 'le="+Inf"} 1' in text


def test_render_counters_full_precision():
    # %g's 6 significant digits would quantize large counters and break
    # rate() over successive scrapes
    m = MetricsRegistry(node=1)
    m.count("decisions_executed", 10_000_000_019)
    assert 'gp_decisions_executed_total{node="1"} 10000000019' in m.render()


def test_tracer_per_key_event_cap_keeps_anchor():
    t = RequestTracer(0, enabled=True)
    t.note("epoch:n0", "rc-propose:create_intent", name="n0")
    for i in range(2 * RequestTracer.EVENTS_PER_KEY):
        t.note("epoch:n0", "start-epoch-round", attempt=i)
    evs = t.events("epoch:n0")
    assert len(evs) == RequestTracer.EVENTS_PER_KEY
    assert evs[0][1] == "rc-propose:create_intent"  # t0 anchor survives
    assert evs[-1][2]["attempt"] == 2 * RequestTracer.EVENTS_PER_KEY - 1


def test_metrics_registry_roundtrip():
    m = MetricsRegistry(node=5)
    m.count("decisions_executed", 3)
    m.count("decisions_executed", 4)
    m.gauge("frontier_stall_groups", 2)
    m.observe("engine_step_s", 0.002)
    assert m.get("decisions_executed") == 7
    assert m.get("frontier_stall_groups") == 2
    snap = m.snapshot()
    assert snap["node"] == 5
    assert snap["counters"]["decisions_executed"] == 7
    assert snap["hists"]["engine_step_s"]["count"] == 1
    text = m.render()
    assert 'gp_decisions_executed_total{node="5"} 7' in text
    assert "gp_engine_step_s_bucket" in text
    line = m.summary_line()
    assert "decisions_executed:7" in line


# ---- the stats admin op over a live loopback cluster -----------------
def test_stats_admin_roundtrip_and_unknown_op():
    from gigapaxos_tpu.clients import PaxosClientAsync
    from gigapaxos_tpu.models import StatefulAdderApp
    from gigapaxos_tpu.net.node_config import NodeConfig
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.server import PaxosServer
    from gigapaxos_tpu.testing.ports import free_ports

    cfg = EngineConfig(n_groups=6, window=8, req_lanes=4, n_replicas=2)
    ports = free_ports(2)
    nc = NodeConfig({i: ("127.0.0.1", p) for i, p in enumerate(ports)})
    servers = [
        PaxosServer(i, nc, StatefulAdderApp(), cfg, tick_interval=0.01)
        for i in range(2)
    ]
    for s in servers:
        s.start()
    client = PaxosClientAsync([("127.0.0.1", p) for p in ports])
    try:
        # unknown op answers instead of hanging the waiter to timeout
        r = client.admin_sync(0, {"op": "frobnicate", "name": "x"},
                              timeout=10)
        assert r is not None, "unknown admin op never answered"
        assert r["ok"] is False and r["error"] == "unknown_op"

        assert client.create_paxos_instance("obs", [0, 1], timeout=30)
        assert client.send_request_sync("obs", "5", timeout=30) == "5"
        # the response fires at the ENTRY replica (possibly node 1), and
        # node 0's engine can run a tick behind it — poll until node 0's
        # own counter reflects the committed decision
        deadline = time.time() + 30
        while True:
            r = client.admin_sync(0, {"op": "stats"}, timeout=10)
            assert r is not None and r["ok"] is True
            eng = r["engine"]
            if eng["counters"].get("decisions_executed", 0) >= 1:
                break
            assert time.time() < deadline, eng["counters"]
            time.sleep(0.2)
        assert "engine_step_s" in eng["hists"]
        # the mesh actually backing the state arrays rides along — an
        # unsharded deployment must be visible at runtime
        assert eng["mesh"]["n_devices"] >= 1
        assert eng["mesh"]["platform"] == "cpu"
        assert isinstance(eng["mesh"]["shape"], dict)
        # blob publishing happened, so the wire-cost counters are live
        assert eng["counters"].get("blob_bytes_sent", 0) > 0
        assert "profiler" in r and "counts" in r["profiler"]
        assert r["profiler_line"].startswith("[")
    finally:
        client.close()
        for s in servers:
            s.stop()


# ---- chaos-diag trace ride-along -------------------------------------
def test_name_diag_carries_merged_cross_member_trace():
    """The soak failure payload: with tracing on (as run_soak enables
    it), _name_diag carries the offending name's MERGED cross-member
    timeline — one causal story per request with every member's
    propose/decide/execute hops interleaved and per-phase latency
    attribution — so a SoakDivergence message shows each request's
    whole cluster journey, not N per-member fragments."""
    from gigapaxos_tpu.models.apps import HashChainApp
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.testing.chaos import SoakDivergence, _name_diag
    from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster

    ar_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        for m in c.ars.managers:
            m.tracer.enabled = True
        for rc in c.reconfigurators:
            rc.tracer.enabled = True
        c.client_request(
            "create_service", {"name": "tn", "actives": [0, 1, 2]}
        )
        for _ in range(40):
            c.step()
        rid = (1 << 55) + 12345
        c.ars.managers[0].propose("tn", "v0", request_id=rid)
        deadline = time.time() + 60
        while time.time() < deadline:
            c.step()
            if all(m.app.state.get("tn") for m in c.ars.managers):
                break
        assert c.ars.managers[0].app.state.get("tn"), "request never executed"
        diag = _name_diag(c, "tn", [0, 1, 2])
        # ONE merged timeline carries the request across all members
        merged = diag.get("merged_trace", "")
        assert str(rid) in merged, merged
        assert "propose" in merged and "execute" in merged
        for a in (0, 1, 2):  # every member's hops interleave in it
            assert f"@ node {a}" in merged, (a, merged)
        assert "phases:" in merged  # per-hop latency attribution
        # the RC epoch timeline rides along too
        assert "rc_epoch_trace" in diag
        assert any("rc-applied" in v or "rc-propose" in v
                   for v in diag["rc_epoch_trace"].values())
        # and the failure message a soak would raise CONTAINS the timeline
        msg = str(SoakDivergence("synthetic", {"members": diag}))
        assert str(rid) in msg and "+" in msg
        # engine metrics moved during the run
        assert c.ars.managers[0].metrics.get("decisions_executed") >= 1
    finally:
        c.close()


# ---- cross-node trace plumbing (sampling, export, merge) --------------
def test_trace_sampling_gate(monkeypatch):
    from gigapaxos_tpu.obs import reqtrace

    monkeypatch.delenv("GP_TRACE_SAMPLE", raising=False)
    assert reqtrace.trace_sample_rate() == 0.0
    assert reqtrace.maybe_mint_trace(3) is None
    monkeypatch.setenv("GP_TRACE_SAMPLE", "1")
    assert reqtrace.trace_sample_rate() == 1.0
    tc = reqtrace.maybe_mint_trace(3)
    assert tc is not None and tc[1] == 3 and tc[2] == 0 and tc[0] > 0
    monkeypatch.setenv("GP_TRACE_SAMPLE", "garbage")
    assert reqtrace.trace_sample_rate() == 0.0
    monkeypatch.setenv("GP_TRACE_SAMPLE", "7")  # clamped
    assert reqtrace.trace_sample_rate() == 1.0


def test_tracer_force_records_when_disabled():
    """The cross-node sampling contract: a request carrying a trace
    context records on EVERY node regardless of the local gate."""
    t = RequestTracer(4, enabled=False)
    t.note(99, "decide", name="svc", force=True, tid=123, slot=5)
    t.note(99, "ignored")  # unforced + disabled: dropped
    evs = t.events(99)
    assert [e[1] for e in evs] == ["decide"]
    assert evs[0][2]["tid"] == 123


def test_tracer_export_shapes():
    t = RequestTracer(1, enabled=True)
    t.note(5, "recv", name="a", node=1)
    t.note(5, "propose", name="a", vid=9)
    t.note(6, "recv", name="b", node=1)
    out = t.export(keys=[5])
    assert set(out) == {"5"}
    assert [e[1] for e in out["5"]] == ["recv", "propose"]
    assert out["5"][0][0] <= out["5"][1][0]  # wall-clock ordered
    by_name = t.export(name="a")
    assert set(by_name) == {"5"}
    everything = t.export()
    assert set(everything) == {"5", "6"}
    assert t.export(limit=1) == {"6": everything["6"]}


def test_tracemerge_attribution_and_skew_clamp():
    from gigapaxos_tpu.obs import tracemerge

    t0 = 1000.0
    dumps = {
        1: {"42": [
            [t0, "recv", {"tid": 7, "hop": 0}],
            [t0 + 0.001, "propose", {"tid": 7, "hop": 0}],
            [t0 + 0.002, "forward-out", {"tid": 7, "hop": 0, "to": 0}],
        ]},
        # node 0's clock runs exactly the hop behind: the forward-in
        # lands at the SAME wall stamp as the forward-out — the hop
        # counter breaks the tie causally and the latency clamps to 0
        0: {"42": [
            [t0 + 0.002, "forward-in", {"tid": 7, "hop": 1}],
            [t0 + 0.004, "decide", {"tid": 7, "slot": 0, "ballot": 3}],
        ]},
    }
    traces = tracemerge.merge_node_dumps(dumps)
    assert len(traces) == 1
    tr = traces[0]
    assert tr["trace_id"] == 7
    assert [e["event"] for e in tr["events"]] == [
        "recv", "propose", "forward-out", "forward-in", "decide"
    ]
    assert all(h["dt_s"] >= 0.0 for h in tr["hops"])
    phases = [h["phase"] for h in tr["hops"]]
    assert "ingress" in phases and "forward-wire" in phases
    wire = [h for h in tr["hops"] if h["phase"] == "forward-wire"][0]
    assert wire["dt_s"] == 0.0  # the skewed hop clamps, never negative
    assert wire["from_node"] == 1 and wire["to_node"] == 0
    text = tracemerge.render_trace(tr)
    assert "tid=0x7" in text and "@ node 1" in text
    # untraced keys correlate by request id and still merge
    plain = tracemerge.merge_node_dumps({
        0: {"9": [[t0, "recv", {}]]},
        1: {"9": [[t0 + 0.01, "execute", {"slot": 1}]]},
    })
    assert len(plain) == 1 and plain[0]["trace_id"] is None
    assert len(plain[0]["events"]) == 2


def test_process_gauges_collect():
    from gigapaxos_tpu.obs.metrics import collect_process_gauges

    m = MetricsRegistry(node=9)
    collect_process_gauges(m)
    snap = m.snapshot()["gauges"]
    assert snap.get("process_rss_bytes", 0) > 0
    assert snap.get("process_open_fds", 0) > 0
    assert snap.get("process_threads", 0) >= 1
    assert "process_gc_collections" in snap
    assert "gp_process_rss_bytes" in m.render()


def test_flight_recorder_rings_and_dump(tmp_path):
    from gigapaxos_tpu.obs.flight import FlightRecorder
    from gigapaxos_tpu.utils.config import Config

    Config.set("FLIGHT_DIR", str(tmp_path))
    fl = FlightRecorder(2, steps=4, decided=6)
    fl.record_step(tick=1, admitted=0, decided=0, preempts=0,
                   coordinator_flips=0, ballot_rises=0,
                   frontier_stalls=0, inflight=0)  # idle: not recorded
    for i in range(10):
        fl.record_step(tick=i, admitted=1, decided=1, preempts=0,
                       coordinator_flips=0, ballot_rises=0,
                       frontier_stalls=0, inflight=2)
        fl.record_decided(3, i, 17, 100 + i)
    snap = fl.snapshot()
    assert len(snap["steps"]) == 4        # ring bound
    assert len(snap["decided"]) == 6      # last-K only
    assert snap["decided"][-1] == [3, 9, 17, 109]
    assert fl.decided_for_group(3) and not fl.decided_for_group(4)
    path = fl.dump(reason="unit test?/x")  # reason is sanitized
    assert path and path.endswith(".json")
    import json as _json

    doc = _json.loads(open(path).read())
    assert doc["node"] == 2 and doc["reason"] == "unit test?/x"
    assert len(doc["decided"]) == 6
    # once-gating: second dump for the same reason suppressed
    assert fl.dump(reason="boom", once=True)
    assert fl.dump(reason="boom", once=True) is None


# ---- hygiene gate ----------------------------------------------------
def test_obs_hygiene_gate():
    """No bare print()/std-stream writes outside obs/, and the
    METRICS.md inventory matches the registered metric names both ways —
    runs the same AST pass future CI uses, as a tier-1 test."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_obs_hygiene.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "metric inventory" in proc.stdout
