"""Durability tests: journal framing/rotation/GC, torn-tail handling,
checkpoint atomicity with prev fallback, and recovery rollforward — the
analog of the reference's recovery testing (``testPaxos(recovery=true)``,
``TESTPaxosMain.java:154``, and SQLPaxosLogger's journal GC)."""

import json
import os

import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL
from gigapaxos_tpu.ops.engine import EngineConfig, init_state
from gigapaxos_tpu.storage import (
    BlockType,
    Journal,
    PaxosLogger,
    load_checkpoint,
    save_checkpoint,
)


def test_journal_roundtrip(tmp_path):
    j = Journal(str(tmp_path))
    j.append_columns(BlockType.ACCEPTS, [
        np.array([0, 1, 2]), np.array([5, 6, 7]),
        np.array([10, 10, 10]), np.array([100, 101, 102]),
    ])
    j.append(BlockType.PAYLOADS, b'{"1":"hello"}')
    blocks = list(j.scan())
    assert [b[0] for b in blocks] == [BlockType.ACCEPTS, BlockType.PAYLOADS]
    m = Journal.columns(blocks[0][1], blocks[0][2], 4)
    assert m[2].tolist() == [2, 7, 10, 102]
    assert blocks[1][1] == b'{"1":"hello"}'
    j.close()


def test_journal_torn_tail(tmp_path):
    j = Journal(str(tmp_path))
    j.append(BlockType.PAYLOADS, b"good-block")
    j.append(BlockType.PAYLOADS, b"second")
    j.close()
    # corrupt the tail: truncate into the middle of the second block
    path = os.path.join(str(tmp_path), "journal_00000000.bin")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    j2 = Journal(str(tmp_path))
    blocks = list(j2.scan())
    assert len(blocks) == 1 and blocks[0][1] == b"good-block"
    # appends after a torn tail still work (single-writer restarts append)
    j2.append(BlockType.PAYLOADS, b"after-crash")
    assert [b[1] for b in j2.scan()][-1] == b"after-crash"
    j2.close()


def test_journal_rotation_and_gc(tmp_path):
    j = Journal(str(tmp_path), max_file_size=64)  # rotate every block
    for i in range(5):
        j.append(BlockType.PAYLOADS, b"x" * 80, n_rows=i)
    assert len(j.file_indices()) >= 4
    blocks = list(j.scan())
    assert [b[2] for b in blocks] == [0, 1, 2, 3, 4]
    # scan from a mid position picks up only later blocks
    mid = blocks[2][3]
    later = list(j.scan(*mid))
    assert [b[2] for b in later] == [3, 4]
    removed = j.gc_below(mid[0])
    assert removed >= 2
    assert [b[2] for b in j.scan(*mid)] == [3, 4]
    j.close()


def test_checkpoint_prev_fallback(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, {"a": np.arange(3)}, {"gen": 1})
    save_checkpoint(d, {"a": np.arange(4)}, {"gen": 2})
    arrays, meta = load_checkpoint(d)
    assert meta["gen"] == 2 and len(arrays["a"]) == 4
    # corrupt the current snapshot: loader must fall back to prev
    with open(os.path.join(d, "checkpoint.npz"), "wb") as f:
        f.write(b"garbage")
    arrays, meta = load_checkpoint(d)
    assert meta["gen"] == 1 and len(arrays["a"]) == 3


def test_checkpoint_crash_between_demotes(tmp_path):
    """A crash after demoting the snapshot but before demoting the sidecar
    must not pair a snapshot with a different generation's sidecar — the
    loader matches embedded generation ids across all combinations."""
    d = str(tmp_path)
    save_checkpoint(d, {"a": np.arange(3)}, {"tag": "g1"})
    save_checkpoint(d, {"a": np.arange(4)}, {"tag": "g2"})
    # simulate: crash mid-demote (snapshot demoted, sidecar not yet)
    os.replace(
        os.path.join(d, "checkpoint.npz"),
        os.path.join(d, "prev_checkpoint.npz"),
    )
    arrays, meta = load_checkpoint(d)
    # prev_checkpoint.npz (gen 2) pairs with checkpoint.meta.json (gen 2)
    assert meta["tag"] == "g2" and len(arrays["a"]) == 4

    # and a sidecar must never ride with a mismatched snapshot: drop the
    # gen-2 sidecar, leaving only the gen-2 snapshot + gen-1 sidecar —
    # no matched pair exists, so the loader must refuse (not silently
    # combine a stale journal_pos with newer arrays)
    os.remove(os.path.join(d, "checkpoint.meta.json"))
    assert load_checkpoint(d) is None


def test_promises_block_rollforward(tmp_path):
    """A bare promise (ballot rose with no accept) must survive a crash:
    the PROMISES block folds into bal with a running max (ADVICE r1 high)."""
    cfg = EngineConfig(n_groups=4, window=4, req_lanes=2, n_replicas=3)
    lg = PaxosLogger(0, str(tmp_path))
    lg.log_create(
        np.array([0, 1]), np.array([0b111, 0b111]),
        np.array([0, 0]), np.array([0, 1]),
    )
    lg.log_promises(np.array([0, 1]), np.array([96, 65]))
    # duplicate group in one block: running max, not last-write-wins
    lg.log_promises(np.array([0, 0]), np.array([128, 97]))
    lg.close()
    lg2 = PaxosLogger(0, str(tmp_path))
    rec = lg2.recover(cfg.window, seed_arrays=_state_arrays(cfg))
    assert rec.arrays["bal"][0] == 128  # not 97
    assert rec.arrays["bal"][1] == 65
    # no accept was logged: windows stay empty, only the promise persists
    assert (rec.arrays["acc_slot"][0] == NULL).all()
    lg2.close()


def test_accepts_duplicate_group_ballot_max(tmp_path):
    """Two lanes of one group in one ACCEPTS block with different ballots:
    the group ballot takes the max (np.maximum.at), not the last row."""
    cfg = EngineConfig(n_groups=2, window=4, req_lanes=2, n_replicas=3)
    lg = PaxosLogger(0, str(tmp_path))
    lg.log_accepts(
        np.array([0, 0]), np.array([0, 1]),
        np.array([99, 33]), np.array([7, 8]),
    )
    rec = lg.recover(cfg.window, seed_arrays=_state_arrays(cfg))
    assert rec.arrays["bal"][0] == 99
    lg.close()


def _state_arrays(cfg):
    return {k: np.asarray(v).copy() for k, v in init_state(cfg)._asdict().items()}


def test_logger_recovery_rollforward(tmp_path):
    """CREATE + ACCEPTS + checkpoint + DECISIONS; recover must equal the
    checkpoint plus exactly the post-checkpoint blocks."""
    cfg = EngineConfig(n_groups=4, window=4, req_lanes=2, n_replicas=3)
    lg = PaxosLogger(0, str(tmp_path))
    lg.log_create(
        np.array([0, 1]), np.array([0b111, 0b111]),
        np.array([0, 0]), np.array([0, 1]),
    )
    lg.log_accepts(
        np.array([0, 0, 1]), np.array([0, 1, 0]),
        np.array([32, 32, 33]), np.array([100, 101, 200]),
    )
    lg.log_payloads({100: "r100", 101: "r101"})

    # crash BEFORE any checkpoint: rollforward over seed arrays
    rec = lg.recover(cfg.window, seed_arrays=_state_arrays(cfg))
    a = rec.arrays
    assert a["member_mask"][0] == 0b111 and a["majority"][1] == 2
    assert a["acc_vid"][0, 0] == 100 and a["acc_vid"][0, 1] == 101
    assert a["acc_slot"][1, 0] == 0 and a["acc_bal"][1, 0] == 33
    assert a["bal"][0] == 32  # promise restored to logged accept ballot
    assert rec.payloads == {100: "r100", 101: "r101"}

    # checkpoint the recovered arrays, then more traffic after it
    lg.checkpoint(a, {"svc0": "appstate"}, {"names": {"svc0": 0}})
    lg.log_decisions(np.array([0, 0]), np.array([0, 1]), np.array([100, 101]))
    lg.log_kill(np.array([1]))
    lg.close()

    # fresh process: recover from disk
    lg2 = PaxosLogger(0, str(tmp_path))
    rec2 = lg2.recover(cfg.window)
    b = rec2.arrays
    assert rec2.meta["app_states"] == {"svc0": "appstate"}
    assert rec2.meta["names"] == {"svc0": 0}
    assert b["dec_vid"][0, 0] == 100 and b["dec_slot"][0, 1] == 1
    assert b["member_mask"][1] == 0  # killed after checkpoint
    assert b["acc_vid"][0, 0] == 100  # pre-checkpoint accept survived via snapshot
    lg2.close()


def test_checkpoint_gcs_journal(tmp_path):
    cfg = EngineConfig(n_groups=2, window=4, req_lanes=2, n_replicas=3)
    lg = PaxosLogger(0, str(tmp_path), max_file_size=64)
    for i in range(6):
        lg.log_accepts(
            np.array([0]), np.array([i]), np.array([1]), np.array([i + 10])
        )
    n_before = len(lg.journal.file_indices())
    rec = lg.recover(cfg.window, seed_arrays=_state_arrays(cfg))
    lg.checkpoint(rec.arrays, {}, {})
    assert len(lg.journal.file_indices()) < n_before
    # recovery after GC must still see full state (via the snapshot)
    rec2 = lg.recover(cfg.window)
    assert rec2.arrays["acc_vid"][0, 5 % 4] == 15
    lg.close()


def test_native_group_commit_parity(tmp_path):
    """The native batched append (gp_journal.cc writev group commit,
    BatchedLogger analog) must produce byte-identical journals to the
    pure-Python path, readable by the same scanner."""
    import os

    import numpy as np

    import gigapaxos_tpu.native as nat
    from gigapaxos_tpu.storage.journal import BlockType, Journal

    blocks = [
        (BlockType.ACCEPTS,
         np.arange(12, dtype=np.int32).reshape(3, 4).tobytes(), 3),
        (BlockType.PAYLOADS, b'{"1":"hello"}', 0),
        (BlockType.NAMES, b'[{"row":2,"name":"x"}]', 0),
    ]
    datas = {}
    for mode in ("native", "python"):
        nat._lib = None
        nat._tried = False
        if mode == "python":
            os.environ["GP_NO_NATIVE"] = "1"
        else:
            os.environ.pop("GP_NO_NATIVE", None)
        try:
            d = str(tmp_path / mode)
            j = Journal(d)
            if mode == "native" and j._native is None:
                import pytest

                pytest.skip("no C++ compiler available")
            pos = j.append_many(list(blocks))
            j.append(BlockType.KILL,
                     np.array([[7]], dtype=np.int32).tobytes(), 1)
            j.close()
            j2 = Journal(d)
            scanned = [(b[0], b[1], b[2]) for b in j2.scan()]
            j2.close()
            with open(f"{d}/journal_00000000.bin", "rb") as f:
                datas[mode] = (pos, scanned, f.read())
        finally:
            os.environ.pop("GP_NO_NATIVE", None)
    nat._lib = None
    nat._tried = False
    assert datas["native"][0] == datas["python"][0]  # positions
    assert datas["native"][1] == datas["python"][1]  # scanned blocks
    assert datas["native"][2] == datas["python"][2]  # raw bytes


def test_async_checkpoint_concurrent_with_appends(tmp_path):
    """The background checkpoint writer races a hot append thread: every
    snapshot's journal_pos must stay consistent (a torn (file, offset)
    pair would skip post-checkpoint blocks on recovery — review find),
    and recovery after the storm must see the last snapshot plus exactly
    the blocks after it."""
    import threading

    cfg = EngineConfig(n_groups=4, window=4, req_lanes=2, n_replicas=3)
    # small files force rotations DURING the storm (the torn-pair window)
    lg = PaxosLogger(0, str(tmp_path), max_file_size=64 * 1024)
    lg.log_create(
        np.array([0]), np.array([0b111]), np.array([0]), np.array([0])
    )
    state = init_state(cfg)
    arrays = {k: np.asarray(v) for k, v in state._asdict().items()}

    stop = threading.Event()
    n_appended = [0]

    def hammer():
        i = 0
        while not stop.is_set():
            lg.log_decisions(
                np.array([0]), np.array([i]), np.array([1000 + i])
            )
            lg.log_payloads({1000 + i: "x" * 256})
            n_appended[0] = i
            i += 1

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for k in range(30):
            lg.checkpoint_async(
                dict(arrays), {"svc": f"s{k}"}, {"names": {"svc": 0}}
            )
        lg.drain_checkpoints()
    finally:
        stop.set()
        t.join()
    lg.close()

    lg2 = PaxosLogger(0, str(tmp_path))
    rec = lg2.recover(cfg.window)
    # the newest landed snapshot is visible, and rollforward reached the
    # hammer thread's tail (no post-checkpoint block skipped)
    assert rec.meta["app_states"]["svc"].startswith("s")
    assert rec.arrays is not None
    top = max(
        (s for g in rec.decisions.values() for s in g), default=-1
    ) if rec.decisions else max(rec.payloads) - 1000
    assert top >= n_appended[0] - 1, (top, n_appended[0])
    lg2.close()
