"""Faults through the SPMD path (VERDICT r4 missing #2).

The reference's whole test strategy runs consensus *under crashes*:
``TESTPaxosConfig.crash/isCrashed`` silently drops a crashed node's
traffic (ref ``testing/TESTPaxosConfig.java:563-580``).  The host-sim
cluster (``testing/sim.py``) has always modeled that with per-link
delivery matrices — but the actual deployment shapes (vmap single-chip
and shard_map multi-chip) hardwired full delivery.  These tests drive
the SAME crash / election / catch-up schedule through all three paths
and require bit-identical engine state, so "multi-chip correctness under
faults" rests on more than static-membership equivalence.
"""

import jax.numpy as jnp
import numpy as np

from gigapaxos_tpu.ops.ballot import NULL, ballot_coord
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.ops.lifecycle import initial_coordinator
from gigapaxos_tpu.parallel.mesh import make_mesh
from gigapaxos_tpu.parallel.spmd import (
    build_replica_states,
    single_chip_step,
    spmd_step,
)
from gigapaxos_tpu.testing.sim import DELIVER, DROP, SimCluster

R, G, K, W = 3, 8, 4, 8
CFG = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)


def _schedule():
    """(delivery [R,R], req [R,G,K], want [R,G]) per step.

    A crash / election / carryover / catch-up storyline:
      steps 0-3   all-deliver traffic to each group's coordinator;
      steps 4-9   replica 0 crashes (drops all its links both ways) while
                  clients keep submitting to it AND to replica 1 — the
                  groups replica 0 coordinated stall;
      step 5      replica 1 runs for coordinator of every group (the FD's
                  want_coord pulse) -> prepare, carryover of replica 0's
                  accepted-but-unchosen slots, fresh ballot;
      steps 10-17 replica 0 rejoins (full delivery, no longer proposing)
                  and must catch back up to the new coordinator's frontier.
    """
    steps = []
    vid = 1
    coord0 = np.asarray(_coord0())
    for t in range(18):
        delivery = np.full((R, R), DELIVER)
        if 4 <= t <= 9:
            delivery[0, :] = DROP
            delivery[:, 0] = DROP
        req = np.full((R, G, K), NULL, np.int32)
        if t <= 3:
            for g in range(G):
                req[int(coord0[g]), g, 0] = vid
                vid += 1
        elif t <= 9:
            for g in range(G):
                req[0, g, 0] = vid  # lost on the dead replica
                vid += 1
                req[1, g, 0] = vid
                vid += 1
        want = np.zeros((R, G), bool)
        if t == 5:
            want[1, :] = True
        steps.append((delivery, req, want))
    return steps


def _run_sim(schedule):
    sim = SimCluster(CFG)
    sim.create_all_groups()
    for delivery, req, want in schedule:
        sim.step_all(
            reqs={i: req[i] for i in range(R)},
            want_coord={i: want[i] for i in range(R)},
            delivery=delivery,
        )
    return sim


def _heard_of(delivery):
    return jnp.asarray(delivery == DELIVER)


def _coord0():
    return initial_coordinator(np.arange(G), np.full(G, (1 << R) - 1))


def _assert_states_equal(states, sim):
    for name in states._fields:
        got = np.asarray(getattr(states, name))
        exp = np.stack([np.asarray(getattr(s, name)) for s in sim.states])
        np.testing.assert_array_equal(got, exp, err_msg=name)


def test_single_chip_faults_match_host_sim():
    schedule = _schedule()
    sim = _run_sim(schedule)

    fn = single_chip_step(CFG)
    states = build_replica_states(CFG, coord0=_coord0())
    for delivery, req, want in schedule:
        states, _ = fn(
            states, jnp.asarray(req), jnp.asarray(want), _heard_of(delivery)
        )

    _assert_states_equal(states, sim)

    # the storyline really happened: an election moved every group's
    # ballot to replica 1, and progress continued under the crash
    bal_coord = ballot_coord(np.asarray(states.bal))
    assert (bal_coord == 1).all(), bal_coord
    fr = np.asarray(states.exec_slot)
    # every group committed its pre-crash traffic, and the groups that
    # kept a live coordinator throughout committed their crash-window
    # traffic too (the exact per-group frontier is pinned by the sim
    # equality above; these bounds just document the storyline)
    assert fr.min() >= 4 and fr.max() >= 10, fr
    # the rejoined replica 0 caught up: frontiers equal across replicas
    assert (fr == fr[0]).all(), fr
    h = np.asarray(states.app_hash)
    assert (h == h[0]).all() and (h[0] != 0).all()


def test_spmd_faults_match_host_sim():
    """The same schedule through shard_map + all_gather on the 8-device
    virtual mesh: the dead peer is masked out of quorums INSIDE the
    sharded region, so elections and carryover run on the ICI path."""
    schedule = _schedule()
    sim = _run_sim(schedule)

    mesh = make_mesh(n_replicas=R, n_group_shards=2)
    fn = spmd_step(CFG, mesh)
    states = build_replica_states(CFG, coord0=_coord0())
    for delivery, req, want in schedule:
        states, _ = fn(
            states, jnp.asarray(req), jnp.asarray(want), _heard_of(delivery)
        )

    _assert_states_equal(states, sim)
    bal_coord = ballot_coord(np.asarray(states.bal))
    assert (bal_coord == 1).all(), bal_coord
    fr = np.asarray(states.exec_slot)
    assert (fr == fr[0]).all() and fr.min() >= 4 and fr.max() >= 10, fr


def test_spmd_partition_heals():
    """A 2/1 partition (replica 2 isolated) on the shard_map path: the
    majority side keeps committing, the minority freezes, and after the
    partition heals the minority catches up bit-exactly (host-sim
    agreement re-checked through the SafetyChecker)."""
    sim = SimCluster(CFG)
    sim.create_all_groups()
    mesh = make_mesh(n_replicas=R, n_group_shards=2)
    fn = spmd_step(CFG, mesh)
    states = build_replica_states(CFG, coord0=_coord0())

    coord0 = np.asarray(_coord0())
    vid = 1
    for t in range(16):
        delivery = np.full((R, R), DELIVER)
        if 3 <= t <= 8:
            delivery[2, :] = DROP
            delivery[:, 2] = DROP
        req = np.full((R, G, K), NULL, np.int32)
        for g in range(G):
            req[int(coord0[g]), g, 0] = vid
            vid += 1
        want = np.zeros((R, G), bool)
        sim.step_all(
            reqs={i: req[i] for i in range(R)},
            want_coord={i: want[i] for i in range(R)},
            delivery=delivery,
        )
        states, _ = fn(
            states, jnp.asarray(req), jnp.asarray(want), _heard_of(delivery)
        )
        if t == 8:
            fr = np.asarray(states.exec_slot)
            # minority stalled while the majority committed
            assert fr[:2].min() > fr[2].max(), fr

    _assert_states_equal(states, sim)
    fr = np.asarray(states.exec_slot)
    assert (fr == fr[0]).all() and fr.min() >= 12
