"""Loopback soak parity (ref: ``tests/loopback_1_group/
testing.properties:1-9`` — 10,000 requests at 1,000 req/s over 1 group x
3 replicas on 127.0.0.1, and the ``loopback_10_groups`` variant): fixed-
load soaks against the DEPLOYABLE node path (sockets + client), asserting
the reference probe's >= 90% response-rate bar.  These are the regression
numbers for the request-coalescing path — before batching, one group
topped out near K/tick ~ 800 req/s and this soak could not pass."""

import threading
import time

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.models.apps import NoopPaxosApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config


@pytest.fixture(scope="module")
def cluster():
    ports = free_ports(6)
    Config.clear()
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    ar_cfg = EngineConfig(n_groups=32, window=16, req_lanes=8, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", NoopPaxosApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", NoopPaxosApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    client = ReconfigurableAppClient.from_properties()
    yield nodes, client
    client.close()
    for n in nodes:
        n.stop()
    Config.clear()


def soak(client, names, n_requests, rate, latency_grace_s=2.0):
    """Fire `n_requests` at `rate`/s round-robin over `names`; returns
    (response_rate, mean_latency_s)."""
    lock = threading.Lock()
    lats = []

    def cb_factory(t0):
        def cb(rid, resp, error):
            if not error:
                with lock:
                    lats.append(time.time() - t0)
        return cb

    interval = 1.0 / rate
    next_t = time.time()
    for i in range(n_requests):
        now = time.time()
        while now < next_t:
            time.sleep(min(interval, next_t - now))
            now = time.time()
        next_t += interval
        client.send_request(
            names[i % len(names)], f"s{i}", cb_factory(time.time())
        )
    time.sleep(latency_grace_s)
    with lock:
        n_ok = len(lats)
        mean = sum(lats) / n_ok if n_ok else float("inf")
    return n_ok / n_requests, mean


@pytest.mark.timeout(180)
def test_loopback_1_group_soak(cluster):
    """1 group x 3 replicas, 10k requests @ 1k/s (the reference's
    loopback_1_group config), >= 90% answered."""
    _nodes, client = cluster
    ack = client.create_name("soak1", actives=[0, 1, 2], timeout=30)
    assert ack and ack.get("ok"), ack
    assert client.send_request_sync("soak1", "warm", timeout=15) is not None
    resp_rate, mean_lat = soak(client, ["soak1"], 10_000, 1_000.0)
    assert resp_rate >= 0.90, (resp_rate, mean_lat)
    assert mean_lat < 2.0, mean_lat


@pytest.mark.timeout(180)
def test_loopback_10_groups_soak(cluster):
    """10 groups variant (loopback_10_groups): the same load spread over
    10 names, >= 90% answered."""
    _nodes, client = cluster
    names = [f"soak10_{i}" for i in range(10)]
    for nm in names:
        ack = client.create_name(nm, actives=[0, 1, 2], timeout=30)
        assert ack and ack.get("ok"), ack
        assert client.send_request_sync(nm, "warm", timeout=15) is not None
    resp_rate, mean_lat = soak(client, names, 10_000, 1_000.0)
    assert resp_rate >= 0.90, (resp_rate, mean_lat)
    assert mean_lat < 2.0, mean_lat
