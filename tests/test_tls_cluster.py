"""Mutual-auth TLS across the DEPLOYABLE cluster (ref: SSL modes
CLEAR/SERVER_AUTH/MUTUAL_AUTH, ``SSLDataProcessingWorker.java:59``,
``PaxosConfig.java:548-553``; the reference's test02_MutualAuthRequest):
boot the full 6-node ReconfigurableNode cluster with MUTUAL_AUTH and
drive create -> request -> response through a certified client; a
certificate-less client must be rejected at the handshake."""

import ssl
import subprocess
import threading
import time

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config


def make_cert(tmp_path):
    key = tmp_path / "key.pem"
    crt = tmp_path / "cert.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        capture_output=True,
    )
    if r.returncode != 0:
        pytest.skip("openssl unavailable for cert generation")
    return str(key), str(crt)


@pytest.mark.timeout(300)
def test_mutual_auth_cluster_end_to_end(tmp_path):
    key, crt = make_cert(tmp_path)
    ports = free_ports(6)
    Config.clear()
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    # the shared self-signed cert doubles as the trust anchor: every
    # node (and the client) must PRESENT it and VERIFY peers against it
    Config.set("SSL_MODE", "MUTUAL_AUTH")
    Config.set("SSL_KEY_FILE", key)
    Config.set("SSL_CERT_FILE", crt)
    Config.set("SSL_CA_FILE", crt)
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    client = ReconfigurableAppClient.from_properties()
    try:
        # full control + data path over mutually-authenticated TLS
        ack = client.create_name("tls", actives=[0, 1, 2], timeout=60)
        assert ack and ack.get("ok"), ack
        resp = client.send_request_sync("tls", "hello", timeout=30)
        assert resp is not None
        # RSM converged across replicas (consensus plane ran under TLS)
        deadline = time.time() + 30
        while time.time() < deadline:
            states = {
                n.servers[0].manager.app.state.get("tls") for n in nodes[:3]
            }
            if len(states) == 1 and None not in states:
                break
            time.sleep(0.5)
        assert len(states) == 1 and None not in states, states

        # a certificate-less client must FAIL the mutual-auth handshake
        bare_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        bare_ctx.load_verify_locations(crt)
        bare_ctx.check_hostname = False  # verifies server, presents nothing
        bare = ReconfigurableAppClient.from_properties()
        bare._ssl_ctx = bare_ctx
        try:
            got = []
            ev = threading.Event()
            bare.send_request(
                "tls", "nope",
                lambda rid, r, e: (got.append((r, e)), ev.set()),
            )
            # resolution itself needs an RC connection, which the
            # handshake rejects — no response may ever arrive
            assert not ev.wait(5), got
        finally:
            bare.close()
    finally:
        client.close()
        for n in nodes:
            n.stop()
        Config.clear()


@pytest.mark.timeout(300)
def test_client_plane_port_split(tmp_path):
    """Per-plane port split (PaxosConfig.java:219-224): a MUTUAL_AUTH
    mesh serves SERVER_AUTH clients on port + CLIENT_PORT_OFFSET — a
    certificate-less client works on the client plane while the mesh
    stays mutually authenticated."""
    key, crt = make_cert(tmp_path)
    ports = free_ports(12)  # mesh ports; +offset client ports are derived
    Config.clear()
    # derive client ports that cannot collide with the mesh ports: use a
    # fresh block's offsets
    offset = 1000
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    Config.set("CLIENT_PORT_OFFSET", offset)
    Config.set("SSL_MODE", "MUTUAL_AUTH")
    Config.set("CLIENT_SSL_MODE", "SERVER_AUTH")
    Config.set("SSL_KEY_FILE", key)
    Config.set("SSL_CERT_FILE", crt)
    Config.set("SSL_CA_FILE", crt)
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    # SERVER_AUTH dialer with NO client certificate, against client ports
    bare_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    bare_ctx.load_verify_locations(crt)
    bare_ctx.check_hostname = False
    client = ReconfigurableAppClient.from_properties()
    client._ssl_ctx = bare_ctx
    try:
        ack = client.create_name("split", actives=[0, 1, 2], timeout=60)
        assert ack and ack.get("ok"), ack
        assert client.send_request_sync("split", "x", timeout=30) is not None
    finally:
        client.close()
        for n in nodes:
            n.stop()
        Config.clear()
