"""Recovery-at-scale smoke (slow): a node hosting thousands of groups
restarts, serves a hot name BEFORE background hydration completes, and
converges.  Asserts phase/ordering facts only — never wall-clock (full
restart-to-serving numbers live in ``scripts/recovery_probe.py`` output,
committed as RECOVERY_r01.json)."""

import numpy as np
import pytest

from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.utils.config import Config

G = 4096
N_NAMES = 2048
HOT = 64


def _ticks(m, n=6):
    for _ in range(n):
        vec, _st = m.publish_snapshot()
        m.tick_host(np.stack([vec]), np.array([True]))


@pytest.mark.slow
def test_restart_serves_hot_before_hydration_completes(tmp_path):
    from gigapaxos_tpu.manager import PaxosManager
    from gigapaxos_tpu.recovery.hydration import Hydrator

    Config.set("RECOVERY_CHECKPOINT_SHARDS", "8")
    Config.set("RECOVERY_HOT_NAMES", str(HOT))
    Config.set("RECOVERY_REPLAY_WORKERS", "4")
    cfg = EngineConfig(n_groups=G, window=8, req_lanes=4, n_replicas=1)
    names = [f"svc{i:05d}" for i in range(N_NAMES)]

    m = PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
        checkpoint_every=10 ** 9, sync_journal=False,
    )
    for lo in range(0, N_NAMES, 512):
        m.create_paxos_batch(names[lo:lo + 512], [0])
    # traffic on a recent slice (these become the manifest's hot hints)
    active = names[-32:]
    for i, nm in enumerate(active):
        m.propose(nm, str(i + 1))
    _ticks(m, 10)
    m.checkpoint_now()
    m.logger.drain_checkpoints()
    # post-checkpoint tail so replay has real work
    m.propose(active[0], "100")
    _ticks(m, 8)
    expected = {nm: int(i) + 1 for i, nm in enumerate(active)}
    expected[active[0]] += 100
    m.close()

    # restart with the background worker held, so the ordering assertion
    # ("hot served while cold backlog outstanding") is deterministic
    held = []
    orig = Hydrator.start_background
    try:
        Hydrator.start_background = lambda self: held.append(self)
        m2 = PaxosManager(
            0, StatefulAdderApp(), cfg, log_dir=str(tmp_path),
            checkpoint_every=10 ** 9, sync_journal=False,
        )
    finally:
        Hydrator.start_background = orig
    try:
        # ORDERING FACT 1: the node is serving (construction returned)
        # while most names are still cold
        st = m2.recovery_stats()
        assert st["phase"] == "recovering"
        assert st["hydration_backlog"] >= N_NAMES - HOT - 64
        assert st["hot_hydrated"] > 0

        # ORDERING FACT 2: a hot name answers correctly NOW — before any
        # background hydration ran
        hot_name = active[-1]
        assert m2.names[hot_name] not in m2.hydrating_rows, (
            "recency hints must make recently-active names hot"
        )
        got = {}
        m2.propose(hot_name, "5", callback=lambda r, v: got.update(v=v))
        _ticks(m2, 8)
        assert got.get("v") == str(expected[hot_name] + 5), got
        assert m2.recovery_phase == "recovering"  # still recovering

        # ORDERING FACT 3: a cold name's request does not execute until
        # hydration, then drains with state intact
        cold_name = names[0]
        assert m2.names[cold_name] in m2.hydrating_rows
        got2 = {}
        m2.propose(cold_name, "9", callback=lambda r, v: got2.update(v=v))
        _ticks(m2, 3)
        assert not got2

        # release the held worker and converge
        assert held, "lazy restart must have scheduled background work"
        held[0].start_background()
        import time

        deadline = time.time() + 120
        while m2.recovery_phase != "serving" and time.time() < deadline:
            time.sleep(0.05)
        assert m2.recovery_phase == "serving"
        _ticks(m2, 8)
        assert got2.get("v") == "9"
        for nm, exp in expected.items():
            want = exp + (5 if nm == hot_name else 0)
            assert m2.app.totals.get(nm) == want, (nm, m2.app.totals.get(nm))
    finally:
        m2.close()
