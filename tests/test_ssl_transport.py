"""SSL on the message transport (ref: the nio SSL stack,
``SSLDataProcessingWorker.java:59`` — SERVER_AUTH mode): the framework's
transport takes asyncio-native TLS contexts; frames flow over an
encrypted channel end to end."""

import socket
import ssl
import subprocess
import threading

import pytest


def make_cert(tmp_path):
    key = tmp_path / "key.pem"
    crt = tmp_path / "cert.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        capture_output=True,
    )
    if r.returncode != 0:
        pytest.skip("openssl unavailable for cert generation")
    return str(key), str(crt)


def test_tls_frames_end_to_end(tmp_path):
    from gigapaxos_tpu.net.node_config import NodeConfig
    from gigapaxos_tpu.net.transport import MessageTransport

    key, crt = make_cert(tmp_path)
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(crt, key)
    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(crt)
    client_ctx.check_hostname = False

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port_a = s.getsockname()[1]
    s2 = socket.socket()
    s2.bind(("127.0.0.1", 0))
    port_b = s2.getsockname()[1]
    s.close()
    s2.close()

    nc = NodeConfig({0: ("127.0.0.1", port_a), 1: ("127.0.0.1", port_b)})
    got = threading.Event()
    inbox = []

    def handler_b(payload, peer, reply):
        inbox.append(payload)
        got.set()

    # each side presents the server cert when listening and verifies it
    # when connecting — asyncio handles both directions of one context
    # pair (SERVER_AUTH mode analog)
    ta = MessageTransport(0, nc, lambda *a: None)
    tb = MessageTransport(1, nc, handler_b)
    ta._ssl = client_ctx   # outbound connects verify
    tb._ssl = server_ctx   # inbound listener presents the cert
    tb.start()
    ta.start()
    try:
        assert ta.send_to_id(1, b"J" + b'{"secret":1}')
        assert got.wait(10), "TLS frame not delivered"
        assert inbox[0].endswith(b'{"secret":1}')
    finally:
        ta.stop()
        tb.stop()
