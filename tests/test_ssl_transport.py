"""SSL on the message transport (ref: the nio SSL stack,
``SSLDataProcessingWorker.java:59`` — SERVER_AUTH mode): each mesh peer
listens with a server context and dials with a verifying client context;
frames flow encrypted in BOTH directions."""

import ssl
import subprocess
import threading

import pytest


def make_cert(tmp_path):
    key = tmp_path / "key.pem"
    crt = tmp_path / "cert.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        capture_output=True,
    )
    if r.returncode != 0:
        pytest.skip("openssl unavailable for cert generation")
    return str(key), str(crt)


def test_tls_frames_both_directions(tmp_path):
    from gigapaxos_tpu.net.node_config import NodeConfig
    from gigapaxos_tpu.net.transport import MessageTransport

    key, crt = make_cert(tmp_path)

    def contexts():
        server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        server.load_cert_chain(crt, key)
        client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        client.load_verify_locations(crt)
        client.check_hostname = False
        return server, client

    nc = NodeConfig({0: ("127.0.0.1", 0), 1: ("127.0.0.1", 0)})
    got = {0: threading.Event(), 1: threading.Event()}
    inbox = {0: [], 1: []}

    def handler(me):
        def h(payload, peer, reply):
            inbox[me].append(payload)
            got[me].set()
        return h

    transports = []
    for nid in (0, 1):
        srv_ctx, cli_ctx = contexts()
        t = MessageTransport(
            nid, nc, handler(nid),
            listen_host="127.0.0.1", listen_port=0,  # race-free ephemeral
            ssl_server_context=srv_ctx, ssl_client_context=cli_ctx,
        )
        t.start()
        nc.add(nid, "127.0.0.1", t.listen_port)  # publish the bound port
        transports.append(t)
    try:
        assert transports[0].send_to_id(1, b"J" + b'{"dir":"0->1"}')
        assert got[1].wait(10), "0->1 TLS frame not delivered"
        assert inbox[1][0].endswith(b'{"dir":"0->1"}')
        # the REVERSE direction: node 1 dials node 0's listener — requires
        # the server/client context split (one shared context cannot both
        # present and verify)
        assert transports[1].send_to_id(0, b"J" + b'{"dir":"1->0"}')
        assert got[0].wait(10), "1->0 TLS frame not delivered"
        assert inbox[0][0].endswith(b'{"dir":"1->0"}')
    finally:
        for t in transports:
            t.stop()
