"""Device-plane observatory (obs/device.py + manager/server hooks).

Covers the four tentpole instruments end to end:

* the retrace/compile sentinel — counts compiles, flags shape-unstable
  steps as retraces after warmup, and the HARD invariant that the
  deployed hot dispatch compiles exactly once across a multi-tick
  loopback run;
* group-heat telemetry — the on-device ``[G]`` accumulator bit-matches
  a longhand host recount of every substep's decided+admitted counts
  over a chaos-seeded ManagerCluster run, and the bulk histogram fold
  bit-matches scalar observes;
* cost attribution — ``step_cost`` AOT split, provenance JSON
  round-trip, the ``profile`` admin op writing into (and bounding) its
  dump directory;
* the perf-regression observatory — the committed PERF_BASELINE.json
  stays structurally valid (``--check-only``; no wall-clock gates in
  tier-1) and the validator actually rejects gutted documents.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- retrace/compile sentinel ----------------------------------------

def test_sentinel_counts_compiles_and_flags_shape_instability():
    import jax
    import jax.numpy as jnp

    from gigapaxos_tpu.obs.device import StepSentinel

    @jax.jit
    def f(x):
        return x * 2

    s = StepSentinel(f, label="unit-test-step")
    s(jnp.ones((4,), jnp.int32))
    assert s.n_compiles == 1 and s.n_retraces == 0
    # same shape again: cache hit, no new compile
    s(jnp.ones((4,), jnp.int32))
    assert s.n_compiles == 1
    s.assert_no_retraces()

    # warmup declared over: the next compile — a SHAPE-UNSTABLE call —
    # must be recorded as a retrace, not just a compile
    s.mark_warm()
    s(jnp.ones((4,), jnp.int32))
    assert s.n_retraces == 0
    s(jnp.ones((5,), jnp.int32))
    assert s.n_compiles == 2 and s.n_retraces == 1
    with pytest.raises(RuntimeError, match="retrace"):
        s.assert_no_retraces()

    kinds = [e["kind"] for e in s.events()]
    assert kinds == ["compile", "retrace"]
    st = s.stats()
    assert st["label"] == "unit-test-step"
    assert st["compiles"] == 2 and st["retraces"] == 1 and st["warm"]
    assert st["last"]["kind"] == "retrace"
    # events are JSON-clean: they ride the stats admin op verbatim
    json.dumps(s.events())


def test_sentinel_is_transparent_to_aot_and_step_cost():
    import jax
    import jax.numpy as jnp

    from gigapaxos_tpu.obs.device import StepSentinel, step_cost

    @jax.jit
    def f(x):
        return x + 1

    s = StepSentinel(f, label="aot")
    x = jnp.ones((8,), jnp.int32)
    cost = step_cost(s, x)
    assert cost["lowering_s"] > 0 and cost["compile_s"] > 0
    assert "flops" in cost and "bytes_accessed" in cost
    assert isinstance(cost["memory"], dict)
    # AOT ran through .lower()/.compile() without touching the jit
    # dispatch cache: the sentinel saw zero compiles
    assert s.n_compiles == 0
    # passthrough attribute access reaches the wrapped jit function
    assert s.fn is f
    s.lower(x)  # must not raise


def test_hot_dispatch_compiles_exactly_once_loopback():
    """THE tentpole invariant: across a multi-tick loopback run with
    real client traffic, the deployed hot dispatch step compiles exactly
    once (warmup) and never retraces — and the retrace sentinel's
    engine.compile block + counters surface that through the stats op.
    Also exercises the `profile` admin op against a live node."""
    import tempfile

    from gigapaxos_tpu.clients import PaxosClientAsync
    from gigapaxos_tpu.models.apps import StatefulAdderApp
    from gigapaxos_tpu.net.node_config import NodeConfig
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.server import PaxosServer
    from gigapaxos_tpu.testing.ports import free_ports

    # distinctive shape: this test owns its make_step cache entry, so
    # the shared sentinel's lifetime counts are this run's counts
    cfg = EngineConfig(n_groups=7, window=8, req_lanes=4, n_replicas=3)
    ports = free_ports(3)
    nc = NodeConfig({i: ("127.0.0.1", p) for i, p in enumerate(ports)})
    servers = [
        PaxosServer(i, nc, StatefulAdderApp(), cfg, tick_interval=0.01)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    client = PaxosClientAsync([("127.0.0.1", p) for p in ports])
    try:
        assert client.create_paxos_instance("obsdev", [0, 1, 2],
                                            timeout=30)
        total = 0
        for i in range(12):
            total += i
            assert client.send_request_sync(
                "obsdev", str(i), timeout=30
            ) == str(total)
        # let every node run a healthy number of further ticks
        time.sleep(0.5)

        for s in servers:
            sent = s.manager._dispatch_step
            assert sent.warm, "first dispatch should have marked warm"
            assert sent.n_compiles == 1, sent.stats()
            assert sent.n_retraces == 0, sent.stats()
            sent.assert_no_retraces()
            s.manager._tick_step.assert_no_retraces()

        # the same picture through the admin plane
        r = client.admin_sync(0, {"op": "stats"}, timeout=10)
        assert r and r["ok"]
        eng = r["engine"]
        comp = eng["compile"]
        assert comp["dispatch"]["compiles"] == 1
        assert comp["dispatch"]["retraces"] == 0
        assert eng["counters"].get("engine_compiles", 0) >= 1
        assert eng["counters"].get("engine_retraces", 0) == 0
        # heat rode along: the decided+admitted traffic shows up in the
        # stats block's heat summary with a real top-groups table
        heat = eng["heat"]
        assert heat["total"] > 0 and heat["active_groups"] >= 1
        assert heat["top_groups"][0]["heat"] > 0

        # `profile` admin op: writes a capture into the requested dir
        with tempfile.TemporaryDirectory() as td:
            r = client.admin_sync(
                0, {"op": "profile", "dir": td, "seconds": 0.02},
                timeout=15,
            )
            assert r and r["ok"], r
            assert r["dir"].startswith(td) and os.path.isdir(r["dir"])
            assert r["seconds"] > 0
    finally:
        client.close()
        for s in servers:
            s.stop()


# ---- group-heat telemetry --------------------------------------------

def test_group_heat_bitmatches_host_recount_chaos_run():
    """The on-device heat accumulator is exact, not approximate: over a
    chaos-seeded stepped run (random proposals, random link drops, an
    election kick), every manager's pulled heat equals a longhand host
    recount of per-substep ``n_committed + n_admitted``."""
    from gigapaxos_tpu.models.apps import HashChainApp
    from gigapaxos_tpu.ops.engine import EngineConfig, StepOutputs
    from gigapaxos_tpu.testing.cluster import DELIVER, DROP, ManagerCluster

    cfg = EngineConfig(n_groups=8, window=4, req_lanes=2, n_replicas=3)
    R, G = cfg.n_replicas, cfg.n_groups
    c = ManagerCluster(cfg, HashChainApp)
    try:
        # longhand recount: intercept every dispatch's StepOutputs list
        # BEFORE the engine's own post-step work consumes it
        expected = [np.zeros(G, np.int64) for _ in range(R)]

        def _wrap(m, exp):
            orig = m._post_step_locked

            def wrapped(outs):
                lst = [outs] if isinstance(outs, StepOutputs) else outs
                for o in lst:
                    exp[:] += np.asarray(o.n_committed).astype(np.int64)
                    exp[:] += np.asarray(o.n_admitted).astype(np.int64)
                return orig(outs)

            m._post_step_locked = wrapped

        for rid, m in enumerate(c.managers):
            _wrap(m, expected[rid])

        names = ["heat0", "heat1", "heat2"]
        for nm in names:
            c.create(nm)
        rng = np.random.default_rng(20260807)
        for step in range(40):
            for _ in range(int(rng.integers(0, 4))):
                nm = names[int(rng.integers(0, len(names)))]
                c.submit(nm, f"v{step}-{rng.integers(1 << 20)}",
                         entry=int(rng.integers(0, R)))
            delivery = np.where(
                rng.random((R, R)) < 0.2, DROP, DELIVER
            )
            np.fill_diagonal(delivery, DELIVER)
            c.step_all(delivery=delivery)
        # settle with clean links so in-flight traffic drains
        c.run(10)

        saw_heat = False
        for rid, m in enumerate(c.managers):
            delta = m.pull_group_heat()
            assert delta.dtype == np.int64
            np.testing.assert_array_equal(m._heat_host, expected[rid])
            saw_heat = saw_heat or expected[rid].any()
            # drained on pull: a second pull returns zeros while the
            # cumulative host view is unchanged
            again = m.pull_group_heat()
            assert not again.any()
            np.testing.assert_array_equal(m._heat_host, expected[rid])
            # the summary agrees with the longhand vector
            summ = m.group_heat_stats()
            assert summ["total"] == int(expected[rid].sum())
            assert summ["active_groups"] == int(
                (expected[rid] > 0).sum()
            )
        assert saw_heat, "chaos run decided/admitted nothing"
    finally:
        c.close()


def test_heat_summary_longhand():
    from gigapaxos_tpu.obs.device import heat_summary

    heat = np.zeros(200, np.int64)
    heat[7] = 100
    heat[13] = 30
    heat[99] = 1
    s = heat_summary(heat, topk=2, name_of={7: "hot"}.get)
    assert s["total"] == 131 and s["active_groups"] == 3
    assert [r["row"] for r in s["top_groups"]] == [7, 13]
    assert s["top_groups"][0]["name"] == "hot"
    assert "name" not in s["top_groups"][1]
    # hot set = top 1% = ceil(200/100) = 2 rows -> 130/131 of traffic
    assert s["hot_set"]["rows"] == 2
    assert s["hot_set"]["traffic_share"] == pytest.approx(130 / 131)
    assert heat_summary(np.zeros(4, np.int64))["total"] == 0


def test_observe_bulk_bitmatches_scalar_observe():
    from gigapaxos_tpu.obs.device import HEAT_BOUNDS
    from gigapaxos_tpu.obs.metrics import MetricsRegistry

    rng = np.random.default_rng(7)
    samples = rng.integers(1, 100_000, size=500).astype(np.float64)
    a = MetricsRegistry(node=0)
    b = MetricsRegistry(node=0)
    for x in samples:
        a.observe("group_heat", float(x), bounds=HEAT_BOUNDS)
    b.observe_bulk("group_heat", samples, bounds=HEAT_BOUNDS)
    sa = a.snapshot()["hists"]["group_heat"]
    sb = b.snapshot()["hists"]["group_heat"]
    assert sa["buckets"] == sb["buckets"]
    assert sa["count"] == sb["count"]
    assert sa["min"] == sb["min"] and sa["max"] == sb["max"]
    assert sa["sum"] == pytest.approx(sb["sum"])
    # empty fold registers nothing
    b.observe_bulk("other", np.array([]))
    assert "other" not in b.snapshot()["hists"]


# ---- cost attribution / provenance / profiler -------------------------

def test_provenance_roundtrips_json():
    from gigapaxos_tpu.obs.device import provenance

    p = provenance(donate=True, extra={"run": "unit"})
    assert json.loads(json.dumps(p)) == p
    for key in ("jax", "jaxlib", "backend", "platform", "device_kind",
                "n_devices", "xla_flags", "python", "donation"):
        assert key in p, key
    assert p["donation"] is True and p["run"] == "unit"
    assert p["platform"] == "cpu"  # conftest pins the test backend


def test_capture_profile_writes_and_bounds_dump_dir(tmp_path):
    from gigapaxos_tpu.obs.device import capture_profile

    root = str(tmp_path / "profiles")
    caps = [
        capture_profile(root, seconds=0.01, max_dumps=2)
        for _ in range(4)
    ]
    for cap in caps[-2:]:
        assert os.path.isdir(cap["dir"])
    dumps = [d for d in os.listdir(root)
             if os.path.isdir(os.path.join(root, d))]
    assert len(dumps) <= 2, dumps
    assert sum(c["rotated_out"] for c in caps) >= 2
    # the per-capture wall clamp holds even against absurd requests
    cap = capture_profile(root, seconds=99.0, max_dumps=2,
                          max_seconds=0.05)
    assert cap["seconds"] < 1.0


# ---- SLO gate ---------------------------------------------------------

def test_slo_budget_parse_and_breach():
    from gigapaxos_tpu.obs import tracemerge as tm
    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.utils.config import Config

    # the shipped default must parse (every phase name real)
    budgets = tm.parse_slo_budgets(Config.get_str(PC.SLO_BUDGETS_MS))
    assert budgets["total"] == pytest.approx(2.0)
    with pytest.raises(ValueError, match="unknown phase"):
        tm.parse_slo_budgets("execute=10")
    trace = {
        "hops": [
            {"phase": "ingress", "dt_s": 0.040},
            {"phase": "ingress", "dt_s": 0.020},
            {"phase": "consensus", "dt_s": 0.100},
        ],
        "total_s": 0.160,
    }
    over = tm.slo_breaches(trace, budgets)
    assert [b["phase"] for b in over] == ["ingress"]  # 60ms > 50ms
    assert not tm.slo_breaches(trace, {"consensus": 0.5})


# ---- perf-regression observatory --------------------------------------

def _load_perf_baseline_module():
    spec = importlib.util.spec_from_file_location(
        "perf_baseline", os.path.join(REPO, "scripts", "perf_baseline.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_baseline_committed_artifact_valid():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "perf_baseline.py"),
         "--check-only"],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr or r.stdout
    doc = json.load(open(os.path.join(REPO, "PERF_BASELINE.json")))
    series = doc["series"]["committed_decisions_per_s"]
    # full committed bench series, split by platform, with bands
    assert series["cpu"]["rounds"] == ["r01", "r02", "r03"]
    assert series["tpu"]["rounds"] == ["r04", "r05"]
    for s in series.values():
        assert 0 < s["band"]["lower"] < min(s["values"])
    assert doc["series"]["dispatch_ablation"]["rounds"] == ["r06"]
    assert doc["fresh_check"]["in_band"] is True
    assert doc["fresh_check"]["provenance"]["jax"]


def test_perf_baseline_validator_rejects_gutted_doc():
    mod = _load_perf_baseline_module()
    doc = json.load(open(os.path.join(REPO, "PERF_BASELINE.json")))
    assert mod.validate(doc) == []
    broken = json.loads(json.dumps(doc))
    del broken["series"]["committed_decisions_per_s"]
    assert any("committed_decisions_per_s" in e
               for e in mod.validate(broken))
    below = json.loads(json.dumps(doc))
    below["fresh_check"]["in_band"] = False
    assert any("out of band" in e for e in mod.validate(below))
    # a fresh value below the band is gated out
    band = doc["series"]["committed_decisions_per_s"]["cpu"]["band"]
    fc = mod.check_fresh(doc, {
        "metric": "committed_decisions_per_s",
        "value": band["lower"] * 0.5,
        "unit": "decisions/s (8192 groups, 3 replicas, 1 chip, cpu)",
    })
    assert fc["in_band"] is False
