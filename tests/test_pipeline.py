"""Serving pipeline: double-buffered dispatch must be STEP-FOR-STEP
state-identical to the serial tick on a recorded request schedule, and
lifecycle ops must serialize against an in-flight step (never interleave
with the device compute + post-step window)."""

import threading
import time

import numpy as np
import pytest

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig

CFG = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


class PackedCluster:
    """Three managers exchanging PACKED blob vectors (the socket
    runtime's wire path), steppable in serial or pipelined mode."""

    def __init__(self, pipelined: bool):
        self.pipelined = pipelined
        self.managers = [
            PaxosManager(r, HashChainApp(), CFG) for r in range(3)
        ]
        for m in self.managers:
            m.outstanding.timeout_s = float("inf")
        self.vecs = [m.blob_vec() for m in self.managers]
        self.inboxes = [[] for _ in range(3)]

    def create(self, name):
        row = self.managers[0].default_row_for(name)
        for m in self.managers:
            m.create_paxos_instance(name, [0, 1, 2], row=row)
        self.vecs = [m.blob_vec() for m in self.managers]
        return row

    def step_all(self):
        for i, m in enumerate(self.managers):
            inbox, self.inboxes[i] = self.inboxes[i], []
            for kind, body in inbox:
                m.on_host_message(kind, body)
        heard = np.ones(3, bool)
        new_vecs = list(self.vecs)
        deltas = []
        for i, m in enumerate(self.managers):
            gathered = np.stack(
                [self.vecs[j] for j in range(3)]
            )
            if self.pipelined:
                pend = m.step_dispatch(gathered, heard)
                vec, _state, delta = m.step_complete(pend)
            else:
                vec, _state, delta = m.tick_host(gathered, heard)
            new_vecs[i] = vec
            deltas.append(delta)
        self.vecs = new_vecs
        for i, delta in enumerate(deltas):
            ae = delta.get("app_exec")
            if delta["arena"] or (ae and ae[1]):
                for j in range(3):
                    if j != i:
                        self.inboxes[j].append(("payloads", delta))
            for dst, kind, body in self.managers[i].drain_forward_out():
                if dst == i:
                    self.managers[i].on_host_message(kind, body)
                elif dst == -1:
                    for j in range(3):
                        if j != i:
                            self.inboxes[j].append((kind, body))
                else:
                    self.inboxes[dst].append((kind, body))

    def close(self):
        for m in self.managers:
            m.close()


def test_pipeline_state_parity():
    """Identical schedule through serial and pipelined dispatch: every
    engine leaf equal after every cluster step, and identical client
    responses."""
    serial, piped = PackedCluster(False), PackedCluster(True)
    try:
        resp_s, resp_p = [], []
        names = ["pa", "pb", "pc"]
        for c in (serial, piped):
            for nm in names:
                c.create(nm)
        rid = 1 << 56
        for step_no in range(40):
            for c, resp in ((serial, resp_s), (piped, resp_p)):
                if step_no % 3 == 0:
                    nm = names[step_no % len(names)]
                    c.managers[step_no % 3].propose(
                        nm, f"v{step_no}",
                        callback=(
                            lambda r, x, _t=step_no, _o=resp:
                            _o.append((_t, r, x))
                        ),
                        request_id=rid + step_no,
                    )
                if step_no == 20:
                    c.managers[1].propose(
                        names[0], "v0",
                        callback=(
                            lambda r, x, _o=resp:
                            _o.append(("dup", r, x))
                        ),
                        request_id=rid + 0,
                    )
                c.step_all()
            # step-for-step: EVERY leaf of EVERY replica identical
            for ms, mp in zip(serial.managers, piped.managers):
                for leaf in ms.state._fields:
                    a = np.asarray(getattr(ms.state, leaf))
                    b = np.asarray(getattr(mp.state, leaf))
                    assert np.array_equal(a, b), (
                        step_no, ms.my_id, leaf,
                    )
                assert np.array_equal(
                    ms.app_exec_slot, mp.app_exec_slot
                ), (step_no, ms.my_id)
        assert sorted(resp_s, key=str) == sorted(resp_p, key=str)
        assert len(resp_s) >= 10  # the schedule actually decided things
    finally:
        serial.close()
        piped.close()


def test_lifecycle_waits_for_inflight_step():
    """A state-replacing op (create) arriving during the in-flight
    window must WAIT for step_complete — interleaving would let the
    post-step host cycle process step outputs against rows the lifecycle
    op rewrote."""
    m = PaxosManager(0, HashChainApp(), CFG)
    try:
        m.create_paxos_instance("x", [0])
        vec = m.blob_vec()
        heard = np.array([True, False, False])
        pend = m.step_dispatch(np.stack([vec, vec, vec]), heard)
        done = threading.Event()

        def create_side():
            m.create_paxos_instance("y", [0])
            done.set()

        t = threading.Thread(target=create_side, daemon=True)
        t.start()
        # the create must be BLOCKED while the step is in flight
        assert not done.wait(0.3), (
            "lifecycle op interleaved with an in-flight step"
        )
        m.step_complete(pend)
        assert done.wait(5.0), "lifecycle op never resumed after complete"
        t.join(5.0)
        assert "y" in m.names
    finally:
        m.close()


def test_flush_coalescing_metrics():
    """A loopback round trip populates the flush metrics (one frame per
    peer per cycle: responses_flushed counter + flush_batch_size hist),
    and the stats admin op reports the live codec + pipeline mode."""
    from tests.test_server import boot_cluster

    servers, client, _ = boot_cluster()
    try:
        assert client.create_paxos_instance("fm", [0, 1, 2], timeout=30)
        for i in range(4):
            assert client.send_request_sync(
                "fm", str(i + 1), timeout=30
            ) is not None
        mx = [s.manager.metrics for s in servers]
        # the client randomizes entry replicas — count across the cluster
        assert sum(m.get("responses_flushed") for m in mx) >= 4
        assert any(
            "flush_batch_size" in m.snapshot()["hists"] for m in mx
        )
        st = client.admin_sync(0, {"op": "stats"}, timeout=10)
        assert st and st["ok"]
        serving = st["serving"]
        assert serving["pipeline_dispatch"] is True
        assert serving["codec"]["binary_frames"] is True
        assert serving["codec"]["impl"] in ("gp_codec.so", "python-struct")
        assert serving["serving_workers"] == 1
    finally:
        client.close()
        for s in servers:
            s.stop()


@pytest.mark.timeout(120)
def test_pipelined_loopback_under_overlap():
    """Sanity: with pipelining ON (the default), concurrent client load
    through real sockets stays correct — responses arrive and replicas
    converge (the overlap window is exercised by the live tick loop)."""
    from tests.test_server import boot_cluster, wait_until

    servers, client, _ = boot_cluster()
    try:
        assert servers[0]._pipeline is True
        assert client.create_paxos_instance("ov", [0, 1, 2], timeout=30)
        total = 0
        for i in range(8):
            resp = client.send_request_sync("ov", str(i + 1), timeout=30)
            total += i + 1
            assert resp == str(total)
        assert wait_until(lambda: all(
            s.manager.app.totals.get("ov") == total for s in servers
        ))
        # overlap metrics populated by the pipelined loop
        assert any(
            "pipeline_overlap_s" in s.manager.metrics.snapshot()["hists"]
            for s in servers
        )
    finally:
        client.close()
        for s in servers:
            s.stop()
