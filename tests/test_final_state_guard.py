"""The epoch-final-state restart fallback must not serve a TRUNCATED
state (chaos-sweep find, the r4 open exactly-once divergence):

``_handle_request_final_state``'s fallback re-checkpoints a stopped
group when the in-memory stop-time capture was lost (restart).  The
``is_stopped`` gate is the DEVICE flag — the host app cursor can lag
behind a missing payload, so ``app.checkpoint`` there is a mid-epoch
state whose dedup set is missing the tail executions.  A next-epoch
joiner adopting it diverges from a joiner that fetched the TRUE final
state (observed: app_n_executed 3 vs 2 at equal frontiers, one dedup
entry missing).  The fallback now also requires the app cursor to have
reached the device frontier (ref semantics: the final state is what the
epoch EXECUTED — ``ActiveReplica.java:1051``,
``PaxosManager.java:318-346``).
"""

import numpy as np

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration.active_replica import ActiveReplica
from gigapaxos_tpu.reconfiguration.coordinator import PaxosReplicaCoordinator
from gigapaxos_tpu.testing.cluster import ManagerCluster


def test_fallback_refuses_truncated_final_state():
    cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, HashChainApp)
    try:
        c.create("svc", members=[0, 1, 2])
        sent = []
        ars = [
            ActiveReplica(
                r,
                PaxosReplicaCoordinator(c.managers[r].app, c.managers[r]),
                (lambda dst, kind, body: sent.append((dst, kind, body))),
            )
            for r in range(3)
        ]
        # lag a NON-coordinator member: the value reaches the
        # coordinator via the entry (or a forward), but this member only
        # ever sees the payload through gossip/pulls — which we drop.
        # Its DEVICE executes the decision (frontier advances) while the
        # host app parks on the missing payload.
        row0 = c.managers[0].names["svc"]
        coord = c.managers[0].coordinator_of_row(row0)
        lag = (coord + 1) % 3
        m1 = c.managers[lag]
        ar1 = ars[lag]
        real_on_host = m1.on_host_message

        def drop_payloads(kind, body):
            if kind in ("payloads", "state_reply"):
                return  # the payload (and any state heal) never arrives
            real_on_host(kind, body)

        m1.on_host_message = drop_payloads
        c.submit("svc", "tail-request", entry=coord)
        c.run(10)
        # the epoch-final stop decides and device-executes everywhere
        c.submit("svc", "", entry=coord, stop=True,
                 callback=None)
        c.run(10)
        row = m1.names["svc"]
        assert int(np.asarray(m1.state.stopped)[row]) == 1
        assert m1.is_stopped("svc")
        # member 1's app never applied the tail request (nor the stop)
        assert not m1.app_caught_up("svc")
        assert m1.app.n_executed.get("svc") is None

        # a joiner asks member 1 for the epoch-final state: the fallback
        # must stay SILENT (serving app.checkpoint here would hand out a
        # truncated history + truncated dedup set)
        ar1._handle_request_final_state(
            {"name": "svc", "epoch": 0, "from": 2}
        )
        assert not [m for m in sent if m[1] == "epoch_final_state"], sent

        # member 0 executed everything: its fallback serves, and the
        # served state carries the full history + the dedup entries
        m0 = c.managers[coord]
        assert m0.app_caught_up("svc")
        ars[coord]._handle_request_final_state(
            {"name": "svc", "epoch": 0, "from": 2}
        )
        served = [m for m in sent if m[1] == "epoch_final_state"]
        assert served, "caught-up member must serve"
        body = served[0][2]
        assert body["state"] == m0.app.checkpoint("svc")
        assert body["dedup"], "dedup snapshot must ride along"

        # once the payload finally lands, member 1 catches up and serves
        # the SAME state
        m1.on_host_message = real_on_host
        c.run(30)
        if m1.app_caught_up("svc"):
            sent.clear()
            ar1._handle_request_final_state(
                {"name": "svc", "epoch": 0, "from": 2}
            )
            served2 = [m for m in sent if m[1] == "epoch_final_state"]
            assert served2 and served2[0][2]["state"] == body["state"]
    finally:
        c.close()
