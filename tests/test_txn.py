"""Distributed transactions (experimental capability parity: ``txn/
DistTransactor.java`` + ``txn/txpackets/``): sorted-order 2PC locks as
consensus ops, atomic multi-group apply, abort releases locks, and
ordinary requests are refused while a group is locked."""

from gigapaxos_tpu.models.apps import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster
from gigapaxos_tpu.txn import DistTransactor, Transaction, TxnApp

CFG = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


def make_cluster():
    c = ManagerCluster(CFG, lambda: TxnApp(StatefulAdderApp()))
    c.create("acct_a")
    c.create("acct_b")
    return c


def submitter(c):
    """Synchronous consensus submit driving the loopback cluster."""

    def submit(name, value, timeout):
        box = {}
        c.managers[0].propose(
            name, value, callback=lambda rid, resp: box.update(r=resp)
        )
        for _ in range(int(timeout / 0.001) if timeout < 5 else 400):
            if "r" in box:
                return box["r"]
            c.step_all()
        return box.get("r")

    return submit


def test_transaction_commits_across_groups():
    c = make_cluster()
    try:
        tx = DistTransactor(submitter(c))
        out = tx.execute(Transaction([("acct_a", "5"), ("acct_b", "7")]))
        assert out["committed"], out
        c.run(6)
        for m in c.managers:
            assert m.app.totals.get("acct_a") == 5
            assert m.app.totals.get("acct_b") == 7
            assert m.app.locks == {}  # all released
    finally:
        c.close()


def test_locked_group_refuses_plain_requests_until_release():
    c = make_cluster()
    try:
        submit = submitter(c)
        tx = DistTransactor(submit)
        txn = Transaction([("acct_a", "1")])
        # acquire the lock manually (phase 1 only)
        r = tx._tx("acct_a", {"kind": "lock", "txid": txn.txid}, 5)
        assert r and r["ok"]
        # a plain request against the locked group is refused
        import json

        resp = submit("acct_a", "99", 5)
        assert resp is not None and not json.loads(resp).get("ok")
        assert json.loads(resp)["locked_by"] == txn.txid
        for m in c.managers:
            assert m.app.totals.get("acct_a", 0) == 0
        # release; plain requests flow again
        tx._tx("acct_a", {"kind": "unlock", "txid": txn.txid}, 5)
        resp = submit("acct_a", "3", 5)
        assert resp is not None
        c.run(4)
        assert c.managers[0].app.totals.get("acct_a") == 3
    finally:
        c.close()


def test_abort_releases_acquired_locks():
    c = make_cluster()
    try:
        submit = submitter(c)
        tx = DistTransactor(submit, lock_timeout_s=2)
        # a rival transaction holds acct_b, so ours cannot lock it
        rival = Transaction([("acct_b", "0")])
        assert tx._tx("acct_b", {"kind": "lock", "txid": rival.txid}, 5)["ok"]
        out = tx.execute(
            Transaction([("acct_a", "2"), ("acct_b", "4")]), timeout=3
        )
        assert not out["committed"] and "lock" in out["aborted"]
        c.run(4)
        # acct_a's lock (acquired first) was released by the abort
        for m in c.managers:
            assert "acct_a" not in m.app.locks
            assert m.app.totals.get("acct_a", 0) == 0
            assert m.app.totals.get("acct_b", 0) == 0
    finally:
        c.close()
