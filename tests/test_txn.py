"""Distributed transactions (``txn/``: sorted 2PC-over-Paxos, the
``DistTransactor.java`` capability made real): every 2PC transition is a
replicated request, commits apply staged ops atomically, aborts discard
them (staged-until-decision — NO participant is ever mutated by a
transaction that did not commit), late prepares hit the resolved-ring
fence, retryable refusals stay out of the exactly-once response cache,
and crash recovery re-derives the whole transaction plane from the
journal (commit re-drive AND presumed abort)."""

import json

from gigapaxos_tpu.models.apps import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster
from gigapaxos_tpu.txn import (
    ABORTED,
    COMMITTED,
    TXN_COORD,
    DistTransactor,
    Transaction,
    Transactor,
    TxnApp,
    TxnResolver,
    tx_op,
    txc_op,
)

CFG = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


def make_cluster(**kw):
    c = ManagerCluster(CFG, lambda: TxnApp(StatefulAdderApp()), **kw)
    c.create(TXN_COORD)
    c.create("acct_a")
    c.create("acct_b")
    return c


_RID = [1 << 40]  # process-wide: two sync_send instances must not collide


def sync_send(c, entry=0):
    """Synchronous replicated submit: one request id per call (minted up
    front so retransmits dedup), retransmitted on a step cadence until
    the decided response arrives."""

    def send(name, value, rid=None, max_steps=600):
        _RID[0] += 1
        rid_ = _RID[0] if rid is None else rid
        box = []
        for attempt in range(max_steps):
            if attempt % 40 == 0:
                c.managers[entry].propose(
                    name, value, request_id=rid_,
                    callback=lambda r, resp: box.append(resp),
                )
            if box:
                return json.loads(box[-1])
            c.step_all()
        raise AssertionError(f"no decision for {name}:{value[:40]}")

    return send


def async_submit(c, entry=0):
    def submit(name, value, rid, cb):
        c.managers[entry].propose(name, value, request_id=rid, callback=cb)

    return submit


def transactor(c, **kw):
    return Transactor(async_submit(c), lambda: c.step_all(), **kw)


# ---------------------------------------------------------------------------
# the happy path + the reference-named alias
# ---------------------------------------------------------------------------


def test_transaction_commits_across_groups():
    c = make_cluster()
    try:
        assert DistTransactor is Transactor  # the stub name, now real
        out = transactor(c).run(
            Transaction([("acct_a", "5"), ("acct_b", "7")])
        )
        assert out["committed"] and out["outcome"] == COMMITTED, out
        c.run(6)
        for m in c.managers:
            assert m.app.totals.get("acct_a") == 5
            assert m.app.totals.get("acct_b") == 7
            assert m.app.locks == {} and m.app.staged == {}
        # the coordinator record was ended; the outcome still answers
        r = sync_send(c)(TXN_COORD, txc_op("outcome", out["txid"]))
        assert r["outcome"] == COMMITTED
    finally:
        c.close()


def test_locked_group_refuses_plain_requests_until_release():
    c = make_cluster()
    try:
        send = sync_send(c)
        txid = "txlockhold"
        r = send("acct_a", tx_op("prepare", txid, vals=["1"]))
        assert r["ok"], r
        # a plain request against the locked group is refused retryably
        resp = send("acct_a", "99")
        assert not resp["ok"] and resp["locked_by"] == txid and resp["retry"]
        for m in c.managers:
            assert m.app.totals.get("acct_a", 0) == 0
        # abort releases the lock; plain requests flow again
        assert send("acct_a", tx_op("abort", txid))["ok"]
        assert send("acct_a", "3")  # decided
        c.run(4)
        assert c.managers[0].app.totals.get("acct_a") == 3
    finally:
        c.close()


# ---------------------------------------------------------------------------
# staged-until-decision: abort leaves NO participant mutated
# ---------------------------------------------------------------------------


def test_abort_mid_protocol_leaves_participants_unmutated():
    """The old stub's no-undo hole, closed: prepare STAGES ops without
    applying them, so an abort after a partial prepare round leaves every
    participant byte-identical — on every replica."""
    c = make_cluster()
    try:
        send = sync_send(c)
        txid = "txabortarm"
        r = send(TXN_COORD, txc_op(
            "begin", txid, names=["acct_a", "acct_b"],
            ops=[["acct_a", "5"], ["acct_b", "7"]], t=0.0,
        ))
        assert r["ok"]
        assert send("acct_a", tx_op("prepare", txid, vals=["5"]))["ok"]
        c.run(4)
        for m in c.managers:  # staged + locked, NOT applied
            assert m.app.locks.get("acct_a") == txid
            assert m.app.staged["acct_a"][0] == txid
            assert m.app.totals.get("acct_a", 0) == 0
        # global abort: decide, drive to BOTH names, end
        assert send(TXN_COORD, txc_op(
            "decide", txid, outcome=ABORTED))["outcome"] == ABORTED
        assert send("acct_a", tx_op("abort", txid))["ok"]
        assert send("acct_b", tx_op("abort", txid))["ok"]
        assert send(TXN_COORD, txc_op("end", txid))["outcome"] == ABORTED
        c.run(4)
        for m in c.managers:
            assert m.app.totals.get("acct_a", 0) == 0
            assert m.app.totals.get("acct_b", 0) == 0
            assert m.app.locks == {} and m.app.staged == {}
        # the late-prepare fence: a straggling prepare retransmit decided
        # AFTER the abort must refuse, not re-lock
        r = send("acct_b", tx_op("prepare", txid, vals=["7"]))
        assert not r["ok"] and r["resolved"] == ABORTED
        for m in c.managers:
            assert m.app.locks == {}
    finally:
        c.close()


def test_prepare_timeout_aborts_and_releases_sorted_prefix():
    c = make_cluster()
    try:
        send = sync_send(c)
        # a rival holds acct_b (second in sorted lock order)
        rival = "txrival"
        assert send("acct_b", tx_op("prepare", rival, vals=["0"]))["ok"]
        out = transactor(c, prepare_timeout_s=1.0).run(
            Transaction([("acct_a", "2"), ("acct_b", "4")])
        )
        assert not out["committed"] and "timeout" in out["aborted"], out
        c.run(4)
        for m in c.managers:
            # acct_a's lock (the acquired prefix) was released; nothing
            # was applied anywhere
            assert "acct_a" not in m.app.locks
            assert m.app.totals.get("acct_a", 0) == 0
            assert m.app.totals.get("acct_b", 0) == 0
            # the rival still holds its lock — only OUR prefix rolled back
            assert m.app.locks.get("acct_b") == rival
    finally:
        c.close()


def test_lock_wait_retries_until_rival_releases():
    """Same-rid retransmit IS the lock-wait retry: the refusal is left
    uncached, so the identical request id re-executes after release."""
    c = make_cluster()
    try:
        send = sync_send(c)
        rival = "txslow"
        assert send("acct_a", tx_op("prepare", rival, vals=["0"]))["ok"]
        steps = [0]
        from gigapaxos_tpu.txn import TxnDriver

        d = TxnDriver(
            Transaction([("acct_a", "3")]), async_submit(c), TXN_COORD,
            lambda: steps[0] * 0.05, prepare_timeout_s=60.0,
        )

        def pump(n):
            for _ in range(n):
                if d.poll() is not None:
                    return
                c.step_all()
                steps[0] += 1

        pump(60)
        assert d.poll() is None  # still waiting on the rival's lock
        assert send("acct_a", tx_op("abort", rival))["ok"]
        pump(800)
        out = d.poll()
        assert out is not None and out["committed"], out
        c.run(4)
        for m in c.managers:
            assert m.app.totals.get("acct_a") == 3
            assert m.app.locks == {}
    finally:
        c.close()


def test_retryable_refusal_is_not_cached():
    """A refusal sets ``request.txn_retry`` and stays OUT of the response
    cache, so the SAME request id executes after the lock clears — and
    exactly once (the post-execute retransmit answers from cache)."""
    c = make_cluster()
    try:
        send = sync_send(c)
        rival = "txholder"
        assert send("acct_a", tx_op("prepare", rival, vals=["0"]))["ok"]
        rid = 0x5EED5EED
        r = send("acct_a", "9", rid=rid)
        assert not r["ok"] and r["retry"]
        assert send("acct_a", tx_op("abort", rival))["ok"]
        # same rid again: executes now (a cached refusal would bounce it)
        r = send("acct_a", "9", rid=rid)
        assert r == 9, r  # the adder's response is the new total
        c.run(4)
        assert c.managers[0].app.totals.get("acct_a") == 9
        # and a THIRD retransmit dedups — no double apply
        r = send("acct_a", "9", rid=rid)
        c.run(4)
        for m in c.managers:
            assert m.app.totals.get("acct_a") == 9
    finally:
        c.close()


# ---------------------------------------------------------------------------
# crash recovery: the whole transaction plane replays from the journal
# ---------------------------------------------------------------------------


def _resolver_for(c, presume_abort_s=5.0):
    steps = [0]

    def clock():
        return steps[0] * 0.05

    res = TxnResolver(
        async_submit(c), TXN_COORD, clock,
        resolve_period_s=0.2, presume_abort_s=presume_abort_s,
        retransmit_s=0.2,
    )

    def pump(max_steps=4000):
        for _ in range(max_steps):
            res.poll()
            c.step_all()
            steps[0] += 1
            if res.scans >= 3 and res.idle():
                return
        raise AssertionError(
            f"resolver never drained: live={res.live_records} "
            f"jobs={sorted(res._jobs)}"
        )

    return res, pump


def test_coordinator_crash_commit_arm_recovers_from_journal(tmp_path):
    """Driver dies between decide(committed) and the outcome drive; every
    member crash-restarts; journal replay rebuilds locks + the decided
    record and the resolver re-drives the commit to a single global
    outcome."""
    dirs = [str(tmp_path / f"n{r}") for r in range(3)]
    c = make_cluster(log_dirs=dirs, checkpoint_every=4)
    try:
        send = sync_send(c)
        txid = "txcommitarm"
        assert send(TXN_COORD, txc_op(
            "begin", txid, names=["acct_a", "acct_b"],
            ops=[["acct_a", "5"], ["acct_b", "7"]], t=0.0,
        ))["ok"]
        assert send("acct_a", tx_op("prepare", txid, vals=["5"]))["ok"]
        assert send("acct_b", tx_op("prepare", txid, vals=["7"]))["ok"]
        assert send(TXN_COORD, txc_op("prepared", txid))["ok"]
        assert send(TXN_COORD, txc_op(
            "decide", txid, outcome=COMMITTED))["outcome"] == COMMITTED
        c.run(4)
        # ---- the driver dies HERE; the whole cluster crash-restarts ----
        for rid in range(3):
            c.restart(rid)
        for m in c.managers:  # replay rebuilt the transaction plane
            assert m.app.locks.get("acct_a") == txid
            assert m.app.locks.get("acct_b") == txid
            assert m.app.records[TXN_COORD][txid]["state"] == COMMITTED
            assert m.app.totals.get("acct_a", 0) == 0  # NOT yet applied
        res, pump = _resolver_for(c)
        pump()
        assert res.resolved_count == 1
        for m in c.managers:
            assert m.app.totals.get("acct_a") == 5
            assert m.app.totals.get("acct_b") == 7
            assert m.app.locks == {} and m.app.staged == {}
        assert sync_send(c)(
            TXN_COORD, txc_op("outcome", txid))["outcome"] == COMMITTED
    finally:
        c.close()


def test_coordinator_crash_presumed_abort_arm(tmp_path):
    """Driver dies mid-prepare (one lock taken, nothing decided); after
    restart the resolver presumes abort past the horizon, releases the
    lock, fences the in-flight prepare, and no participant is mutated."""
    dirs = [str(tmp_path / f"n{r}") for r in range(3)]
    c = make_cluster(log_dirs=dirs, checkpoint_every=4)
    try:
        send = sync_send(c)
        txid = "txdoubtarm"
        assert send(TXN_COORD, txc_op(
            "begin", txid, names=["acct_a", "acct_b"],
            ops=[["acct_a", "5"], ["acct_b", "7"]], t=0.0,
        ))["ok"]
        assert send("acct_a", tx_op("prepare", txid, vals=["5"]))["ok"]
        c.run(4)
        # ---- driver dies; cluster crash-restarts -----------------------
        for rid in range(3):
            c.restart(rid)
        for m in c.managers:
            assert m.app.locks.get("acct_a") == txid
            assert m.app.records[TXN_COORD][txid]["state"] == "begun"
        res, pump = _resolver_for(c, presume_abort_s=0.5)
        pump()
        assert res.resolved_count == 1
        for m in c.managers:
            assert m.app.totals.get("acct_a", 0) == 0
            assert m.app.totals.get("acct_b", 0) == 0
            assert m.app.locks == {} and m.app.staged == {}
        send = sync_send(c)
        assert send(TXN_COORD,
                    txc_op("outcome", txid))["outcome"] == ABORTED
        # the fence holds for the dead driver's straggling prepare
        r = send("acct_b", tx_op("prepare", txid, vals=["7"]))
        assert not r["ok"] and r["resolved"] == ABORTED
    finally:
        c.close()


# ---------------------------------------------------------------------------
# the chaos family, smoke-sized (the full campaign lives in test_chaos)
# ---------------------------------------------------------------------------


def test_txn_soak_smoke():
    from gigapaxos_tpu.testing.chaos import run_txn_soak

    r = run_txn_soak(11, rounds=120, settle_budget_s=300.0)
    assert r["txns"] >= 1 and r["committed"] >= 1, r
