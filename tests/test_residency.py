"""Pause/residency: the 1M-idle-groups memory story (ref:
``PaxosManager.java:2264-2392,2786-2881`` — Deactivator sweep, pause to
disk, message-triggered unpause).  TPU re-design: rows must stay aligned
across replicas, so pause/resume is RC-coordinated — pause frees the row
on every active; a touch reactivates at a freshly probed row through the
start-epoch machinery, same epoch."""

import numpy as np
import pytest

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import RCState
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


def make_cluster(n_rows=16, **kw):
    ar_cfg = EngineConfig(n_groups=n_rows, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    return ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp, **kw)


def create(c, name, max_steps=120):
    c.client_request("create_service", {"name": name, "actives": [0, 1, 2]})
    ack = c.wait_for("create_ack", max_steps=max_steps)
    assert ack and ack["ok"], (name, ack)
    return ack


def run_requests(c, name, values, entry=0, max_steps=80):
    done = {}
    for v in values:
        c.ars.managers[entry].propose(
            name, v, callback=lambda rid, r: done.setdefault(rid, r)
        )
    for _ in range(max_steps):
        if len(done) == len(values):
            return done
        c.step()
    raise AssertionError(f"{len(done)}/{len(values)} executed for {name}")


def pause(c, name, max_steps=80):
    """Drive a pause to PAUSED via the suggest path."""
    rec0 = c.reconfigurators[0].rc_app.get_record(name)
    c.active_replicas[0].send(
        ("RC", 0), "suggest_pause",
        {"name": name, "epoch": rec0.epoch, "from": 0},
    )
    for _ in range(max_steps):
        c.step()
        rec = c.reconfigurators[0].rc_app.get_record(name)
        if rec is not None and rec.state is RCState.PAUSED:
            return
    raise AssertionError(
        f"pause of {name} did not complete: "
        f"{c.reconfigurators[0].rc_app.get_record(name)}"
    )


def reactivate(c, name, max_steps=120):
    """Touch via request_actives until the record is READY again."""
    for _ in range(max_steps):
        c.client_request("request_actives", {"name": name})
        c.step()
        rec = c.reconfigurators[0].rc_app.get_record(name)
        if rec is not None and rec.state is RCState.READY and rec.row >= 0:
            c.drain_client()
            return rec
        c.drain_client()
    raise AssertionError(f"reactivation of {name} wedged")


def test_pause_frees_rows_and_reactivation_preserves_state():
    c = make_cluster()
    try:
        create(c, "svc")
        run_requests(c, "svc", [f"r{i}" for i in range(6)])
        h_before = c.ars.managers[0].app.state["svc"]
        n_before = c.ars.managers[0].app.n_executed["svc"]
        old_row = c.ars.managers[0].names["svc"]

        pause(c, "svc")
        for m in c.ars.managers:
            assert m.names.get("svc") is None, "row not freed"
            assert ("svc", 0) in m.paused
        rec = reactivate(c, "svc")
        assert rec.epoch == 0, "resume must not bump the epoch"
        # run more requests; the hash chain continues from pre-pause state
        run_requests(c, "svc", ["after1", "after2"], entry=1, max_steps=160)
        a0 = c.ars.managers[0].app
        assert a0.n_executed["svc"] == n_before + 2
        for m in c.ars.managers[1:]:
            assert m.app.state["svc"] == a0.state["svc"]
        assert a0.state["svc"] != h_before  # chain advanced, not reset
    finally:
        c.close()


def test_paging_beyond_row_capacity():
    """More names than engine rows, served by paging idle ones out (the
    VERDICT item-4 'row capacity < #names' criterion).  4 rows; 3 resident
    names + 2 paused names = 5 > 4."""
    c = make_cluster(n_rows=4)
    try:
        for n in ("a", "b", "c"):
            create(c, n)
            run_requests(c, n, [f"{n}0", f"{n}1"])
        pause(c, "a")
        pause(c, "b")
        # two rows free now: two more names fit
        for n in ("d", "e"):
            create(c, n, max_steps=200)
            run_requests(c, n, [f"{n}0"], max_steps=160)
        # 5 names exist on 4 rows; touch a paused one — it pages back in
        reactivate(c, "a")
        run_requests(c, "a", ["a2"], max_steps=160)
        a0 = c.ars.managers[0].app
        assert a0.n_executed["a"] == 3  # 2 pre-pause + 1 post-resume
    finally:
        c.close()


def test_pause_survives_restart(tmp_path):
    """A paused group's snapshot is durable: restart every node, then
    reactivate — state continues from the pre-pause chain."""
    ar_dirs = [str(tmp_path / f"ar{i}") for i in range(3)]
    rc_dirs = [str(tmp_path / f"rc{i}") for i in range(3)]
    c = make_cluster(ar_log_dirs=ar_dirs, rc_log_dirs=rc_dirs)
    try:
        create(c, "dur")
        run_requests(c, "dur", ["x", "y", "z"])
        h = c.ars.managers[0].app.state["dur"]
        pause(c, "dur")
    finally:
        c.close()

    c2 = make_cluster(ar_log_dirs=ar_dirs, rc_log_dirs=rc_dirs)
    try:
        for m in c2.ars.managers:
            assert ("dur", 0) in m.paused, "pause record lost on restart"
        rec = c2.reconfigurators[0].rc_app.get_record("dur")
        assert rec is not None and rec.state is RCState.PAUSED
        reactivate(c2, "dur")
        run_requests(c2, "dur", ["w"], max_steps=200)
        a0 = c2.ars.managers[0].app
        assert a0.n_executed["dur"] == 4
        assert a0.state["dur"] != h  # advanced from the restored chain
        for m in c2.ars.managers[1:]:
            assert m.app.state["dur"] == a0.state["dur"]
    finally:
        c2.close()


def test_rc_cluster_restart_mid_migration(tmp_path):
    """VERDICT r2 weak #4: restart the RECONFIGURATORS from their journals
    mid-migration — the paxos-replicated record recovers in WAIT_* state
    and the re-drive completes the stranded migration."""
    ar_dirs = [str(tmp_path / f"ar{i}") for i in range(3)]
    rc_dirs = [str(tmp_path / f"rc{i}") for i in range(3)]
    c = make_cluster(ar_log_dirs=ar_dirs, rc_log_dirs=rc_dirs)
    try:
        create(c, "mid")
        run_requests(c, "mid", ["a", "b"])
        # start a migration and cut the world down before it completes:
        # drop all start/stop traffic so the record strands in WAIT_*
        c.msg_filter = lambda dst, kind, body: kind not in (
            "stop_epoch", "start_epoch", "ack_stop_epoch", "ack_start_epoch",
        )
        c.client_request("reconfigure", {"name": "mid", "new_actives": [1, 2]})
        for _ in range(30):
            c.step()
        rec = c.reconfigurators[0].rc_app.get_record("mid")
        assert rec is not None and rec.state is not RCState.READY, (
            "migration unexpectedly completed before the restart"
        )
        stranded_state = rec.state
    finally:
        c.close()

    c2 = make_cluster(ar_log_dirs=ar_dirs, rc_log_dirs=rc_dirs)
    try:
        for rc in c2.reconfigurators:
            rc.REDRIVE_EVERY = 4
        rec = c2.reconfigurators[0].rc_app.get_record("mid")
        assert rec is not None, "record lost across RC restart"
        assert rec.state == stranded_state
        # the re-drive completes the migration without any client help
        import time as _time

        deadline = _time.time() + 60
        while _time.time() < deadline:
            c2.step()
            rec = c2.reconfigurators[0].rc_app.get_record("mid")
            if rec.state is RCState.READY and sorted(rec.actives) == [1, 2]:
                break
        assert rec.state is RCState.READY, rec.to_json()
        assert sorted(rec.actives) == [1, 2]
        run_requests(c2, "mid", ["after"], entry=1, max_steps=200)
        a1 = c2.ars.managers[1].app
        # a, b, the epoch-final stop, and the post-migration request
        assert a1.n_executed["mid"] == 4
    finally:
        c2.close()


def test_frozen_coordinator_heals_via_pause_probe():
    """Chaos-soak find: a pause round that aborts after SOME members
    froze leaves them holding pause records while the RC record stays
    READY.  A frozen ballot COORDINATOR wedges the whole group (it still
    answers pings and stays in the member mask, so no election fires).
    The frozen member's periodic pause-probe must get a committed resume
    from the RC and rejoin, unwedging consensus."""
    c = make_cluster()
    try:
        # no organic idle-pausing in this test (slow-compile wall time
        # can exceed the 60s sweep period and pause the group for real;
        # the healed member's own fast sweep would instantly re-pause it)
        for ar in c.active_replicas:
            ar.pause_option = False
        create(c, "fz")
        run_requests(c, "fz", ["w1", "w2"])
        m0 = c.ars.managers[0]
        row = m0.names["fz"]
        coord = m0.coordinator_of_row(row)
        epoch = m0.current_epoch("fz")
        # simulate the aborted pause round: ONLY the coordinator froze
        mc = c.ars.managers[coord]
        assert mc.pause_group("fz", epoch, force=True) == "ok"
        assert "fz" not in mc.names and ("fz", epoch) in mc.paused
        # fast probe cadence ONLY on the frozen member (a fast sweep on
        # the LIVE members would also fire genuine idle-pause suggestions
        # and pause the whole group mid-test)
        c.active_replicas[coord].deactivation_period_s = 0.1
        # traffic from a live member: wedged until the probe heals the
        # coordinator back in.  RETRANSMITTED like a real client — a
        # pre-heal forward to the frozen coordinator is consumed there
        # (not hosting -> dropped), and only the retransmit after the
        # heal can commit (exactly-once holds via the shared request id)
        entry = (coord + 1) % 3
        done = {}
        rid0 = 1 << 54
        import time as _t

        deadline = _t.time() + 60
        last_send = 0.0
        while _t.time() < deadline and not done:
            if _t.time() - last_send > 1.0:
                last_send = _t.time()
                c.ars.managers[entry].propose(
                    "fz", "x", request_id=rid0,
                    callback=lambda rid, r: done.setdefault(rid, r),
                )
            c.step()
        assert done, "frozen-coordinator group never unwedged"
        assert "fz" in mc.names  # the coordinator rejoined in place
        assert ("fz", epoch) not in mc.paused
    finally:
        c.close()


def test_orphaned_pause_record_dropped_by_probe():
    """A pause record for a DELETED name must be GC'd by the probe
    instead of lingering forever."""
    c = make_cluster()
    try:
        for ar in c.active_replicas:
            ar.pause_option = False
        create(c, "gone")
        run_requests(c, "gone", ["v"])
        epoch = c.ars.managers[0].current_epoch("gone")
        mc = c.ars.managers[1]
        assert mc.pause_group("gone", epoch, force=True) == "ok"
        # delete the name while member 1 holds a frozen copy
        c.client_request("delete_service", {"name": "gone"})
        ack = c.wait_for("delete_ack", max_steps=400)
        assert ack and ack.get("ok"), ack
        c.active_replicas[1].deactivation_period_s = 0.1
        import time as _t

        deadline = _t.time() + 60
        while _t.time() < deadline and ("gone", epoch) in mc.paused:
            c.step()
        assert ("gone", epoch) not in mc.paused, "orphan record never GC'd"
    finally:
        c.close()


def test_stranded_pending_row_heals_via_pending_probe():
    """Chaos-soak find: a member stranded at a LOSING probe row (its
    late-start retransmits expired) refuses every proposal forever, and
    the commit round that would heal it already completed on the other
    members.  The member's pending-row probe must get a committed resume
    at the winning row."""
    c = make_cluster()
    try:
        for ar in c.active_replicas:
            ar.pause_option = False
        create(c, "pr")
        run_requests(c, "pr", ["a", "b"])
        rec = c.reconfigurators[0].rc_app.get_record("pr")
        win_row = rec.row
        m1 = c.ars.managers[1]
        # strand member 1 at a losing pending row for the same epoch
        assert m1.kill("pr")
        lose_row = (win_row + 5) % 16
        assert m1.create_paxos_instance(
            "pr", [0, 1, 2], row=lose_row, version=rec.epoch, pending=True
        )
        assert m1.names["pr"] == lose_row and lose_row in m1.pending_rows
        c.active_replicas[1].deactivation_period_s = 0.1
        import time as _t

        deadline = _t.time() + 60
        while _t.time() < deadline and m1.names.get("pr") != win_row:
            c.step()
        assert m1.names.get("pr") == win_row, (
            "pending-row straggler never re-homed",
            m1.names.get("pr"), win_row,
        )
        assert win_row not in m1.pending_rows
        run_requests(c, "pr", ["c"], entry=1, max_steps=160)
    finally:
        c.close()


def test_stranded_winning_row_confirm_heals_via_pending_probe():
    """The sibling shape: the member holds the WINNING row but its
    epoch_commit confirm was lost and the commit round completed without
    needing it — the probe re-sends the confirm directly."""
    c = make_cluster()
    try:
        for ar in c.active_replicas:
            ar.pause_option = False
        create(c, "pw")
        run_requests(c, "pw", ["a"])
        rec = c.reconfigurators[0].rc_app.get_record("pw")
        m1 = c.ars.managers[1]
        row = m1.names["pw"]
        assert row == rec.row
        # simulate the lost confirm: re-gate the row
        m1.pending_rows.add(row)
        c.active_replicas[1].deactivation_period_s = 0.1
        import time as _t

        deadline = _t.time() + 60
        while _t.time() < deadline and row in m1.pending_rows:
            c.step()
        assert row not in m1.pending_rows, "lost confirm never re-sent"
    finally:
        c.close()
