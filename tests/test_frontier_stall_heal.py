"""A member stranded a SMALL distance behind the majority must still
heal when the decisions it needs no longer exist in any peer's window
(chaos-soak find: after the live majority pause+resume at frontier f,
their below-f decision lanes are gone — a member at f-1 could neither
learn the decision through the rings nor qualify for a checkpoint jump,
and diverged forever)."""

import numpy as np

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import DELIVER, DROP, ManagerCluster


def _isolate(R, dead):
    d = np.full((R, R), DELIVER)
    d[dead, :] = DROP
    d[:, dead] = DROP
    return d


def test_small_gap_straggler_heals_after_majority_resume():
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, HashChainApp)
    c.create("svc", members=[0, 1, 2])
    row = c.managers[0].names["svc"]

    # commit TWO slots on the majority while member 2 is isolated
    dead = _isolate(3, 2)
    done = {}
    for v in ("x1", "x2"):
        c.managers[0].propose(
            "svc", v, callback=lambda r, resp: done.setdefault(r, resp)
        )
    for _ in range(40):
        if len(done) == 2:
            break
        c.step_all(delivery=dead)
    assert len(done) == 2

    # the live majority pause + resume in place: their window remnants
    # (>= frontier) survive, but the decided slots BELOW the frontier
    # leave every ring — nothing can serve them lane-wise anymore
    epoch = c.managers[0].current_epoch("svc")
    for m in (c.managers[0], c.managers[1]):
        assert m.pause_group("svc", epoch, force=True) == "ok"
        assert m.resume_group("svc", epoch, [0, 1, 2], row, pending=False)
    c.blobs = [m.blob() for m in c.managers]

    # reconnect member 2: it sits 2 slots behind (< W=8, < jump horizon);
    # the frontier-stall heal must pull it up to the majority frontier
    for i in range(400):
        c.step_all()
        if int(np.asarray(c.managers[2].state.exec_slot)[row]) >= 2 and \
                c.managers[2].app.state.get("svc") == \
                c.managers[0].app.state.get("svc"):
            break
    h2 = c.managers[2].app.state.get("svc")
    h0 = c.managers[0].app.state.get("svc")
    assert h0 is not None and h2 == h0, (
        "small-gap straggler never healed",
        int(np.asarray(c.managers[2].state.exec_slot)[row]), h2, h0,
    )
    # and new traffic keeps all three in agreement
    done2 = {}
    c.managers[0].propose(
        "svc", "x3", callback=lambda r, resp: done2.setdefault(r, resp)
    )
    for _ in range(40):
        if done2:
            break
        c.step_all()
    assert done2
    for _ in range(40):
        states = {m.app.state.get("svc") for m in c.managers}
        if len(states) == 1:
            break
        c.step_all()
    assert len(states) == 1, states
    c.close()


def test_majority_behind_single_ahead_member_heals():
    """The inverted shape (also chaos-found): TWO members blank-rejoin at
    frontier 0 while ONE resumed member sits at frontier 2 with no
    below-frontier lanes.  maj_exec equals the stragglers' own frontier,
    so a majority-based stall detector never fires — the detector must
    measure against the MAX known frontier (peer app-cursor gossip)."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, HashChainApp)
    c.create("svc", members=[0, 1, 2])
    row = c.managers[0].names["svc"]

    done = {}
    for v in ("x1", "x2"):
        c.managers[0].propose(
            "svc", v, callback=lambda r, resp: done.setdefault(r, resp)
        )
    for _ in range(40):
        if len(done) == 2:
            break
        c.step_all()
    assert len(done) == 2
    epoch = c.managers[0].current_epoch("svc")

    # member 2: pause+resume in place (frontier 2, below-frontier lanes
    # gone).  members 0 and 1: blank re-join at frontier 0 (the commit-
    # heal shape) — now the MAJORITY is behind the lone resumed member.
    assert c.managers[2].pause_group("svc", epoch, force=True) == "ok"
    assert c.managers[2].resume_group("svc", epoch, [0, 1, 2], row,
                                      pending=False)
    for r in (0, 1):
        m = c.managers[r]
        assert m.kill("svc")
        assert m.create_paxos_instance("svc", [0, 1, 2], row=row,
                                       version=epoch)
    c.blobs = [m.blob() for m in c.managers]

    import numpy as np

    for _ in range(400):
        c.step_all()
        if all(
            int(np.asarray(m.state.exec_slot)[row]) >= 2 for m in c.managers
        ) and len({m.app.state.get("svc") for m in c.managers}) == 1:
            break
    states = {m.app.state.get("svc") for m in c.managers}
    assert len(states) == 1 and None not in states, (
        "majority-behind stragglers never healed",
        [int(np.asarray(m.state.exec_slot)[row]) for m in c.managers],
        states,
    )
    c.close()
