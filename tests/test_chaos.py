"""Seeded chaos soak over the reconfiguration plane: random creates,
migrations, pauses, reactivating touches, deletes, elastic membership
churn (remove/re-add actives), and app traffic under random
control-plane loss — then the system must settle to a consistent state
(the reference's randomized TESTReconfiguration* suites compressed into
one adversarial run).

End-state invariants:
  * every surviving record settles to READY/PAUSED (no wedged WAIT_*);
  * each READY record's actives actually host the name at one aligned
    row, and live members agree on the app state (RSM invariant);
  * deleted names are gone from every active and every RC;
  * paused names hold pause records on their actives.
"""

import random
import time

import pytest

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import RCState
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


import os as _os

_SEEDS = (
    [int(_os.environ["CHAOS_SEED"])] if _os.environ.get("CHAOS_SEED")
    else [1234, 7, 20260730]
)


@pytest.mark.parametrize("seed", _SEEDS)
def test_chaos_soak(seed, monkeypatch):
    from gigapaxos_tpu.reconfiguration import active_replica as ar_mod
    from gigapaxos_tpu.reconfiguration import reconfigurator as rc_mod

    # fast retransmits so recovery happens within the soak budget
    # (monkeypatch: the shared class attributes must restore afterwards)
    for cls in (rc_mod.StartEpochTask, rc_mod.StopEpochTask,
                rc_mod.DropEpochTask, rc_mod.EpochCommitTask,
                rc_mod.LateStartTask, rc_mod.PauseEpochTask,
                ar_mod.WaitEpochFinalState):
        monkeypatch.setattr(cls, "restart_period_s", 0.05)

    # exactly-once is only guaranteed within the response-cache TTL; on a
    # heavily loaded box a soak round can span minutes of wall time, and
    # TTL-expired dedup entries would let re-proposed duplicates re-execute
    # — a genuine (documented) semantics boundary, but not what this test
    # probes.  Pin the window far past any plausible run time.
    from gigapaxos_tpu.utils.config import Config

    Config.set("RESPONSE_CACHE_TTL_S", "3600")

    rng = random.Random(seed)
    ar_cfg = EngineConfig(n_groups=24, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        for rc in c.reconfigurators:
            rc.REDRIVE_EVERY = 4
        names = [f"n{i}" for i in range(6)]
        deleted = set()
        # 20% control-plane loss throughout the soak
        c.msg_filter = lambda dst, kind, body: rng.random() > 0.2

        for nm in names:
            c.client_request("create_service", {"name": nm, "actives": [0, 1, 2]})
        for _ in range(40):
            c.step()

        for round_no in range(60):
            op = rng.random()
            nm = rng.choice(names)
            if op < 0.35:  # traffic
                entry = rng.randrange(4)
                c.ars.managers[entry].propose(nm, f"r{round_no}")
            elif op < 0.55:  # migrate to a random 3-set
                target = rng.sample(range(4), 3)
                c.client_request(
                    "reconfigure", {"name": nm, "new_actives": target}
                )
            elif op < 0.7:  # pause suggestion
                rec = c.reconfigurators[0].rc_app.get_record(nm)
                if rec is not None and not rec.deleted:
                    c.active_replicas[0].send(
                        ("RC", rng.randrange(3)), "suggest_pause",
                        {"name": nm, "epoch": rec.epoch, "from": 0},
                    )
            elif op < 0.85:  # touch (reactivates if paused)
                c.client_request("request_actives", {"name": nm})
            elif op < 0.92:  # elastic membership churn: remove, then re-add
                removed = getattr(c, "_chaos_removed", None)
                if removed is None:
                    c.client_request("remove_active", {"id": rng.randrange(4)})
                    c._chaos_removed = True
                else:
                    # re-add every node (idempotent) so capacity recovers
                    for nid in range(4):
                        c.client_request("add_active", {"id": nid})
                    c._chaos_removed = None
            elif nm not in deleted and len(deleted) < 2:  # delete (max 2)
                c.client_request("delete_service", {"name": nm})
                deleted.add(nm)
            c.step()
            c.drain_client()

        # lossless settle: every protocol round must be able to finish.
        # Budget generously in BOTH steps and wall time: under a loaded
        # box the first settle iterations can be eaten by cold jax
        # compiles for this test's engine shapes, not by the protocol.
        c.msg_filter = None
        # deadline-bound (not iteration-capped): under a loaded box the
        # time-gated protocol retransmits fire rarely relative to steps,
        # so a fixed iteration budget can exhaust long before the wall
        # budget the retransmit timers actually need
        deadline = time.time() + 420
        settled = False
        while not settled:
            if time.time() > deadline:
                break
            for _ in range(8):
                c.step()
            c.drain_client()
            recs = {
                nm: c.reconfigurators[0].rc_app.get_record(nm)
                for nm in names
            }
            settled = all(
                r is None or r.deleted
                or r.state in (RCState.READY, RCState.PAUSED)
                for r in recs.values()
            )
        assert settled, {
            nm: (r.to_json() if r else None) for nm, r in recs.items()
        }

        # record agreement across RCs
        for nm in names:
            views = [rc.rc_app.get_record(nm) for rc in c.reconfigurators]
            datas = [None if v is None else v.to_json() for v in views]
            assert all(d == datas[0] for d in datas), (nm, datas)

        for nm, rec in recs.items():
            if rec is None or rec.deleted:
                for m in c.ars.managers:
                    assert m.names.get(nm) is None, (nm, "lingers post-delete")
                continue
            if rec.state is RCState.PAUSED:
                held = [m for m in c.ars.managers
                        if (nm, rec.epoch) in m.paused]
                assert held, (nm, "paused with no pause records anywhere")
                continue
            # READY: actives host the name at ONE aligned row and agree.
            # POLLED: a member that missed its start is healed by the
            # commit round's re-drive (wall-timer based), which may still
            # be in flight the instant the record itself reads READY.
            # The record is re-read each iteration: the 60s deactivation
            # sweep can legitimately pause a name mid-poll.
            rows = set()
            for _ in range(600):
                rec = c.reconfigurators[0].rc_app.get_record(nm)
                if rec is None or rec.deleted or \
                        rec.state is not RCState.READY:
                    break  # paused/deleted mid-poll: nothing to align
                rows = {c.ars.managers[a].names.get(nm) for a in rec.actives}
                if rows == {rec.row}:
                    break
                c.step()
            else:
                rows = {c.ars.managers[a].names.get(nm) for a in rec.actives}
            if rec is None or rec.deleted or rec.state is not RCState.READY:
                continue
            assert rows == {rec.row}, (nm, rec.row, rows)
            # a laggard may still be catching up through payload pulls or
            # a checkpoint jump — poll until the RSM states converge (a
            # real wedge still fails after the budget; a member restored
            # at the very end of the soak can need several blocked-pull
            # rounds of 64 ticks each before its cursor unparks)
            states = set()
            for _ in range(800):
                states = {
                    c.ars.managers[a].app.state.get(nm) for a in rec.actives
                }
                if len(states) == 1:
                    break
                c.step()
            assert len(states) == 1, (nm, "RSM divergence", states)
    finally:
        c.close()
        Config.clear()
