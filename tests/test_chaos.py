"""Seeded chaos soak over the reconfiguration plane — see
:mod:`gigapaxos_tpu.testing.chaos` for the soak body and the end-state
invariants (settle, RC agreement, alignment, RSM + exactly-once audit).

Three layers, mirroring the reference's randomized TESTReconfiguration*
suites plus its ``Repeat``-rule / travis ×10 re-run hammering
(``travis_checks.sh``):

  * pinned regression seeds — past chaos finds stay found; these are
    the GREEN gate (deterministic schedules, must always pass);
  * time-budgeted FRESH-seed batches (plain, duplicate-retransmit, and
    a larger 5-replica shape) — different seeds every CI run.  These
    are a DISCOVERY mechanism: the soak's fault space still contains
    rare timing-dependent shapes (~1 in 30 heavy-shape seeds on a
    loaded box; see README "Robustness"), so by default a fresh-seed
    hit emits a LOUD warning carrying the reproduce seed instead of
    failing the run — every such seed is a work item, not a
    regression.  Set ``CHAOS_FRESH_STRICT=1`` (the offline sweeps'
    mode) to turn discovery hits into failures.
"""

import os
import time
import warnings

import pytest


def _fresh(seed: int, repro: str, fn=None, **kw) -> bool:
    """Run one discovery soak; returns False when the budgeted loop
    should stop early (strict mode raises instead)."""
    try:
        (fn or run_soak)(seed, **kw)
        return True
    except Exception as e:
        msg = (
            f"DISCOVERY: fresh-seed soak found a shape at seed={seed} "
            f"(reproduce: {repro}): {str(e)[:400]}"
        )
        if os.environ.get("CHAOS_FRESH_STRICT"):
            raise AssertionError(msg) from e
        warnings.warn(msg)
        return False

from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.chaos import run_soak, run_txn_soak

_SEEDS = (
    [int(os.environ["CHAOS_SEED"])] if os.environ.get("CHAOS_SEED")
    # 1280113 / 777063353: the r5 offline sweep's two liveness-wedge
    # shapes (a READY record with one member hosting nothing; a
    # WAIT_ACK_STOP migration that never settled) — pinned so the
    # shapes stay covered even though they no longer reproduce on HEAD
    else [1234, 7, 20260730, 1280113, 777063353]
)

# exactly-once breach shapes the r5 sweeps caught in the act (timing-
# sensitive: they fired under a 90-round/30%-loss soak on a loaded box;
# pinned at that shape so the schedules stay covered).  662625602: the
# PR-2 trace-root-caused unpaired-dedup-install breach (a member
# skip-executed slot 0 on a pre-existing cache entry its app state did
# not contain) — fixed by pairing every install with its state adoption
# in create_paxos_instance; pinned here as the trajectory guard, with
# test_unpaired_dedup_install_regression as the schedule-independent one
_BREACH_SEEDS = [991134624, 881578088, 881205895, 662625602]

# txn-family breach shapes from the r1 fresh-seed txn sweeps — all
# three are forced-pause (hibernate) wounds.  786083501 / 786384423:
# the pause snapshotted a non-quiescent row (app cursor behind the
# device frontier) and the restore reinstated the stranded cursor with
# the gap's decisions gone from every store — no heal detector fired
# because the gap sat under jump_horizon with nothing payload-blocked
# (fixed: resume parks such rows in _needs_state so the state pull +
# app_only adoption close the gap).  495514: a proposal admitted into
# the device ring before the pause was in neither the held queue nor
# the window remnants, so its surviving inflight entry parked every
# retransmit of that request id and poisoned forward-dedup of fresh
# peer proposals — the resolver's commit re-drive starved through 4k+
# retransmits (fixed: resume releases orphaned undecided vids).  Pinned
# so the hibernate-mid-traffic schedules stay covered
_TXN_BREACH_SEEDS = [786083501, 786384423, 495514]

# txn green pins: deterministic full-default schedules (kills,
# restarts, partitions, hibernates, in-doubt resolution) that must stay
# green
_TXN_SEEDS = (
    [int(os.environ["CHAOS_TXN_SEED"])]
    if os.environ.get("CHAOS_TXN_SEED") else [11, 1, 2]
)


@pytest.mark.parametrize("seed", _BREACH_SEEDS)
def test_chaos_breach_shapes(seed):
    run_soak(seed, rounds=90, loss=0.3)


@pytest.mark.parametrize("seed", _TXN_BREACH_SEEDS)
def test_txn_breach_shapes(seed):
    run_txn_soak(seed)


@pytest.mark.parametrize("seed", _TXN_SEEDS)
@pytest.mark.slow
def test_txn_soak_pinned(seed):
    run_txn_soak(seed)


def test_txn_fresh_seeds():
    """Budgeted fresh-seed discovery over the txn 2PC soak family —
    same DISCOVERY/strict convention as test_chaos_fresh_seeds."""
    budget = float(os.environ.get("CHAOS_TXN_BUDGET_S", "60"))
    base = (int(time.time()) + 104729) % 1_000_000_007
    deadline = time.time() + budget
    ran = 0
    while ran == 0 or time.time() < deadline:
        seed = base + ran * 7919
        if not _fresh(
            seed,
            f"CHAOS_TXN_SEED={seed} pytest "
            f"tests/test_chaos.py::test_txn_soak_pinned",
            fn=run_txn_soak,
        ):
            break
        ran += 1


def test_unpaired_dedup_install_regression():
    """Schedule-independent guard for the seed-662625602 family: dedup
    entries shipped WITH an epoch-state handoff must install IF AND ONLY
    IF the create adopts the state.  A failed (collision) or no-op
    (idempotent re-create) create that leaves the entries behind lets
    the member skip-execute decisions its app state does not contain."""
    from gigapaxos_tpu.manager import PaxosManager
    from gigapaxos_tpu.models import StatefulAdderApp
    from gigapaxos_tpu.ops.engine import EngineConfig as EC

    m = PaxosManager(
        0, StatefulAdderApp(),
        EC(n_groups=4, window=4, req_lanes=2, n_replicas=3),
    )
    dedup = {"123": [time.time(), "7", "svc"]}
    m.create_paxos_instance("other", [0, 1, 2], row=0)
    # collision: the create fails -> the entries must NOT appear
    with pytest.raises(RuntimeError):
        m.create_paxos_instance(
            "svc", [0, 1, 2], initial_state="5", version=1, row=0,
            dedup=dedup,
        )
    assert 123 not in m.response_cache
    # adoption: state restored -> the paired entries install
    assert m.create_paxos_instance(
        "svc", [0, 1, 2], initial_state="5", version=1, row=1, dedup=dedup
    )
    assert m.app.totals.get("svc") == 5
    assert m.response_cache[123][1] == "7"
    # idempotent re-create adopts nothing -> fresh entries must NOT ride
    assert m.create_paxos_instance(
        "svc", [0, 1, 2], initial_state="5", version=1, row=1,
        dedup={"456": [time.time(), "9", "svc"]},
    )
    assert 456 not in m.response_cache


@pytest.mark.parametrize("seed", _SEEDS)
def test_chaos_soak(seed):
    run_soak(seed)


def test_chaos_fresh_seeds():
    """Run as many fresh-seed soaks as the time budget allows (≥1; ~10+
    warm).  The seed stream derives from wall time — every CI invocation
    probes different shapes."""
    budget = float(os.environ.get("CHAOS_FRESH_BUDGET_S", "90"))
    base = int(time.time()) % 1_000_000_007
    deadline = time.time() + budget
    ran = 0
    while ran == 0 or time.time() < deadline:
        seed = base + ran * 7919
        if not _fresh(
            seed,
            f"CHAOS_SEED={seed} pytest tests/test_chaos.py::test_chaos_soak",
        ):
            break
        ran += 1


def test_chaos_duplicate_retransmits():
    """Fresh-seed soak with CLIENT-RETRANSMIT injection (dup_rate=0.3):
    a quarter of traffic rounds re-propose a past request id through a
    random entry — the direct stressor for dedup entries lost across
    blank-join/resume/state-pull handoffs (the r4 open-issue shape).  A
    member missing the entry re-executes the duplicate; the per-step
    probe catches the divergence at birth."""
    budget = float(os.environ.get("CHAOS_DUP_BUDGET_S", "60"))
    base = (int(time.time()) + 7919) % 1_000_000_007
    deadline = time.time() + budget
    ran = 0
    while ran == 0 or time.time() < deadline:
        seed = base + ran * 104729
        if not _fresh(
            seed, f"run_soak({seed}, dup_rate=0.3)", dup_rate=0.3
        ):
            break
        ran += 1


def test_chaos_traced_liveness_seeds():
    """Re-probe the r5 sweep's recorded WAIT_ACK_STOP/START liveness
    seeds at their heavy shape, now with per-request tracing wired into
    the soak (run_soak enables every member's RequestTracer): a hit's
    DISCOVERY warning carries the offending name's request timelines and
    the RCs' epoch-op timeline (``_name_diag``'s ``trace`` /
    ``rc_epoch_trace`` fields), so a wedge arrives root-causable instead
    of just red.  DISCOVERY convention, not a gate — the family is
    contention-dependent: the 2026-08-03 re-probe settled all four clean
    on an idle box, but the SAME probe under deliberate load hit two
    shapes whose embedded traces root-caused them (seeds 662625602 /
    661277166 — see README fault-model notes)."""
    budget = float(os.environ.get("CHAOS_TRACED_BUDGET_S", "40"))
    deadline = time.time() + budget
    for seed in (661118786, 661277166, 555688974, 662625602):
        if not _fresh(
            seed, f"run_soak({seed}, rounds=90, loss=0.3)",
            rounds=90, loss=0.3,
        ):
            break
        if time.time() > deadline:
            break


def test_chaos_large_shape():
    """One soak at a bigger deployment shape: more groups, wider window,
    5 replicas, more adversarial rounds."""
    seed = int(os.environ.get("CHAOS_LARGE_SEED", str(int(time.time()))))
    _fresh(
        seed, f"CHAOS_LARGE_SEED={seed}",
        rounds=90,
        n_names=10,
        ar_cfg=EngineConfig(
            n_groups=64, window=16, req_lanes=4, n_replicas=5
        ),
        rc_cfg=EngineConfig(
            n_groups=8, window=8, req_lanes=4, n_replicas=3
        ),
    )
