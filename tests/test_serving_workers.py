"""Sharded serving workers (gigapaxos_tpu/serving/): shard assignment,
worker view derivation, the worker-sharded chaos-soak seed family
(exactly-once / handoff invariants across the shard boundary), and a
slow full-process socket smoke (supervisor + router + workers)."""

import os

import pytest

from gigapaxos_tpu.paxos_config import PC
from gigapaxos_tpu.serving import (
    apply_worker_view,
    partition_by_shard,
    shard_of_name,
    worker_address,
)
from gigapaxos_tpu.utils.config import Config

# the pinned seed family for the worker-sharded soak (chaos-soak
# conventions: compressed timers, step-driven, no wall-clock gates).
# Recorded 20260804 green at workers=2; a regression here means the
# shard boundary broke exactly-once/handoff, not that timing drifted.
SHARDED_SOAK_SEEDS = [20260804]


def test_shard_of_name_deterministic_and_spread():
    names = [f"svc{i}" for i in range(512)]
    a = [shard_of_name(nm, 4) for nm in names]
    b = [shard_of_name(nm, 4) for nm in names]
    assert a == b
    counts = [a.count(w) for w in range(4)]
    assert all(c > 64 for c in counts), counts  # no starved shard
    assert all(0 <= w < 4 for w in a)
    assert all(shard_of_name(nm, 1) == 0 for nm in names[:8])


def test_partition_by_shard_covers_everything():
    names = [f"p{i}" for i in range(40)]
    parts = partition_by_shard(names, 3)
    flat = [nm for sub in parts.values() for nm in sub]
    assert sorted(flat) == sorted(names)
    for w, sub in parts.items():
        assert all(shard_of_name(nm, 3) == w for nm in sub)


def test_apply_worker_view(monkeypatch):
    Config.clear()
    try:
        Config.set("active.AR0", "127.0.0.1:2000")
        Config.set("active.AR1", "10.0.0.2:2001")
        Config.set("reconfigurator.RC0", "127.0.0.1:3000")
        Config.set("ENGINE_ROWS", "1024")
        Config.set("SERVING_WORKERS", "4")
        off = Config.get_int(PC.SERVING_WORKER_PORT_OFFSET)
        apply_worker_view(2, 4)
        acts = Config.node_addresses("active")
        # every active shifts to ITS node's worker-2 port
        assert acts["AR0"] == ("127.0.0.1", 2000 + off + 2)
        assert acts["AR1"] == ("10.0.0.2", 2001 + off + 2)
        # RCs stay at base (unsharded; parent routes their AR traffic)
        assert Config.node_addresses("reconfigurator")["RC0"] == (
            "127.0.0.1", 3000
        )
        # rows split; recursion fuse blown
        assert Config.get_int(PC.ENGINE_ROWS) == 256
        assert Config.get_int(PC.SERVING_WORKERS) == 1
        assert worker_address(("h", 2000), 0) == ("h", 2000 + off)
    finally:
        Config.clear()


@pytest.mark.parametrize("seed", SHARDED_SOAK_SEEDS)
def test_sharded_soak_seed_family(seed):
    """SERVING_WORKERS=2 chaos family: the recorded seed's schedule
    (traffic + duplicate retransmits through rotating entries +
    migrations + pauses + deletes) runs across TWO worker-shard
    clusters; routing must stay deterministic, no name may leak across
    the boundary, and each shard passes the full settle/exactly-once
    audit (see run_sharded_soak)."""
    from gigapaxos_tpu.testing.chaos import run_sharded_soak

    out = run_sharded_soak(seed, workers=2, rounds=30, n_names=6)
    assert out["workers"] == 2


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_sharded_node_socket_smoke():
    """Full-process smoke: a sharded active (parent router + 2 worker
    processes) serves admin creates and client traffic on BOTH shards
    over real sockets, and the aggregated stats op reports per-worker
    phase + the live codec."""
    from gigapaxos_tpu.clients.paxos_client import PaxosClientAsync
    from gigapaxos_tpu.serving.router import ShardedActiveNode
    from gigapaxos_tpu.testing.ports import free_ports

    Config.clear()
    port = free_ports(1)[0]
    Config.set("active.AR0", f"127.0.0.1:{port}")
    Config.set("ENGINE_ROWS", "128")
    Config.set("SLOT_WINDOW", "8")
    Config.set("SERVING_WORKERS", "2")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    node = ShardedActiveNode("AR0", 2)
    node.start()
    client = PaxosClientAsync([("127.0.0.1", port)])
    try:
        names = [f"shard-smoke-{i}" for i in range(6)]
        spread = {shard_of_name(nm, 2) for nm in names}
        assert spread == {0, 1}, "names must land on both shards"
        for nm in names:
            assert client.create_paxos_instance(nm, [0], timeout=30), nm
        for i, nm in enumerate(names):
            assert client.send_request_sync(
                nm, f"v{i}", timeout=30
            ) is not None, nm
        st = client.admin_sync(0, {"op": "stats"}, timeout=20)
        assert st and st.get("ok"), st
        assert st["phase"] == "serving"
        assert st["serving"]["serving_workers"] == 2
        assert st["serving"]["worker_phases"] == ["serving", "serving"]
        assert len(st["workers"]) == 2
        assert st["serving"]["requests_routed"] >= 6
    finally:
        client.close()
        node.stop()
        Config.clear()
