"""EMULATE_UNREPLICATED / LAZY_PROPAGATION test modes
(``PaxosManager.java:1731-1778``): bypass or decouple consensus so a
capacity run can attribute cost between app+wire and agreement."""

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster
from gigapaxos_tpu.utils.config import Config


def cfg():
    return EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


def test_emulate_unreplicated_answers_without_consensus():
    Config.set("EMULATE_UNREPLICATED", "true")
    try:
        c = ManagerCluster(cfg(), HashChainApp)
        c.create("u", members=[0, 1, 2])
        done = {}
        for i in range(10):
            c.submit("u", f"v{i}", entry=0,
                     callback=lambda rid, r: done.setdefault(rid, r))
        # NO cluster ticks ran: responses must already be there
        assert len(done) == 10
        assert all(r is not None for r in done.values())
        assert c.managers[0].app.n_executed.get("u") == 10
        # peers never executed anything (consensus fully bypassed)
        assert c.managers[1].app.n_executed.get("u") is None
        # a retransmitted id answers from the cache without re-execution
        rid = next(iter(done))
        got = []
        c.managers[0].propose("u", "dup", request_id=rid,
                              callback=lambda r, resp: got.append(resp))
        assert got == [done[rid]]
        assert c.managers[0].app.n_executed.get("u") == 10
        c.close()
    finally:
        Config.clear()


def test_lazy_propagation_replies_early_but_still_replicates():
    Config.set("LAZY_PROPAGATION", "true")
    try:
        c = ManagerCluster(cfg(), HashChainApp)
        c.create("l", members=[0, 1, 2])
        done = {}
        for i in range(8):
            c.submit("l", f"v{i}", entry=0,
                     callback=lambda rid, r: done.setdefault(rid, r))
        assert len(done) == 8  # answered before any tick
        c.run(15)  # ...but the proposals still flow through the group
        counts = [m.app.n_executed.get("l") for m in c.managers]
        # peers executed every request through consensus; the entry's
        # early executions were deduped at commit time
        assert counts[0] == 8 and counts[1] == 8 and counts[2] == 8, counts
        c.close()
    finally:
        Config.clear()
