"""The epoch-commit round's truthful-ack + heal matrix, unit-level: an
active must ack ok only when it truly runs the current epoch at the
winning row; every other shape NACKs 'missing' and is healed by a
committed RESUME start (re-home / restore / empty join)."""

from typing import Dict, List, Tuple

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models.apps import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration.active_replica import ActiveReplica
from gigapaxos_tpu.reconfiguration.coordinator import PaxosReplicaCoordinator

CFG = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


def make_ar() -> Tuple[ActiveReplica, PaxosManager, List]:
    mgr = PaxosManager(0, StatefulAdderApp(), CFG)
    coord = PaxosReplicaCoordinator(mgr.app, mgr)
    sent = []
    ar = ActiveReplica(0, coord, lambda dst, kind, body: sent.append(
        (dst, kind, body)
    ))
    return ar, mgr, sent


def commit(ar, name, epoch, row) -> None:
    ar.handle_message("epoch_commit", {
        "name": name, "epoch": epoch, "row": row, "rc": ["RC", 0],
    })


def last_ack(sent) -> Dict:
    kind_bodies = [(k, b) for (_d, k, b) in sent if k == "ack_epoch_commit"]
    assert kind_bodies, "no ack sent"
    return kind_bodies[-1][1]


def test_ack_matrix():
    ar, mgr, sent = make_ar()

    # live at the winning row, pending -> ok + unpended
    mgr.create_paxos_instance("a", [0, 1, 2], row=3, pending=True)
    commit(ar, "a", 0, 3)
    assert last_ack(sent)["ok"] and 3 not in mgr.pending_rows

    # losing pending row (commit names row 5, we hold row 3) -> missing
    mgr.create_paxos_instance("b", [0, 1, 2], row=4, pending=True)
    commit(ar, "b", 0, 6)
    ack = last_ack(sent)
    assert not ack["ok"] and ack["reason"] == "missing"
    assert 4 in mgr.pending_rows  # the losing row must stay gated

    # not hosting at all -> missing
    commit(ar, "ghost", 0, 7)
    ack = last_ack(sent)
    assert not ack["ok"] and ack["reason"] == "missing"

    # paused -> missing (the member needs a resume, not a silent ok)
    mgr.create_paxos_instance("c", [0, 1, 2], row=5)
    assert mgr.pause_group("c", 0) == "ok"
    commit(ar, "c", 0, 5)
    ack = last_ack(sent)
    assert not ack["ok"] and ack["reason"] == "missing"

    # historic round for a superseded epoch -> ok (nothing to confirm)
    mgr.create_paxos_instance("d", [0, 1, 2], row=6)
    mgr.propose_stop("d")
    # simulate the stop having executed so the epoch can move on
    st = mgr.state
    mgr.state = st._replace(stopped=st.stopped.at[6].set(1))
    mgr.create_paxos_instance("d", [0, 1, 2], row=7, version=1)
    commit(ar, "d", 0, 6)
    assert last_ack(sent)["ok"]


def test_resume_heal_shapes():
    """The committed resume start heals each missing shape."""
    ar, mgr, sent = make_ar()

    def heal(name, epoch, row, initial=None):
        ar.handle_message("start_epoch", {
            "name": name, "epoch": epoch, "actives": [0, 1, 2], "row": row,
            "initial_state": initial, "prev_actives": [], "prev_epoch": -1,
            "resume": True, "committed": True, "rc": ["RC", 0],
        })

    # losing pending row -> re-homed to the winning row, unpended, queue kept
    mgr.create_paxos_instance("x", [0, 1, 2], row=1, pending=True)
    mgr.propose("x", "5")
    heal("x", 0, 2)
    assert mgr.names["x"] == 2 and 2 not in mgr.pending_rows
    assert mgr.queues.get(2), "held queue lost in the re-home"

    # paused -> restored at the new row with its state
    mgr.create_paxos_instance("y", [0, 1, 2], row=3)
    assert mgr.pause_group("y", 0) == "ok"
    heal("y", 0, 4)
    assert mgr.names["y"] == 4 and ("y", 0) not in mgr.paused

    # nothing at all -> empty join with the birth state
    heal("z", 0, 5, initial="7")
    assert mgr.names["z"] == 5
    assert mgr.app.totals.get("z") == 7  # StatefulAdder restore("7")

    # after healing, the commit retransmit acks ok
    for nm, row in (("x", 2), ("y", 4), ("z", 5)):
        commit(ar, nm, 0, row)
        assert last_ack(sent)["ok"], nm


def test_ready_audit_heals_post_commit_row_loss():
    """Chaos-sweep find: a member can lose its row AFTER the epoch's
    commit round completed (failed re-home / aborted pause) — it holds
    no pause record and no pending row, so no probe fires, and the old
    one-shot commit round never re-runs: the READY record keeps a
    member hosting NOTHING forever.  The slow READY audit re-runs the
    idempotent commit round; its missing-NACK drives the committed
    resume that re-joins the member."""
    import time as _t

    from gigapaxos_tpu.models.apps import HashChainApp
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster

    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        for rc in c.reconfigurators:
            rc.REDRIVE_EVERY = 4
            rc.ready_audit_period_s = 0.3  # fast audit for the test
        for ar in c.active_replicas:
            ar.pause_option = False
        c.client_request("create_service", {"name": "pl", "actives": [0, 1, 2]})
        ack = c.wait_for("create_ack", max_steps=200)
        assert ack and ack["ok"], ack
        done = {}
        c.ars.managers[0].propose(
            "pl", "w", callback=lambda rid, r: done.setdefault(rid, r)
        )
        for _ in range(80):
            if done:
                break
            c.step()
        assert done

        # post-commit row loss on member 2: no pause record, no pending
        # row — only the audit can see it
        m2 = c.ars.managers[2]
        assert m2.kill("pl")
        assert m2.names.get("pl") is None

        deadline = _t.time() + 60
        while _t.time() < deadline and m2.names.get("pl") is None:
            c.step()
        assert m2.names.get("pl") is not None, "audit never re-healed"
        # and the healed member converges to the group state
        deadline = _t.time() + 60
        while _t.time() < deadline:
            states = {m.app.state.get("pl") for m in c.ars.managers}
            if len(states) == 1:
                break
            c.step()
        assert len(states) == 1, states
    finally:
        c.close()
