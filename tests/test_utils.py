"""Unit tests for the config/flag system and profiler (mirrors the
reference's utils self-tests, e.g. ``utils/UtilTest.java``)."""

import enum

from gigapaxos_tpu.utils.config import Config, parse_properties
from gigapaxos_tpu.utils.profiler import DelayProfiler


class Flags(enum.Enum):
    ALPHA = 42
    BETA = True
    GAMMA = "hello"
    DELTA = 1.5


Config.register(Flags)


def test_defaults():
    assert Config.get(Flags.ALPHA) == 42
    assert Config.get_bool(Flags.BETA) is True
    assert Config.get_str(Flags.GAMMA) == "hello"
    assert Config.get_float(Flags.DELTA) == 1.5


def test_three_tiers(tmp_path):
    p = tmp_path / "t.properties"
    p.write_text("ALPHA=7\nBETA=false\n# comment\nactive.AR0=1.2.3.4:2000\n")
    Config.load_file(str(p))
    assert Config.get_int(Flags.ALPHA) == 7          # file beats default
    assert Config.get_bool(Flags.BETA) is False
    rest = Config.register_args(["ALPHA=9", "positional", "-x"])
    assert rest == ("positional", "-x")
    assert Config.get_int(Flags.ALPHA) == 9          # CLI beats file
    assert Config.node_addresses("active") == {"AR0": ("1.2.3.4", 2000)}


def test_parse_properties():
    props = parse_properties("a=1\nb: two\n!ignored\n\nc = 3 ")
    assert props == {"a": "1", "b": "two", "c": "3"}


def test_profiler():
    DelayProfiler.clear()
    DelayProfiler.update_mov_avg("lat", 1.0)
    DelayProfiler.update_count("reqs", 5)
    assert DelayProfiler.get("lat") == 1.0
    assert DelayProfiler.get("reqs") == 5
    assert "lat" in DelayProfiler.get_stats()
