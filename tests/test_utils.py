"""Unit tests for the config/flag system and profiler (mirrors the
reference's utils self-tests, e.g. ``utils/UtilTest.java``)."""

import enum

from gigapaxos_tpu.utils.config import Config, parse_properties
from gigapaxos_tpu.utils.profiler import DelayProfiler


class Flags(enum.Enum):
    ALPHA = 42
    BETA = True
    GAMMA = "hello"
    DELTA = 1.5


Config.register(Flags)


def test_defaults():
    assert Config.get(Flags.ALPHA) == 42
    assert Config.get_bool(Flags.BETA) is True
    assert Config.get_str(Flags.GAMMA) == "hello"
    assert Config.get_float(Flags.DELTA) == 1.5


def test_three_tiers(tmp_path):
    p = tmp_path / "t.properties"
    p.write_text("ALPHA=7\nBETA=false\n# comment\nactive.AR0=1.2.3.4:2000\n")
    Config.load_file(str(p))
    assert Config.get_int(Flags.ALPHA) == 7          # file beats default
    assert Config.get_bool(Flags.BETA) is False
    rest = Config.register_args(["ALPHA=9", "positional", "-x"])
    assert rest == ("positional", "-x")
    assert Config.get_int(Flags.ALPHA) == 9          # CLI beats file
    assert Config.node_addresses("active") == {"AR0": ("1.2.3.4", 2000)}


def test_parse_properties():
    props = parse_properties("a=1\nb: two\n!ignored\n\nc = 3 ")
    assert props == {"a": "1", "b": "two", "c": "3"}


def test_profiler():
    DelayProfiler.clear()
    DelayProfiler.update_mov_avg("lat", 1.0)
    DelayProfiler.update_count("reqs", 5)
    assert DelayProfiler.get("lat") == 1.0
    assert DelayProfiler.get("reqs") == 5
    assert "lat" in DelayProfiler.get_stats()


def test_flags_reach_the_framework(tmp_path):
    """VERDICT r2 item 5: the three-tier flag system must actually control
    the framework — a properties file changes the manager's checkpoint
    cadence/jump horizon and the failure detector's timeout."""
    from gigapaxos_tpu.failure_detection import FailureDetector
    from gigapaxos_tpu.manager import PaxosManager
    from gigapaxos_tpu.models import NoopPaxosApp
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.reconfiguration.rc_config import RC
    from gigapaxos_tpu.utils.config import Config

    props = tmp_path / "gigapaxos.properties"
    props.write_text(
        "CHECKPOINT_INTERVAL=7\n"
        "JUMP_HORIZON_WINDOWS=2\n"
        "FAILURE_DETECTION_TIMEOUT_S=1.5\n"
        "REQUEST_TIMEOUT_S=3.0\n"
        "RC.DEFAULT_NUM_REPLICAS=5\n"
    )
    Config.clear()
    try:
        Config.load_file(str(props))
        cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=3)
        m = PaxosManager(0, NoopPaxosApp(), cfg)
        assert m.checkpoint_every == 7
        assert m.jump_horizon == 2 * 8
        assert m.outstanding.timeout_s == 3.0
        fd = FailureDetector(0, [0, 1, 2])
        assert fd.timeout_s == 1.5
        assert Config.get_int(RC.DEFAULT_NUM_REPLICAS) == 5
        # CLI tier beats the file tier
        Config.register_args(["CHECKPOINT_INTERVAL=11"])
        m2 = PaxosManager(1, NoopPaxosApp(), cfg)
        assert m2.checkpoint_every == 11
    finally:
        Config.clear()


def test_no_flag_aliasing():
    """Plain enum.Enum treats equal-valued members as ALIASES of one
    member — so overriding BATCHING_ENABLED used to flip
    ENABLE_JOURNALING too (both default True): a capacity run with
    batching disabled silently lost its journal.  Every registered flag
    must be a distinct member with independent override behavior."""
    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.reconfiguration.rc_config import RC
    from gigapaxos_tpu.utils.config import Config, flag_default

    for enum_cls in (PC, RC):
        members = {name: m for name, m in enum_cls.__members__.items()}
        assert len(set(members.values())) == len(members), (
            "aliased flags in " + enum_cls.__name__
        )
    Config.clear()
    try:
        Config.set("BATCHING_ENABLED", "false")
        assert Config.get_bool(PC.ENABLE_JOURNALING) is True
        assert Config.get_bool(PC.PAUSE_OPTION) is True
        assert Config.get_bool(PC.BATCHING_ENABLED) is False
        Config.set("ENGINE_ROWS", "128")
        assert Config.get_int(PC.RESPONSE_CACHE_SIZE) == flag_default(
            PC.RESPONSE_CACHE_SIZE
        )
    finally:
        Config.clear()


def test_every_registered_flag_is_read_somewhere():
    """Flag hygiene (VERDICT r3 weak #3): a registered flag with no read
    site lies about a capability.  Every PC/RC member must be consumed
    by at least one source file outside its defining module (the
    reference consumes every PaxosConfig.PC flag somewhere,
    PaxosConfig.java:214-967)."""
    import pathlib

    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.reconfiguration.rc_config import RC

    pkg = pathlib.Path(__file__).parent.parent / "gigapaxos_tpu"
    sources: Dict[str, str] = {}
    for p in pkg.rglob("*.py"):
        sources[str(p)] = p.read_text(encoding="utf-8")
    unread = []
    for enum_cls, defining in ((PC, "paxos_config.py"),
                               (RC, "rc_config.py")):
        for member in enum_cls:
            token = f"{enum_cls.__name__}.{member.name}"
            if not any(
                token in text
                for path, text in sources.items()
                if not path.endswith(defining)
            ):
                unread.append(token)
    assert not unread, f"decorative flags with no read site: {unread}"


def test_diskmap_spills_and_restores(tmp_path):
    """DiskMap analog (DiskMap.java:97): cold entries page to disk and
    restore transparently; deletes reach spilled entries."""
    from gigapaxos_tpu.utils.diskmap import DiskMap

    dm = DiskMap(str(tmp_path / "dm"), capacity=8)
    for i in range(20):
        dm[("k", i)] = {"v": i}
    assert len(dm) == 20
    assert dm.n_in_memory <= 8 and dm.n_on_disk >= 12
    # every entry readable (spilled ones restore)
    for i in range(20):
        assert dm[("k", i)] == {"v": i}
    # delete reaches both tiers
    del dm[("k", 3)]
    assert ("k", 3) not in dm and len(dm) == 19
    # overwrite of a spilled key doesn't leave a stale file
    dm[("k", 5)] = {"v": 500}
    assert dm[("k", 5)] == {"v": 500}
    assert set(dm) == {("k", i) for i in range(20) if i != 3}


def test_rtt_redirector_prefers_fast_server():
    from gigapaxos_tpu.net.rtt import LatencyAwareRedirector

    rd = LatencyAwareRedirector()
    rd.PROBE_RATIO = 0.0  # deterministic for the test
    for _ in range(20):
        rd.record(0, 0.100)
        rd.record(1, 0.005)
        rd.record(2, 0.050)
    assert rd.pick([0, 1, 2]) == 1
    # unknown candidates get measured before exploitation settles
    assert rd.pick([0, 1, 7]) == 7


def test_rtt_redirector_seeding_and_deterministic_ties():
    """Cold-start fix: echo-probe seeds orient the FIRST pick, never
    overwrite traffic-learned estimates, and exact-RTT ties break
    deterministically (same measurements -> same pick, every client)."""
    from gigapaxos_tpu.net.rtt import LatencyAwareRedirector

    rd = LatencyAwareRedirector()
    rd.PROBE_RATIO = 0.0
    # probe seeds land before any traffic: first pick is oriented
    assert rd.seed(2, 0.003) and rd.seed(0, 0.050) and rd.seed(1, 0.020)
    assert rd.pick([0, 1, 2]) == 2
    # real traffic taught key 2 its true (slower) end-to-end number...
    for _ in range(50):
        rd.record(2, 0.200)
    # ...and a later probe round must NOT drag it back down
    assert rd.seed(2, 0.003) is False
    assert rd.pick([0, 1, 2]) == 1
    # exact ties break deterministically toward the stable-lowest key
    rd2 = LatencyAwareRedirector()
    rd2.PROBE_RATIO = 0.0
    for k in (3, 1, 2):
        rd2.seed(k, 0.010)
    assert all(rd2.pick([3, 1, 2]) == 1 for _ in range(10))
