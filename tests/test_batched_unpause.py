"""Batched unpause parity + admission-aware eviction ordering.

The density campaign's correctness pin: ``resume_group_batch`` (ONE
fused device install for N woken rows) must be bit-exact with the
per-name ``resume_group`` loop on EVERY engine leaf — including the
forced-pause shapes chaos finds #23/#24 exposed (a record captured with
the app lagging the engine frontier, and window remnants / held vids
riding the record).  Two managers are fed byte-identical histories, one
wakes per-name and one batched, and all 19 state leaves plus the host
bookkeeping must agree."""

import numpy as np
import pytest

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.utils.config import Config

NAMES = [f"par{i}" for i in range(8)]


def ticks(m, n=3):
    for _ in range(n):
        vec, _st = m.publish_snapshot()
        m.tick_host(np.stack([vec]), np.array([True]))


def _mk(tmp_path, tag, G=64, W=8):
    cfg = EngineConfig(n_groups=G, window=W, req_lanes=4, n_replicas=1)
    return PaxosManager(
        0, StatefulAdderApp(), cfg, log_dir=str(tmp_path / tag),
        checkpoint_every=10 ** 9, sync_journal=False,
    )


def _drive_and_sleep(m):
    """Identical history for both managers: varied decided traffic, two
    names left NON-QUIESCENT (requests still queued at pause — the
    forced-pause record carries them as held vids / window remnants),
    then one batched hibernate of everything."""
    m.create_paxos_batch(NAMES, [0])
    for rnd in range(3):
        for i, nm in enumerate(NAMES[: 6]):
            m.propose(nm, str(10 + rnd + i))
        ticks(m, 3)
    ticks(m, 4)
    # in-flight at pause: proposed, NOT ticked
    m.propose(NAMES[6], "777")
    m.propose(NAMES[7], "888")
    assert m.hibernate_batch(NAMES) == len(NAMES)
    assert len(m.names) == 0


def _leafdict(m):
    return {f: np.asarray(getattr(m.state, f))
            for f in m.state._fields}


def _assert_parity(m1, m2):
    l1, l2 = _leafdict(m1), _leafdict(m2)
    for f in l1:
        assert np.array_equal(l1[f], l2[f]), f"leaf {f} diverged"
    assert m1.names == m2.names
    assert m1.app.totals == m2.app.totals
    assert {r: list(q) for r, q in m1.queues.items() if q} == \
           {r: list(q) for r, q in m2.queues.items() if q}
    assert m1._needs_state == m2._needs_state
    assert np.array_equal(m1.app_exec_slot, m2.app_exec_slot)


def test_batched_resume_bit_exact_vs_sequential(tmp_path):
    m1 = _mk(tmp_path, "seq")
    m2 = _mk(tmp_path, "bat")
    try:
        _drive_and_sleep(m1)
        _drive_and_sleep(m2)
        _assert_parity(m1, m2)  # identical histories to start from

        for nm in NAMES:  # per-name loop: N device installs
            assert m1.restore(nm)
        res = m2.restore_batch(NAMES)  # ONE fused install
        assert res == len(NAMES)

        _assert_parity(m1, m2)  # bit-exact right after the wake
        ticks(m1, 6)  # held vids re-propose and decide identically
        ticks(m2, 6)
        _assert_parity(m1, m2)
        # the in-flight requests actually landed exactly once
        for nm, want in ((NAMES[6], 777), (NAMES[7], 888)):
            assert m1.app.totals.get(nm) == want
    finally:
        m1.close()
        m2.close()


def test_batched_resume_nonquiescent_record_parks_needs_state(tmp_path):
    """Chaos-find #23 shape: a forced-pause record whose ``app_exec``
    lags the engine frontier must park the row in ``_needs_state`` (the
    app cannot serve until a state pull catches it up) — identically on
    both wake paths."""
    m1 = _mk(tmp_path, "seq23")
    m2 = _mk(tmp_path, "bat23")
    try:
        for m in (m1, m2):
            m.create_paxos_batch(NAMES[:2], [0])
            for _ in range(3):
                m.propose(NAMES[0], "5")
                ticks(m, 3)
            row = m.names[NAMES[0]]
            # simulate the app lagging the frontier at pause time (the
            # #23 interleaving: forced pause raced the execute drain)
            m.app_exec_slot[row] = max(0, int(m.app_exec_slot[row]) - 2)
            assert m.pause_group(NAMES[0], 0, force=True) == "ok"
            assert m.pause_group(NAMES[1], 0, force=True) == "ok"
        assert m1.restore(NAMES[0]) and m1.restore(NAMES[1])
        assert m2.restore_batch(NAMES[:2]) == 2
        _assert_parity(m1, m2)
        assert m1.names[NAMES[0]] in m1._needs_state
        assert m2.names[NAMES[0]] in m2._needs_state
        assert m2.names[NAMES[1]] not in m2._needs_state
    finally:
        m1.close()
        m2.close()


def test_restore_batch_mixed_known_unknown(tmp_path):
    m = _mk(tmp_path, "mix")
    try:
        m.create_paxos_batch(NAMES[:4], [0])
        assert m.hibernate_batch(NAMES[:4]) == 4
        # unknown names and already-awake names don't poison the batch
        assert m.restore_batch([NAMES[0], "ghost", NAMES[1]]) == 2
        assert m.restore_batch([NAMES[0], NAMES[2]]) == 2  # 1 awake + 1
        assert set(m.names) == {NAMES[0], NAMES[1], NAMES[2]}
    finally:
        m.close()


def test_eviction_candidates_cold_first_heat_tiebreak(tmp_path):
    """Sweep order: oldest activity first, PR-18 group heat as the
    tiebreak; queued/pending/recently-resumed names never listed."""
    m = _mk(tmp_path, "evict")
    try:
        Config.set("PAUSE_EVICTION_HYSTERESIS_S", "3600")
        pool = ["cold", "warmish", "hot_old", "busy", "fresh", "flappy"]
        m.create_paxos_batch(pool, [0])
        # heat: hot_old sees real traffic, others stay cold
        for _ in range(4):
            m.propose("hot_old", "1")
            ticks(m, 3)
        m.pull_group_heat()  # drain the device accumulator into _heat_host
        now = __import__("time").time()
        for nm, age in (("cold", 500), ("warmish", 500),
                        ("hot_old", 500), ("busy", 500), ("fresh", 1)):
            m.row_activity[m.names[nm]] = now - age
        m.propose("busy", "9")  # queued admission: not idle by definition
        order = m.eviction_candidates(idle_s=60.0)
        listed = [nm for nm, _e in order]
        assert "busy" not in listed  # queued work
        assert "fresh" not in listed  # inside the idle cut
        # equal activity times: heat breaks the tie, coldest first
        assert listed.index("hot_old") > listed.index("cold")
        assert listed.index("hot_old") > listed.index("warmish")
        # limit takes the head of the sorted order, not an arbitrary set
        capped = m.eviction_candidates(idle_s=60.0, limit=2)
        assert [nm for nm, _e in capped] == listed[:2]

        # hysteresis: a just-resumed name is exempt from the next sweep
        assert m.hibernate("flappy")
        assert m.restore("flappy")
        m.row_activity[m.names["flappy"]] = now - 500
        assert "flappy" not in [
            nm for nm, _e in m.eviction_candidates(idle_s=60.0)
        ]
        Config.set("PAUSE_EVICTION_HYSTERESIS_S", "0.0")
        assert "flappy" in [
            nm for nm, _e in m.eviction_candidates(idle_s=60.0)
        ]
    finally:
        Config.clear()
        m.close()
