"""Ops-launcher smoke (slow): scripts/gp_server.py boots the 3AR+3RC
loopback scenario from its properties pair, probe.py completes a short
capacity pass attached to it, and stop tears everything down cleanly —
the ``bin/gpServer.sh start all`` / ``TESTPaxosClient`` loop, end to end
over real OS processes."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from gigapaxos_tpu.testing.ports import free_ports

REPO = Path(__file__).resolve().parent.parent


def _run(args, timeout):
    return subprocess.run(
        args, cwd=REPO, timeout=timeout, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


@pytest.mark.slow
def test_gp_server_start_probe_stop(tmp_path):
    # the committed scenario pins ports for operators; the test rewrites
    # them to free ephemerals so parallel CI runs can't collide
    scenario = (REPO / "scenarios/loopback_3ar_3rc.properties").read_text()
    ports = free_ports(6)
    for i, (old, new) in enumerate(zip(
        ("21000", "21001", "21002", "22000", "22001", "22002"),
        (str(p) for p in ports),
    )):
        scenario = scenario.replace(f":{old}", f":{new}")
    cfg = tmp_path / "smoke.properties"
    cfg.write_text(scenario)
    run_dir = tmp_path / "run"
    gp = [sys.executable, "scripts/gp_server.py",
          "--config", str(cfg), "--run-dir", str(run_dir)]
    try:
        r = _run(gp + ["start", "all"], timeout=180)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "up:" in r.stdout

        r = _run(gp + ["status", "all"], timeout=60)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert r.stdout.count(": up") == 6, r.stdout

        r = _run(
            [sys.executable, "probe.py", "--attach", str(cfg), "--cpu",
             "--groups", "2", "--clients", "2", "--max-rounds", "1",
             "--window-s", "1.0", "--init-load", "50"],
            timeout=300,
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        lines = [json.loads(ln) for ln in r.stdout.splitlines()
                 if ln.startswith("{")]
        seeded = next(
            ln for ln in lines if "echo_probe_seeded_actives" in ln
        )
        assert seeded["echo_probe_seeded_actives"] == 3
        summary = next(
            ln for ln in lines
            if ln.get("metric") == "system_capacity_requests_per_s"
        )
        assert summary["value"] > 0, lines
    finally:
        r = _run(gp + ["stop", "all"], timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert not list(run_dir.glob("*.pid")), "pidfiles leaked after stop"
    r = _run(gp + ["status", "all"], timeout=60)
    assert r.stdout.count(": down") == 6, r.stdout
