"""Large-checkpoint streaming (ref: ``LargeCheckpointer.java:43``,
``SQLReconfiguratorDB.CheckpointServer:1237``): a multi-MB app state
migrates between replica sets as paced chunk frames instead of one giant
frame, and the consensus/epoch planes stay responsive while it streams.
Also covers MAX_LOG_MESSAGE_SIZE enforcement at the send boundary."""

import threading
import time
from typing import Dict, Optional

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.interfaces.app import Replicable
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config

BIG = 8 * 1024 * 1024  # 8 MB app state


class BigStateApp(Replicable):
    """Counter app whose checkpoint pads to BIG bytes (the digits ride in
    front, so restore can recover the count and divergence is visible)."""

    def __init__(self):
        self.counts: Dict[str, int] = {}

    def execute(self, request, do_not_reply_to_client: bool = False) -> bool:
        name = request.get_service_name()
        self.counts[name] = self.counts.get(name, 0) + 1
        if hasattr(request, "response_value"):
            request.response_value = str(self.counts[name])
        return True

    def checkpoint(self, name: str) -> Optional[str]:
        head = f"{self.counts.get(name, 0)}:"
        return head + "x" * (BIG - len(head))

    def restore(self, name: str, state: Optional[str]) -> bool:
        if not state:
            self.counts.pop(name, None)
            return True
        self.counts[name] = int(state.split(":", 1)[0])
        return True

    def get_request(self, stringified: str):
        from gigapaxos_tpu.packets.paxos_packets import RequestPacket

        return RequestPacket(request_value=stringified)


@pytest.mark.timeout(300)
def test_big_state_migration_streams_without_stalling():
    ports = free_ports(8)
    Config.clear()
    for i in range(4):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
    for i in range(3):
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[4 + i]}")
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", BigStateApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(4)
    ] + [
        ReconfigurableNode(f"RC{i}", BigStateApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    client = ReconfigurableAppClient.from_properties()
    try:
        ack = client.create_name("big", actives=[0, 1, 2], timeout=30)
        assert ack and ack.get("ok"), ack
        ack = client.create_name("side", actives=[0, 1, 2], timeout=30)
        assert ack and ack.get("ok"), ack
        for _ in range(3):
            assert client.send_request_sync("big", "inc", timeout=15)
        assert client.send_request_sync("side", "warm", timeout=15)

        # side-channel liveness probe while the 8MB state streams
        side_lats = []
        stop_probe = threading.Event()

        def probe():
            while not stop_probe.is_set():
                t0 = time.time()
                r = client.send_request_sync("side", "p", timeout=20)
                if r is not None:
                    side_lats.append(time.time() - t0)
                time.sleep(0.1)

        th = threading.Thread(target=probe, daemon=True)
        th.start()

        # migrate [0,1,2] -> [1,2,3]: AR3 must fetch the 8MB final state
        ack = client.reconfigure("big", [1, 2, 3], timeout=120)
        assert ack and ack.get("ok"), ack
        # the new epoch serves requests with the carried-over count
        resp = client.send_request_sync("big", "inc", timeout=30)
        assert resp is not None and int(resp) >= 4, resp
        stop_probe.set()
        th.join(timeout=5)

        # the epoch plane stayed responsive during the stream: the side
        # group kept answering, and no single probe waited out a giant
        # frame (8MB at loopback is fast; the bar catches multi-second
        # head-of-line stalls)
        assert side_lats, "side probe never completed during migration"
        assert max(side_lats) < 5.0, max(side_lats)

        # count survived on the new set: AR3's replica restored 8MB state
        # (possibly via the needs_state pull if the commit-heal blank-
        # joined it before the streamed final state landed — poll for the
        # heal, not just row presence)
        m3 = nodes[3].servers[0].manager
        deadline = time.time() + 60
        while time.time() < deadline and m3.app.counts.get("big", 0) < 3:
            time.sleep(0.5)
        assert "big" in m3.names
        assert m3.app.counts.get("big", 0) >= 3
    finally:
        client.close()
        for n in nodes:
            n.stop()
        Config.clear()
