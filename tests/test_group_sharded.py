"""Group-sharded SPMD mode (the zero-collective scale-out shape):
parity pins against the single-chip vmap step, padding/edge-shard
behavior for a non-divisible G, the mesh-shape sweep, the runtime mesh
descriptor behind the ``stats`` admin op, the footprint probe's
``--sharded`` budget assert, and the driver's ``dryrun_multichip``
one-line JSON artifact (the previously-blind multichip smoke)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL, ballot_coord
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.parallel.mesh import (
    describe_state_mesh,
    make_group_mesh,
    pick_mesh_shape,
)
from gigapaxos_tpu.parallel.spmd import (
    build_replica_states,
    group_sharded_step,
    pad_group_states,
    padded_group_count,
    shard_group_inputs,
    single_chip_step,
    strip_group_pad,
)

ROOT = Path(__file__).resolve().parents[1]


def _parity_schedule(cfg):
    """A 4-step schedule that exercises requests, an election pulse, and
    a dropped peer — returns [(req, want, heard), ...] host arrays."""
    R, G, K = cfg.n_replicas, cfg.n_groups, cfg.req_lanes
    steps = []
    # step 0: live requests at two coordinator rows
    req = np.full((R, G, K), NULL, np.int32)
    req[0, 0, :2] = [5, 6]
    req[1, 1 % G, 0] = 7
    steps.append((req, np.zeros((R, G), bool), None))
    # step 1: quiet
    steps.append((np.full((R, G, K), NULL, np.int32),
                  np.zeros((R, G), bool), None))
    # step 2: election pulse (replica 1 runs for every group) under a
    # dropped peer (replica R-1 unheard) — carryover through both modes
    heard = np.ones((R, R), bool)
    heard[:, R - 1] = False
    want = np.zeros((R, G), bool)
    want[1, :] = True
    steps.append((np.full((R, G, K), NULL, np.int32), want, heard))
    # step 3: full delivery again, more requests at every row (only the
    # active coordinator admits)
    req = np.full((R, G, K), NULL, np.int32)
    req[:, :, 0] = 9
    steps.append((req, np.zeros((R, G), bool), None))
    return steps


def _assert_parity(cfg, n_devices):
    mesh = make_group_mesh(n_devices)
    Gp = padded_group_count(cfg.n_groups, n_devices)
    vm = single_chip_step(cfg)
    gs = group_sharded_step(cfg, mesh)

    states_v = build_replica_states(cfg)
    R, G, K = cfg.n_replicas, cfg.n_groups, cfg.req_lanes
    states_s, _r0, _w0 = shard_group_inputs(
        mesh, cfg, build_replica_states(cfg),
        np.full((R, G, K), NULL, np.int32), np.zeros((R, G), bool),
    )
    assert states_s.bal.shape == (R, Gp)

    for t, (req, want, heard) in enumerate(_parity_schedule(cfg)):
        states_v, out_v = vm(
            states_v, jnp.asarray(req), jnp.asarray(want),
            None if heard is None else jnp.asarray(heard),
        )
        req_p = np.concatenate(
            [req, np.full((R, Gp - G, K), NULL, np.int32)], axis=1
        )
        want_p = np.concatenate(
            [want, np.zeros((R, Gp - G), bool)], axis=1
        )
        states_s, out_s = gs(
            states_s, jnp.asarray(req_p), jnp.asarray(want_p),
            None if heard is None else jnp.asarray(heard),
        )
        # EVERY state leaf and EVERY StepOutputs field, every step
        su = strip_group_pad(states_s, G)
        ou = strip_group_pad(out_s, G)
        for name in states_v._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(states_v, name)),
                np.asarray(getattr(su, name)),
                err_msg=f"state.{name} @ step {t}",
            )
        for name in out_v._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(out_v, name)),
                np.asarray(getattr(ou, name)),
                err_msg=f"out.{name} @ step {t}",
            )
    # commits actually flowed (the schedule is live, not a no-op parity)
    assert np.asarray(states_v.exec_slot).max() >= 1
    return states_s


def test_group_sharded_parity_8dev():
    """Bit-identical to single_chip_step over 4 steps on the 8-device
    virtual mesh — every leaf, every output field, every step."""
    cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    states = _assert_parity(cfg, 8)
    sh = states.bal.sharding
    assert len(sh.device_set) == 8  # really spread over the mesh


def test_group_sharded_parity_nondivisible_g():
    """G=13 over 8 shards: the padded edge shard must not perturb any
    real group, and the inert pad tail stays bit-frozen."""
    cfg = EngineConfig(n_groups=13, window=8, req_lanes=4, n_replicas=3)
    states = _assert_parity(cfg, 8)
    Gp = padded_group_count(13, 8)
    assert Gp == 16
    tail = np.asarray(states.member_mask)[:, 13:]
    assert (tail == 0).all()
    assert (np.asarray(states.exec_slot)[:, 13:] == 0).all()


def test_group_sharded_commits_end_to_end():
    """Drive coordinator-routed traffic for 10 steps: commits flow on
    every group through the sharded step (not just parity on quiet
    schedules)."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=2, n_replicas=3)
    mesh = make_group_mesh(8)
    fn = group_sharded_step(cfg, mesh)
    R, G, K = 3, 8, 2
    states, _r, _w = shard_group_inputs(
        mesh, cfg, build_replica_states(cfg),
        np.full((R, G, K), NULL, np.int32), np.zeros((R, G), bool),
    )
    vid = 1
    for _ in range(10):
        req = np.full((R, G, K), NULL, np.int32)
        coord = ballot_coord(np.asarray(states.bal)[0])
        for g in range(G):
            req[int(coord[g]), g, 0] = vid
            vid += 1
        states, out = fn(
            states, jnp.asarray(req), jnp.zeros((R, G), bool)
        )
    fr = np.asarray(states.exec_slot)
    assert (fr == fr[0]).all() and fr.min() >= 6
    h = np.asarray(states.app_hash)
    assert (h == h[0]).all() and (h[0] != 0).all()


def test_pick_mesh_shape_sweep():
    """n_devices in {1, 2, 3, 4, 8}: replica axis prefers 3, then 2,
    then 1; group shards take the rest."""
    expect = {1: (1, 1), 2: (1, 2), 3: (1, 3), 4: (2, 2), 8: (4, 2)}
    for n, want in expect.items():
        assert pick_mesh_shape(n) == want, n


def test_padded_group_count():
    assert padded_group_count(16, 8) == 16
    assert padded_group_count(13, 8) == 16
    assert padded_group_count(1, 8) == 8
    assert padded_group_count(17, 8) == 24
    assert padded_group_count(7, 1) == 7


def test_pad_group_states_inert_tail():
    cfg = EngineConfig(n_groups=5, window=8, req_lanes=2, n_replicas=3)
    padded = pad_group_states(cfg, build_replica_states(cfg), 4)
    assert padded.bal.shape == (3, 8)
    assert (np.asarray(padded.member_mask)[:, 5:] == 0).all()
    assert (np.asarray(padded.bal)[:, 5:] == NULL).all()


def test_make_group_mesh_shapes():
    for n in (1, 2, 4, 8):
        mesh = make_group_mesh(n)
        assert dict(mesh.shape) == {"g": n}
    with pytest.raises(ValueError):
        make_group_mesh(len(jax.devices()) + 1)


def test_describe_state_mesh():
    """The stats-op mesh descriptor: sharded array reports the mesh,
    a plain single-device array reports n_devices=1, host data reports
    residency 0 (never raises)."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=2, n_replicas=3)
    mesh = make_group_mesh(8)
    states, _r, _w = shard_group_inputs(
        mesh, cfg, build_replica_states(cfg),
        np.full((3, 8, 2), NULL, np.int32), np.zeros((3, 8), bool),
    )
    d = describe_state_mesh(states.bal)
    assert d["n_devices"] == 8
    assert d["shape"] == {"g": 8}
    assert d["platform"] == "cpu"

    single = describe_state_mesh(jnp.zeros((4,), jnp.int32))
    assert single["n_devices"] == 1 and single["platform"] == "cpu"

    host = describe_state_mesh(np.zeros((4,), np.int32))
    assert host["platform"] == "host" and host["n_devices"] == 0


_SUBPROC_PARITY = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
sys.path.insert(0, {tests!r})
assert len(jax.devices()) >= 8
from gigapaxos_tpu.ops.engine import EngineConfig
from test_group_sharded import _assert_parity
for G in (16, 13):
    _assert_parity(
        EngineConfig(n_groups=G, window=8, req_lanes=4, n_replicas=3), 8
    )
print("PARITY_OK")
"""


@pytest.mark.slow
def test_group_sharded_parity_subprocess():
    """The same parity pin from a pristine interpreter with the explicit
    XLA_FLAGS virtual-mesh bring-up (the ``__graft_entry__`` pattern) —
    proves the mode needs nothing from the test harness' conftest.
    Slow-marked: tier-1 already pins the identical parity in-process on
    the same 8-virtual-device mesh; this re-proves the bring-up path,
    and a fresh interpreter + two step compiles is ~1 min of the tier-1
    budget on a 1-core box."""
    code = _SUBPROC_PARITY.format(
        root=str(ROOT), tests=str(ROOT / "tests")
    )
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "PARITY_OK" in out.stdout


@pytest.mark.slow
def test_dryrun_multichip_prints_artifact_json():
    """The driver's multichip smoke must RECORD a measurement: one JSON
    line with n_devices, both mesh shapes, step wall time, and dec/s
    (the MULTICHIP_r0*.json ``tail`` was empty for five rounds).
    Slow-marked: the driver runs dryrun_multichip itself every round
    (the artifact IS the gate); this spawns a fresh interpreter + three
    mesh compiles."""
    code = (
        f"import sys; sys.path.insert(0, {str(ROOT)!r}); "
        "import __graft_entry__ as ge; ge.dryrun_multichip(8)"
    )
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert lines, out.stdout
    rec = json.loads(lines[-1])
    assert rec["n_devices"] == 8
    assert rec["mesh"] == {"g": 4, "r": 2}
    assert rec["step_wall_s"] > 0
    assert rec["dec_per_s"] > 0
    gs = rec["group_sharded"]
    assert gs["mesh"] == {"g": 8}
    assert gs["n_groups"] == 35 and gs["padded_groups"] == 40
    assert gs["dec_per_s"] > 0


def test_bench_capacity_cpu_skip_leaves_evidence_untouched():
    """The capacity run's CPU path: prints the {platform, G, no_oom,
    dec_per_s, per_device_hbm_bytes} shape but must NOT touch
    TPU_EVIDENCE.json (never overwrite chip numbers with host
    stand-ins).  CAPACITY_G is overridden small so the full bench loop
    runs in test time; the G=2M shape itself is a bench-invocation
    concern, not a codepath difference."""
    ev = ROOT / "TPU_EVIDENCE.json"
    before = ev.read_bytes()
    code = (
        f"import os, sys; sys.path.insert(0, {str(ROOT)!r}); "
        "os.environ['JAX_PLATFORMS'] = 'cpu'; "
        "os.environ['BENCH_G'] = '4096'; "
        "os.environ['BENCH_W'] = '8'; os.environ['BENCH_K'] = '4'; "
        "import bench; bench.CAPACITY_G = 4096; sys.exit(bench.main())"
    )
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    cap = rec["capacity"]
    assert cap["no_oom"] is True
    assert cap["platform"] == "cpu"
    assert cap["G"] == 4096
    assert cap["dec_per_s"] > 0
    assert "per_device_hbm_bytes" in cap
    assert ev.read_bytes() == before, "CPU run must not touch evidence"


def test_footprint_probe_sharded_budget():
    """--sharded N: per-device blob bytes per hosted group must sit AT
    the compact budget (16 + 16W) for every shard count — sharding adds
    zero per-group exchange overhead."""
    for n in (1, 2, 8):
        out = subprocess.run(
            [sys.executable, str(ROOT / "scripts" / "footprint_probe.py"),
             "--sharded", str(n)],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        rec = json.loads(out.stdout.strip())
        sh = rec["sharded"]
        assert sh["n_shards"] == n
        assert sh["within_budget"] is True
        assert sh["compact_budget_bytes_per_group"] == 528  # W=32
        assert sh["per_device_blob_bytes_per_group"] <= 528
        assert sh["groups_per_device"] * n == sh["padded_groups"]
        # per-device peak: the single-chip model at the LOCAL group count
        # (HBM = bytes_per_group x G / n_shards — the capacity lever)
        if n == 8:
            full = subprocess.run(
                [sys.executable,
                 str(ROOT / "scripts" / "footprint_probe.py")],
                capture_output=True, text=True, timeout=120,
            )
            peak_full = json.loads(full.stdout.strip())[
                "single_chip_peak_estimate_bytes"]
            assert sh["per_device_peak_estimate_bytes"] < peak_full / 6
