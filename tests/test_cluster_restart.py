"""Whole-cluster crash/restart over the DEPLOYABLE path — the
reference's ``testPaxos(testRecovery=true)`` shape (run the integration,
restart every node from its durable state, keep going;
``TESTPaxosMain.java:154``): 6 journaled nodes stop cold and fresh
processes-worth of node objects must recover the RC records, the name
map, and the app state, then serve new traffic that CONTINUES the
pre-restart history."""

import time

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config


def boot(tmp_path, ports):
    Config.clear()
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg, log_dir=str(tmp_path / f"AR{i}"))
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", HashChainApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg, log_dir=str(tmp_path / f"RC{i}"))
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    return nodes


@pytest.mark.timeout(300)
def test_full_cluster_restart_resumes_service(tmp_path):
    ports = free_ports(6)
    nodes = boot(tmp_path, ports)
    client = ReconfigurableAppClient.from_properties()
    try:
        ack = client.create_name("dur", actives=[0, 1, 2], timeout=60)
        assert ack and ack.get("ok"), ack
        pre = None
        for i in range(12):
            pre = client.send_request_sync("dur", f"v{i}", timeout=20)
            assert pre is not None, i
    finally:
        client.close()
        for n in nodes:
            n.stop()

    # cold restart: brand-new node objects on the same dirs and ports
    time.sleep(0.5)
    nodes = boot(tmp_path, ports)
    client = ReconfigurableAppClient.from_properties()
    try:
        # resolution works from the recovered RC records (no re-create)
        acts = None
        deadline = time.time() + 60
        while time.time() < deadline and not acts:
            acts = client.request_actives("dur", timeout=5, force=True)
        assert acts and sorted(acts) == [0, 1, 2], acts
        # new traffic CONTINUES the recovered hash chain: the response
        # must equal the locally recomputed 13-step chain (v0..v11 then
        # "after"), so a truncated or corrupted replay fails loudly
        post = client.send_request_sync("dur", "after", timeout=30)
        assert post is not None
        expect = HashChainApp()
        for v in [f"v{i}" for i in range(12)] + ["after"]:
            req = expect.get_request(v)
            req.paxos_id = "dur"
            expect.execute(req)
        assert post == req.response_value, (
            "recovered chain does not continue the pre-restart history",
            post, req.response_value,
        )
        # and the replicas agree on the continued state
        deadline = time.time() + 30
        states = set()
        while time.time() < deadline:
            states = {
                n.servers[0].manager.app.state.get("dur") for n in nodes[:3]
            }
            if len(states) == 1 and None not in states:
                break
            time.sleep(0.5)
        assert len(states) == 1 and None not in states, states
    finally:
        client.close()
        for n in nodes:
            n.stop()
        Config.clear()
