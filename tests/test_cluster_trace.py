"""Cluster-wide observability: cross-node trace propagation + merge
(client → entry AR → coordinator forward → decide on all replicas →
execute → response, ONE causal timeline out of N nodes' trace_dump
rings), the black-box flight recorder (divergence / mid-load dumps),
and the TLS HTTP stats surface."""

import json
import os
import ssl
import subprocess
import time
import urllib.request

import pytest

from gigapaxos_tpu.clients import PaxosClientAsync
from gigapaxos_tpu.models import StatefulAdderApp
from gigapaxos_tpu.net.node_config import NodeConfig
from gigapaxos_tpu.obs import tracemerge
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.server import PaxosServer
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config


def _boot_cluster(n, groups=8):
    cfg = EngineConfig(n_groups=groups, window=8, req_lanes=4,
                       n_replicas=n)
    ports = free_ports(n)
    nc = NodeConfig({i: ("127.0.0.1", p) for i, p in enumerate(ports)})
    servers = [
        PaxosServer(i, nc, StatefulAdderApp(), cfg, tick_interval=0.01)
        for i in range(n)
    ]
    for s in servers:
        s.start()
    return servers, ports


# ---- the acceptance path: one traced request, one merged timeline -----
@pytest.mark.timeout(180)
def test_traced_request_merges_into_one_causal_timeline(monkeypatch):
    """A sampled request (GP_TRACE_SAMPLE=1) through a live loopback
    cluster, entering at a NON-coordinator (so the coordinator-forward
    hop is on the path): every node's trace_dump merges into ONE
    timeline sharing the trace id, containing every hop — recv/propose/
    forward-out at the entry, forward-in/propose at the coordinator,
    decide+execute on ALL replicas, respond-flush at the entry — with
    non-negative per-hop latencies.  Servers run with tracing DISABLED:
    the origin's sampling decision alone makes every hop record."""
    monkeypatch.setenv("GP_TRACE_SAMPLE", "1")
    servers, ports = _boot_cluster(3)
    client = PaxosClientAsync([("127.0.0.1", p) for p in ports])
    try:
        assert all(not s.tracer.enabled for s in servers)
        assert client.create_paxos_instance("tr0", [0, 1, 2], timeout=30)
        m0 = servers[0].manager
        row = m0.names["tr0"]
        coord = m0.coordinator_of_row(row)
        entry = (coord + 1) % 3
        resp = client.send_request_sync("tr0", "7", timeout=30,
                                        server=entry)
        assert resp == "7"

        # all replicas executed (the decide/execute fan-out is complete)
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(s.manager.app.totals.get("tr0") == 7 for s in servers):
                break
            time.sleep(0.1)

        # fan trace_dump over the cluster and merge (scripts/gp_trace.py
        # does exactly this against a deployed cluster)
        dumps = {}
        for i in range(3):
            r = client.admin_sync(i, {"op": "trace_dump"}, timeout=10)
            assert r and r["ok"], r
            assert r["enabled"] is False  # forced recording, not GP_TRACE
            dumps[r["node"]] = r["events"]
        traces = tracemerge.merge_node_dumps(dumps)
        # the create-plane admin ops aren't traced; exactly the sampled
        # request's timeline comes back
        assert len(traces) == 1, [t["keys"] for t in traces]
        tr = traces[0]

        # ONE shared trace id stamped at the client
        assert tr["trace_id"], tr
        tids = {e["detail"]["tid"] for e in tr["events"]
                if "tid" in e["detail"]}
        assert tids == {tr["trace_id"]}

        by = {}
        for e in tr["events"]:
            by.setdefault(e["event"], set()).add(e["node"])
        # entry hops
        assert entry in by.get("recv", set())
        assert entry in by.get("propose", set())
        assert entry in by.get("forward-out", set())
        assert entry in by.get("respond-flush", set())
        # coordinator hops (hop counter bumped across the forward)
        assert coord in by.get("forward-in", set())
        assert coord in by.get("propose", set())
        fwd_in = [e for e in tr["events"] if e["event"] == "forward-in"]
        assert fwd_in and all(
            e["detail"].get("hop", 0) >= 1 for e in fwd_in
        )
        # decide + execute landed on EVERY replica, with the decided
        # slot's (group, slot, ballot) attribution
        assert by.get("decide") == {0, 1, 2}, by
        assert by.get("execute") == {0, 1, 2}, by
        for e in tr["events"]:
            if e["event"] == "decide":
                assert e["detail"]["row"] == row
                assert "slot" in e["detail"] and "ballot" in e["detail"]
        # causal order with non-negative per-hop latencies
        assert tr["events"][0]["event"] == "recv"
        assert all(h["dt_s"] >= 0.0 for h in tr["hops"])
        assert tr["total_s"] >= 0.0
        # the per-hop phase attribution names the forward + consensus legs
        phases = {h["phase"] for h in tr["hops"]}
        assert "forward-wire" in phases
        assert "ingress" in phases
        # ... and the response carried the context back to the client
        # (S/JSON trace field round trip) — rendering smoke-check too
        text = tracemerge.render_trace(tr)
        assert "forward-wire" in text and "@ node" in text
    finally:
        client.close()
        for s in servers:
            s.stop()


# ---- trace_dump + flightdump against a node mid-load ------------------
@pytest.mark.timeout(180)
def test_trace_dump_and_flightdump_mid_load(tmp_path, monkeypatch):
    """The two new admin ops answer against a node under live traffic:
    trace_dump streams the ring (name-filtered), flightdump writes the
    engine-history rings to disk and reports the path."""
    monkeypatch.setenv("GP_TRACE_SAMPLE", "1")
    Config.set("FLIGHT_DIR", str(tmp_path / "flight"))
    servers, ports = _boot_cluster(2)
    client = PaxosClientAsync([("127.0.0.1", p) for p in ports])
    try:
        assert client.create_paxos_instance("mid", [0, 1], timeout=30)
        # live load: a stream of requests in flight while we dump
        for i in range(40):
            client.send_request("mid", "1")
        assert client.send_request_sync("mid", "1", timeout=30) is not None

        r = client.admin_sync(0, {"op": "trace_dump", "name": "mid"},
                              timeout=10)
        assert r and r["ok"] and r["node"] == 0
        assert r["events"], "mid-load trace_dump returned an empty ring"
        assert any(
            ev[1] == "propose"
            for evs in r["events"].values() for ev in evs
        )

        f = client.admin_sync(0, {"op": "flightdump"}, timeout=10)
        assert f and f["ok"], f
        assert f["steps"] > 0 and f["decided"] > 0, f
        assert os.path.isfile(f["path"]), f
        doc = json.loads(open(f["path"]).read())
        assert doc["node"] == 0 and doc["reason"] == "admin"
        assert doc["steps"] and doc["decided"]
        # decided entries are (group, slot, ballot, vid) with the slot
        # sequence for the loaded group
        row = servers[0].manager.names["mid"]
        mine = [d for d in doc["decided"] if d[0] == row]
        assert mine, doc["decided"][:5]
        assert all(len(d) == 4 for d in mine)
    finally:
        client.close()
        for s in servers:
            s.stop()


# ---- divergence → black box on disk -----------------------------------
@pytest.mark.timeout(300)
def test_soak_divergence_dumps_flight_recorder(tmp_path):
    """Force an exactly-once divergence in the stepped chaos harness and
    assert the flight recorder lands on disk, attached to the failure,
    containing the divergent group's last-K decided entries."""
    from gigapaxos_tpu.models.apps import HashChainApp
    from gigapaxos_tpu.testing.chaos import (
        SoakDivergence,
        probe_exactly_once,
    )
    from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster

    Config.set("FLIGHT_DIR", str(tmp_path / "flight"))
    ar_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        for m in c.ars.managers:
            m.tracer.enabled = True
        c.client_request(
            "create_service", {"name": "dv", "actives": [0, 1, 2]}
        )
        for _ in range(40):
            c.step()
        rid = (1 << 55) + 777
        c.ars.managers[0].propose("dv", "v0", request_id=rid)
        deadline = time.time() + 120
        while time.time() < deadline:
            c.step()
            if all(m.app.state.get("dv") for m in c.ars.managers):
                break
        assert c.ars.managers[0].app.state.get("dv"), "request never ran"
        # wait until every member is caught up (app cursor == frontier)
        # so the probe actually compares them
        row = c.ars.managers[0].names["dv"]
        while time.time() < deadline:
            if all(
                int(m.app_exec_slot[m.names["dv"]])
                == int(m._np("exec_slot")[m.names["dv"]]) > 0
                for m in c.ars.managers
            ):
                break
            c.step()
        # the breach: one member's app state silently diverges
        c.ars.managers[0].app.state["dv"] = "CORRUPTED"
        with pytest.raises(SoakDivergence) as ei:
            probe_exactly_once(c, ["dv"])
        paths = ei.value.diag.get("flight_dumps")
        assert paths, "divergence carried no flight dumps"
        # the dumps are the failure message too (post-mortemable from
        # the artifact alone)
        assert "flight_dumps" in str(ei.value)
        found_divergent_group = False
        for p in paths:
            assert os.path.isfile(p)
            doc = json.loads(open(p).read())
            decided = [d for d in doc["decided"] if d[0] == row]
            if decided:
                found_divergent_group = True
                # (group, slot, ballot, vid): the decided sequence the
                # post-mortem diffs across members
                assert all(len(d) == 4 for d in decided)
                slots = [d[1] for d in decided]
                assert slots == sorted(slots)
        assert found_divergent_group, (paths, row)
    finally:
        c.close()


# ---- RC + AR HTTP stats surface under TLS -----------------------------
def _make_cert(tmp_path):
    key = tmp_path / "key.pem"
    crt = tmp_path / "cert.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        capture_output=True,
    )
    if r.returncode != 0:
        pytest.skip("openssl unavailable for cert generation")
    return str(key), str(crt)


@pytest.mark.timeout(300)
def test_rc_http_stats_and_metrics_under_tls(tmp_path):
    """The RC and AR HTTP fronts serve /stats + /metrics over HTTPS when
    the cluster runs a TLS mode (previously only plaintext was
    exercised): the node cert is presented and verified, and a plaintext
    client is rejected."""
    from gigapaxos_tpu.models import NoopPaxosApp
    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode

    key, crt = _make_cert(tmp_path)
    ports = free_ports(2)
    Config.set("active.AR0", f"127.0.0.1:{ports[0]}")
    Config.set("reconfigurator.RC0", f"127.0.0.1:{ports[1]}")
    # fast stats cadence so the process gauges refresh within the poll
    Config.set("STATS_LOG_PERIOD_S", "0.5")
    Config.set("SSL_MODE", "SERVER_AUTH")
    Config.set("SSL_KEY_FILE", key)
    Config.set("SSL_CERT_FILE", crt)
    Config.set("SSL_CA_FILE", crt)
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=1)
    nodes = [
        ReconfigurableNode("AR0", NoopPaxosApp, ar_cfg=cfg, rc_cfg=cfg,
                           tick_interval=0.01),
        ReconfigurableNode("RC0", NoopPaxosApp, ar_cfg=cfg, rc_cfg=cfg,
                           tick_interval=0.01),
    ]
    for n in nodes:
        n.start()
    try:
        ctx = ssl.create_default_context(cafile=crt)
        ctx.check_hostname = False  # node identity = address book
        off = Config.get_int(PC.HTTP_PORT_OFFSET)
        for port, want in (
            (ports[1] + off, "placement"),   # RC front
            (ports[0] + off, "stats"),       # AR front
        ):
            with urllib.request.urlopen(
                f"https://127.0.0.1:{port}/stats", timeout=10,
                context=ctx,
            ) as resp:
                body = json.loads(resp.read())
            assert want in body, (port, body)
            with urllib.request.urlopen(
                f"https://127.0.0.1:{port}/metrics", timeout=10,
                context=ctx,
            ) as resp:
                text = resp.read().decode()
            assert "# delayprofiler" in text
        # the RC /metrics carries its engine registry; the process
        # gauges land there at the stats cadence (refreshed by the tick
        # loop) — poll briefly rather than assume the cadence fired
        deadline = time.time() + 30
        seen = ""
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"https://127.0.0.1:{ports[1] + off}/metrics",
                timeout=10, context=ctx,
            ) as resp:
                seen = resp.read().decode()
            if "gp_process_rss_bytes" in seen:
                break
            time.sleep(0.5)
        assert "gp_process_rss_bytes" in seen
        assert "gp_process_open_fds" in seen
        # plaintext to the TLS port must NOT succeed
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{ports[1] + off}/stats", timeout=5
            )
    finally:
        for n in nodes:
            n.stop()
