"""Safety + liveness property tests for the batched consensus engine.

Mirrors the reference's test strategy (SURVEY.md §4): in-process multi-node
cluster with emulated crashes/delays, asserting the RSM invariant (identical
app state at identical frontiers, ``TESTPaxosMain.assertRSMInvariant``),
decision agreement, and ballot/frontier monotonicity under random message
schedules — the highest-risk properties of the vectorized design.

All clusters share ONE EngineConfig (G=6, W=8, K=4, R=3) so the whole suite
reuses a single compiled step executable (``my_id`` is traced, not static).
"""

import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL, ballot_coord, ballot_num, encode_ballot
from gigapaxos_tpu.ops.engine import EngineConfig, STOP_BIT
from gigapaxos_tpu.testing.sim import DELIVER, DROP, STALE, SimCluster

# G != W on purpose: a wrong-axis broadcast in the engine must raise a shape
# error here rather than silently masking the wrong axis.
G, W, K, R = 6, 8, 4, 3
CFG = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)


def make_cluster(create_all=True):
    c = SimCluster(CFG)
    if create_all:
        c.create_all_groups()
    return c


def no_reqs():
    return np.full((G, K), NULL, np.int32)


def reqs_for(c, g, vids):
    """Build a request injection dict targeted at group g's coordinator."""
    arr = no_reqs()
    arr[g, : len(vids)] = vids
    return {c.coordinator_of(g): arr}


def test_ballot_codec():
    b = encode_ballot(5, 2)
    assert ballot_num(b) == 5 and ballot_coord(b) == 2
    assert encode_ballot(5, 2) > encode_ballot(4, 31)
    assert encode_ballot(5, 3) > encode_ballot(5, 2)


def test_single_commit():
    c = make_cluster()
    c.step_all(reqs=reqs_for(c, 0, [101]))
    c.run(4)
    fr = c.exec_frontiers()
    assert (fr[:, 0] == 1).all(), fr
    c.assert_rsm_invariant()
    assert c.checker.chosen[(0, 0)] == 101


def test_pipelined_commits_all_groups():
    c = make_cluster()
    vid = 1
    sent = {g: [] for g in range(G)}
    for _ in range(12):
        inject = {}
        staged = {}
        for g in range(G):
            rid = c.coordinator_of(g)
            arr = inject.setdefault(rid, no_reqs())
            vids = list(range(vid, vid + K))
            vid += K
            arr[g, :] = vids
            staged[g] = (rid, vids)
        outs = c.step_all(reqs=inject)
        # the engine refuses lanes when the slot window is full; the host
        # batcher requeues those — here we just track what WAS admitted
        for g, (rid, vids) in staged.items():
            n = int(np.asarray(outs[rid].n_admitted)[g])
            sent[g].extend(vids[:n])
    c.run(6)
    fr = c.exec_frontiers()
    # every group fully committed and executed everywhere
    assert (fr == fr[0]).all()
    assert fr.min() > 0
    c.assert_rsm_invariant()
    # ordering: committed vids per group are exactly the admitted sequence
    for g in range(G):
        committed = [c.checker.chosen[(g, s)] for s in range(int(fr[0, g]))]
        assert committed == sent[g], (g, committed, sent[g])
        assert len(committed) > 0


def test_straggler_catches_up_via_decision_rings():
    c = make_cluster()
    # replica 2 hears nothing for a while; 0 and 1 keep committing
    part = np.full((3, 3), DELIVER)
    part[2, 0] = part[2, 1] = DROP
    part[0, 2] = part[1, 2] = DROP
    vid = 1
    for _ in range(6):
        arr = no_reqs()
        arr[0, 0] = vid
        arr[1, 0] = vid + 1
        vid += 2
        # groups 0,1 have coordinators 0,1 (round robin) — both live
        c.step_all(reqs={c.coordinator_of(0): arr}, delivery=part)
    fr = c.exec_frontiers()
    assert fr[2].sum() == 0 or fr[2].sum() < fr[0].sum()
    # heal the partition: straggler must catch up purely from decision rings
    c.run(6)
    fr = c.exec_frontiers()
    assert (fr[2] == fr[0]).all(), fr
    c.assert_rsm_invariant()


def test_coordinator_failover():
    c = make_cluster()
    c.step_all(reqs=reqs_for(c, 0, [11]))
    c.run(4)
    assert (c.exec_frontiers()[:, 0] == 1).all()
    dead = c.coordinator_of(0)
    alive = [r for r in range(3) if r != dead]
    # kill the coordinator (drop all its links both ways)
    d = np.full((3, 3), DELIVER)
    for r in range(3):
        d[r, dead] = DROP
        d[dead, r] = DROP
    # failure detector fires on a live replica
    want = np.zeros((G,), bool)
    want[0] = True
    c.step_all(want_coord={alive[0]: want}, delivery=d)
    c.run(4, delivery=d)
    # new coordinator commits new requests
    arr = no_reqs()
    arr[0, 0] = 77
    c.step_all(reqs={alive[0]: arr}, delivery=d)
    c.run(5, delivery=d)
    fr = c.exec_frontiers()
    assert fr[alive[0], 0] >= 2, fr
    assert fr[alive[1], 0] >= 2, fr
    assert c.checker.chosen[(0, 1)] == 77
    c.assert_rsm_invariant(groups=[0])
    # the old coordinator rejoins and catches up
    c.run(6)
    assert (c.exec_frontiers()[:, 0] == fr[alive[0], 0]).all()
    c.assert_rsm_invariant(groups=[0])


def test_dueling_coordinators_safe():
    c = make_cluster()
    rng = np.random.default_rng(0)
    vid = 1
    for t in range(40):
        want = np.zeros((G,), bool)
        want[0] = True
        wc = {t % 3: want} if t % 4 == 0 else {}
        arr = no_reqs()
        arr[0, 0] = vid
        vid += 1
        rid = int(rng.integers(0, 3))
        c.step_all(reqs={rid: arr}, want_coord=wc)
    c.run(8)
    c.assert_rsm_invariant()
    # progress must have happened despite the churn
    assert c.exec_frontiers()[0, 0] > 0


def test_random_schedule_fuzz():
    """The big one: random drops/stale-delivery/elections for many steps;
    every step asserts agreement + monotonicity; then heal and converge."""
    c = make_cluster()
    rng = np.random.default_rng(42)
    vid = 1
    for t in range(120):
        delivery = rng.choice(
            [DELIVER, STALE, DROP], size=(3, 3), p=[0.6, 0.2, 0.2]
        )
        inject = {}
        for g in range(G):
            if rng.random() < 0.5:
                rid = int(rng.integers(0, 3))
                arr = inject.setdefault(rid, no_reqs())
                arr[g, 0] = vid
                vid += 1
        wc = {}
        if rng.random() < 0.1:
            w = rng.random(G) < 0.3
            wc[int(rng.integers(0, 3))] = w
        c.step_all(reqs=inject, want_coord=wc, delivery=delivery)
    # heal: full delivery, one replica nudged to lead any stuck group
    for t in range(30):
        wc = {}
        if t % 10 == 0:
            wc = {t % 3: np.ones(G, bool)}
        c.step_all(want_coord=wc)
    fr = c.exec_frontiers()
    assert (fr == fr[0]).all(), fr
    c.assert_rsm_invariant()
    assert c.checker.total_committed() > 20


def test_stop_request_halts_group():
    c = make_cluster()
    stop_vid = 5 | STOP_BIT
    c.step_all(reqs=reqs_for(c, 0, [1, 2, stop_vid, 4]))
    c.run(6)
    fr = c.exec_frontiers()
    # slots 0,1 committed; stop at slot 2 committed; lane 3's request 4 must
    # NOT have been admitted after the stop
    assert (fr[:, 0] == 3).all(), fr
    assert c.checker.chosen[(0, 2)] == stop_vid
    assert (0, 3) not in c.checker.chosen
    # group is stopped: further requests are refused
    c.step_all(reqs=reqs_for(c, 0, [99]))
    c.run(4)
    assert (c.exec_frontiers()[:, 0] == 3).all()
    for r in range(3):
        assert int(np.asarray(c.states[r].stopped)[0]) == 1


def test_per_group_membership_subset():
    """Groups with a 2-of-3 member subset: non-member must stay untouched."""
    c = make_cluster(create_all=False)
    c.create_group(0, members=[0, 1])
    c.create_group(1, members=[0, 1, 2])
    arr = no_reqs()
    arr[0, 0] = 10
    c.step_all(reqs={c.coordinator_of(0): arr})
    c.run(5)
    fr = c.exec_frontiers()
    assert fr[0, 0] == 1 and fr[1, 0] == 1
    assert fr[2, 0] == 0  # non-member untouched
    c.assert_rsm_invariant(groups=[1])


def test_instance_tag_guard():
    """Rows are reused across instances: a stale holdout still running the
    row's PREVIOUS tenant must not contaminate the new tenant's consensus
    (its decided values merging into the new instance executed a different
    name's epoch-final stop inside a live group — chaos-soak find)."""
    import jax.numpy as jnp

    c = make_cluster(create_all=False)
    c.create_group(0, members=[0, 1, 2])
    # replica 2 is a stale holdout: same row, different instance tag, with
    # a decided value sitting in its rings at the new tenant's frontier.
    # Its own row is frozen (non-member in its local mask, like a holdout
    # whose drop landed) but its blob still ships the poisoned rings.
    st = c.states[2]
    c.states[2] = st._replace(
        tag=st.tag.at[0].set(999),
        member_mask=st.member_mask.at[0].set(0b011),
        dec_slot=st.dec_slot.at[0, 0].set(0),
        dec_vid=st.dec_vid.at[0, 0].set(777),
    )
    c.run(5)
    for r in (0, 1):
        assert 777 not in np.asarray(c.states[r].dec_vid)[0], r
        assert int(np.asarray(c.states[r].exec_slot)[0]) == 0
    # matching tags (the committed instance) still decide normally
    arr = no_reqs()
    arr[0, 0] = 10
    c.step_all(reqs={c.coordinator_of(0): arr})
    c.run(5)
    fr = c.exec_frontiers()
    assert fr[0, 0] == 1 and fr[1, 0] == 1
    assert c.checker.chosen[(0, 0)] == 10
