"""Binary hot-path codec ('R'/'S' frames, net/hot_codec.py): parity,
golden bytes, malformed-frame rejection, and the native-toolchain
lifecycle (in-venv build from source; CLEAN fallback when no compiler —
the `stats` admin op must report which codec is live so a silent
regression to the Python path can never masquerade as the fast path)."""

import os
import struct

import pytest

import gigapaxos_tpu.native as native
from gigapaxos_tpu.net import hot_codec

REQ_ITEMS = [
    (123456789012345678, "probe0", "value-äß\x00end", False),
    ((1 << 61) + 7, "n", "", True),
    (1, "a" * 300, "v" * 5000, False),
    # traced item: the 5-tuple form carries (tid, origin, hop)
    (99, "tr", "tv", False, ((1 << 62) + 5, 3, 2)),
    (100, "tr2", "", True, (1, -1, 0)),  # client origin tag -1
]
RESP_ITEMS = [
    {"request_id": 42, "response": "ok:1", "name": "probe0"},
    {"request_id": 43, "response": None, "name": "x", "error": "overload"},
    {"request_id": 44, "response": None, "name": "y",
     "error": "unknown_name"},
    {"request_id": 45, "response": "", "name": "z", "error": "exhausted"},
    # traced response: the "tc" field rides a fixed 13-byte tail
    {"request_id": 46, "response": "ok", "name": "t",
     "tc": [(1 << 62) + 5, 3, 2]},
    {"request_id": 47, "response": None, "name": "t2",
     "error": "overload", "tc": [7, -1, 0]},
]

# golden bytes pin the WIRE layout (computed from the documented layout,
# not from the codec — a layout change must fail here, not silently
# re-golden): one item, rid=7, stop, name "ab", value "c".  UNTRACED
# frames must stay byte-identical to the pre-trace wire format — these
# two goldens are unchanged from before the trace field existed.
GOLDEN_R = (
    b"R" + struct.pack("<iI", -1, 1)
    + struct.pack("<QBHI", 7, 1, 2, 1) + b"ab" + b"c"
)
# rid=9, err overload(1), no response, name "n"
GOLDEN_S = (
    b"S" + struct.pack("<iI", 2, 1)
    + struct.pack("<QBBHI", 9, 1, 0, 1, 0) + b"n"
)
# traced goldens: flag bit1 set, 13-byte trace tail (tid u64, origin
# i32, hop u8) appended after the payload bytes
GOLDEN_R_TRACED = (
    b"R" + struct.pack("<iI", -1, 1)
    + struct.pack("<QBHI", 7, 1 | 2, 2, 1) + b"ab" + b"c"
    + struct.pack("<QiB", 0x1122334455667788, 3, 2)
)
GOLDEN_S_TRACED = (
    b"S" + struct.pack("<iI", 2, 1)
    + struct.pack("<QBBHI", 9, 1, 0 | 2, 1, 0) + b"n"
    + struct.pack("<QiB", 0x1122334455667788, 3, 2)
)


@pytest.fixture(params=["native", "python"])
def codec_mode(request, monkeypatch):
    """Run the test body under the native codec AND the pure-Python
    fallback (same pattern as tests/test_recovery.py's journal runs)."""
    if request.param == "python":
        monkeypatch.setenv("GP_NO_NATIVE", "1")
    native._libs.clear()
    yield request.param
    native._libs.clear()


def test_round_trip_requests(codec_mode):
    frame = hot_codec.encode_request_batch(-1, REQ_ITEMS)
    if codec_mode == "native" and not hot_codec.native_active():
        pytest.skip("no toolchain in this environment")
    assert hot_codec.decode_request_batch(frame) == (-1, REQ_ITEMS)


def test_round_trip_responses(codec_mode):
    frame = hot_codec.encode_response_batch(5, RESP_ITEMS)
    sender, items = hot_codec.decode_response_batch(frame)
    assert sender == 5
    assert items == RESP_ITEMS


def test_golden_bytes(codec_mode):
    assert hot_codec.encode_request_batch(
        -1, [(7, "ab", "c", True)]
    ) == GOLDEN_R
    assert hot_codec.encode_response_batch(2, [{
        "request_id": 9, "response": None, "name": "n",
        "error": "overload",
    }]) == GOLDEN_S


def test_golden_bytes_traced(codec_mode):
    """The trace field pinned on the wire — present AND absent: the
    traced item appends exactly the 13-byte (tid, origin, hop) tail
    behind the flag bit, and the untraced goldens above prove absence
    is byte-identical to the pre-trace format."""
    tc = (0x1122334455667788, 3, 2)
    frame = hot_codec.encode_request_batch(-1, [(7, "ab", "c", True, tc)])
    assert frame == GOLDEN_R_TRACED
    assert hot_codec.decode_request_batch(frame) == (
        -1, [(7, "ab", "c", True, tc)]
    )
    sframe = hot_codec.encode_response_batch(2, [{
        "request_id": 9, "response": None, "name": "n",
        "error": "overload", "tc": list(tc),
    }])
    assert sframe == GOLDEN_S_TRACED
    _s, items = hot_codec.decode_response_batch(sframe)
    assert items[0]["tc"] == list(tc)
    assert items[0]["error"] == "overload"
    assert items[0]["response"] is None


def test_native_python_parity():
    """The two implementations must be byte-identical BOTH directions on
    the same inputs (the golden test pins one point; this pins many)."""
    native._libs.clear()
    os.environ.pop("GP_NO_NATIVE", None)
    if not hot_codec.native_active():
        pytest.skip("no toolchain in this environment")
    na_r = hot_codec.encode_request_batch(-1, REQ_ITEMS)
    na_s = hot_codec.encode_response_batch(3, RESP_ITEMS)
    na_rd = hot_codec.decode_request_batch(na_r)
    na_sd = hot_codec.decode_response_batch(na_s)
    os.environ["GP_NO_NATIVE"] = "1"
    native._libs.clear()
    try:
        assert not hot_codec.native_active()
        assert hot_codec.encode_request_batch(-1, REQ_ITEMS) == na_r
        assert hot_codec.encode_response_batch(3, RESP_ITEMS) == na_s
        assert hot_codec.decode_request_batch(na_r) == na_rd
        assert hot_codec.decode_response_batch(na_s) == na_sd
    finally:
        del os.environ["GP_NO_NATIVE"]
        native._libs.clear()


def test_malformed_frames_rejected(codec_mode):
    good = hot_codec.encode_request_batch(-1, REQ_ITEMS)
    for bad in (
        b"", b"R", good[:-1], good + b"x",
        b"R" + struct.pack("<iI", -1, 99) + b"\x00" * 10,
        b"J" + good[1:],
    ):
        with pytest.raises(ValueError):
            hot_codec.decode_request_batch(bad)
    goods = hot_codec.encode_response_batch(1, RESP_ITEMS)
    for bad in (b"", goods[:-1], goods + b"y"):
        with pytest.raises(ValueError):
            hot_codec.decode_response_batch(bad)


def test_unknown_error_string_falls_back_to_json():
    item = {"request_id": 1, "response": None, "name": "n",
            "error": "weird_new_error"}
    assert not hot_codec.encodable_response(item)
    assert hot_codec.encodable_response(RESP_ITEMS[0])


def test_native_builds_from_source_in_venv(tmp_path):
    """Tier-1 toolchain gate: the codec library builds from its .cc with
    the system compiler, and a MISSING toolchain degrades cleanly to the
    Python codec (no exception, status() says so)."""
    so = os.path.join(os.path.dirname(native.__file__), "libgp_codec.so")
    native._libs.clear()
    os.environ.pop("GP_NO_NATIVE", None)
    if os.path.exists(so):
        os.unlink(so)  # force a rebuild from source
    lib = native.codec_lib()
    if lib is None:
        pytest.skip("no C++ toolchain in this environment")
    assert os.path.exists(so), "build did not produce the shared object"
    assert hot_codec.status()["impl"] == "gp_codec.so"


def test_clean_fallback_when_toolchain_absent(monkeypatch):
    """Simulate a host with no compiler: loader returns None, codec
    still round-trips via Python, and status() reports the regression
    (the `stats` admin op surfaces this — tested in test_pipeline)."""
    so = os.path.join(os.path.dirname(native.__file__), "libgp_codec.so")
    native._libs.clear()
    monkeypatch.setattr(native, "_build", lambda src, so_: False)
    if os.path.exists(so):
        os.unlink(so)
    assert native.codec_lib() is None
    st = hot_codec.status()
    assert st["native"] is False and st["impl"] == "python-struct"
    frame = hot_codec.encode_request_batch(-1, REQ_ITEMS)
    assert hot_codec.decode_request_batch(frame) == (-1, REQ_ITEMS)
    native._libs.clear()  # let later tests rebuild
