"""PackedSpillStore unit tests: the segment-file spill layout behind the
paused-group table at density scale (round-trip, LRU spill, batched
restore, torn-tail repair, dead-ratio compaction, layout hygiene)."""

import os

import pytest

from gigapaxos_tpu.utils.packedstore import (
    _HDR,
    PackedSpillStore,
    SpillCorruption,
)


def _store(tmp_path, **kw):
    kw.setdefault("capacity", 8)
    return PackedSpillStore(str(tmp_path / "spill"), **kw)


def _seg_files(store):
    out = []
    for root, _dirs, files in os.walk(store.dir):
        out.extend(os.path.join(root, f) for f in files
                   if f.endswith(".seg"))
    return sorted(out)


def test_round_trip_and_lru_spill(tmp_path):
    st = _store(tmp_path, capacity=8)
    for i in range(20):
        st[("svc%d" % i, 0)] = {"exec": i, "members": [0, 1, 2]}
    # over capacity: LRU half paged out as packed appends, nothing lost
    assert len(st) == 20
    assert st.n_in_memory <= 8
    assert st.n_on_disk == 20 - st.n_in_memory
    for i in range(20):
        assert st[("svc%d" % i, 0)]["exec"] == i
    # tuple keys survive the JSON wire (lists round-trip back to tuples)
    assert ("svc3", 0) in st
    assert set(st) == {("svc%d" % i, 0) for i in range(20)}
    st.close()


def test_delete_and_overwrite_mark_dead(tmp_path):
    st = _store(tmp_path, capacity=2)
    for i in range(8):
        st[i] = "v%d" % i
    del st[0]
    st[1] = "v1b"  # overwrite of a spilled key kills the old copy
    assert 0 not in st
    assert st[1] == "v1b"
    stats = st.stats()
    assert stats["dead_records"] >= 1
    assert stats["live_records"] == stats["on_disk"]
    with pytest.raises(KeyError):
        del st[0]
    st.close()


def test_demote_and_restore_batch(tmp_path):
    st = _store(tmp_path, capacity=64)
    keys = [("n%03d" % i, 0) for i in range(32)]
    for k in keys:
        st[k] = {"k": k[0]}
    assert st.demote_batch(keys) == 32
    assert st.n_in_memory == 0 and st.n_on_disk == 32
    # already-spilled keys count, unknown keys don't
    assert st.demote_batch(keys[:4] + [("ghost", 9)]) == 4
    assert st.demote(("ghost", 9)) is False
    got = st.restore_batch(keys + [("ghost", 9)])
    assert set(got) == set(keys)
    assert all(got[k]["k"] == k[0] for k in keys)
    st.close()


def test_peek_items_does_not_promote(tmp_path):
    st = _store(tmp_path, capacity=4)
    for i in range(12):
        st[i] = i * 10
    spilled_before = st.n_on_disk
    assert dict(st.peek_items()) == {i: i * 10 for i in range(12)}
    assert st.n_on_disk == spilled_before
    st.close()


def test_torn_tail_truncated_record_is_dropped(tmp_path):
    """A record whose payload was cut mid-write must fail its CRC read
    and be skipped by the sequential scanner — intact earlier records
    stay readable."""
    st = _store(tmp_path, capacity=2)
    for i in range(6):
        st[i] = {"v": i}
    st.close()
    # tear the tail: chop the last 3 bytes of the newest segment
    seg = _seg_files(st)[-1]
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 3)
    torn_key = None
    ok = 0
    for key, (s, off, ln) in list(st._index.items()):
        try:
            _k, v = st._read_record(s, off, ln)
            assert v == {"v": key}
            ok += 1
        except SpillCorruption:
            torn_key = key
    assert torn_key is not None and ok == st.n_on_disk - 1
    # the scanner stops cleanly at the torn frame
    scanned = list(st._scan_segment(int(os.path.basename(seg)[3:-4])))
    assert all(k != torn_key for k, _v, _off in scanned)


def test_compaction_reclaims_dead_segments(tmp_path):
    """Dead-heavy non-tail segments are rewritten: live records move to
    the tail, the file unlinks, disk usage stays O(live)."""
    st = _store(
        tmp_path, capacity=2, segment_bytes=4096, compact_ratio=0.3
    )
    keys = [("g%04d" % i, 0) for i in range(200)]
    for k in keys:
        st[k] = {"pad": "x" * 64, "k": k[0]}
    st.demote_batch(keys)
    n_seg_before = len(_seg_files(st))
    assert n_seg_before > 1  # the shape needs multiple segments
    # kill most of the population: dead ratios cross the gate
    for k in keys[: 160]:
        del st[k]
    assert st.compactions > 0
    stats = st.stats()
    assert stats["live_records"] == 40
    # survivors intact after their records were re-appended
    for k in keys[160:]:
        assert st[k]["k"] == k[0]
    # compacted files actually unlinked
    assert len(_seg_files(st)) <= n_seg_before
    st.close()


def test_tail_segment_never_compacts_in_place(tmp_path):
    st = _store(tmp_path, capacity=2, segment_bytes=1 << 20)
    for i in range(10):
        st[i] = "v%d" % i
    st.demote_batch(list(range(10)))
    for i in range(9):  # everything in the single (tail) segment dies
        del st[i]
    assert st.compactions == 0  # the open tail is exempt
    assert st[9] == "v9"
    st.close()


def test_segments_fan_over_subdirs(tmp_path):
    st = _store(
        tmp_path, capacity=2, segment_bytes=4096, subdirs=4
    )
    for i in range(300):
        st[i] = {"pad": "y" * 64}
    st.demote_batch(list(range(300)))
    subdirs = {os.path.basename(os.path.dirname(p))
               for p in _seg_files(st)}
    assert len(subdirs) > 1  # segment files spread across shards
    for d in subdirs:
        int(d, 16)  # 2-hex-char shard names
    st.close()


def test_wipes_stale_layouts_at_construction(tmp_path):
    d = tmp_path / "spill"
    d.mkdir()
    (d / "stale.dm").write_text("old file-per-key spill")
    (d / "0a").mkdir()
    (d / "0a" / "seg00000007.seg").write_text("old segment")
    st = PackedSpillStore(str(d), capacity=4)
    assert not (d / "stale.dm").exists()
    assert not (d / "0a").exists()
    assert len(st) == 0
    st.close()


def test_frame_header_is_length_plus_crc(tmp_path):
    """The record frame the density footprint math keys on: u32 length +
    u32 crc, then the JSON payload."""
    st = _store(tmp_path, capacity=2)
    st["k"] = "value"
    st.demote("k")
    seg = _seg_files(st)[0]
    with open(seg, "rb") as f:
        raw = f.read()
    length, _crc = _HDR.unpack(raw[: _HDR.size])
    assert len(raw) == _HDR.size + length
    st.close()
