"""Client anycast + batched creates over the deployable socket path
(ref: ``ReconfigurableAppClientAsync.java:798-1404`` sendRequestAnycast;
``Reconfigurator.java:484-680`` batched CreateServiceName split by RC
group)."""

import threading
import time

import pytest

from gigapaxos_tpu.clients.reconfigurable_client import ReconfigurableAppClient
from gigapaxos_tpu.models.apps import NoopPaxosApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
from gigapaxos_tpu.testing.ports import free_ports
from gigapaxos_tpu.utils.config import Config


@pytest.fixture()
def cluster():
    ports = free_ports(6)
    Config.clear()
    for i in range(3):
        Config.set(f"active.AR{i}", f"127.0.0.1:{ports[i]}")
        Config.set(f"reconfigurator.RC{i}", f"127.0.0.1:{ports[3 + i]}")
    ar_cfg = EngineConfig(n_groups=256, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    nodes = [
        ReconfigurableNode(f"AR{i}", NoopPaxosApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ] + [
        ReconfigurableNode(f"RC{i}", NoopPaxosApp, ar_cfg=ar_cfg,
                           rc_cfg=rc_cfg)
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    client = ReconfigurableAppClient.from_properties()
    yield nodes, client
    client.close()
    for n in nodes:
        n.stop()
    Config.clear()


@pytest.mark.timeout(180)
def test_batched_creates_few_round_trips(cluster):
    """100 names created through batched per-RC rounds; all resolvable;
    a re-issued batch is idempotent (ok/existed)."""
    _nodes, client = cluster
    names = [f"bc{i}" for i in range(100)]
    t0 = time.time()
    results = client.create_names(names, timeout=60)
    took = time.time() - t0
    assert set(results) == set(names), (
        sorted(set(names) - set(results))[:5], len(results)
    )
    bad = {n: r for n, r in results.items() if not r.get("ok")}
    assert not bad, dict(list(bad.items())[:3])
    # every created name resolves to a live active set
    for nm in names[::17]:
        acts = client.request_actives(nm)
        assert acts, nm
    # a second batch over the same names is idempotent success
    again = client.create_names(names, timeout=60)
    assert all(r.get("ok") for r in again.values()), again
    assert any(r.get("existed") for r in again.values())
    # sanity: 100 creates did NOT cost 100 sequential client round trips
    # (each name singly takes >= one RC round trip; batched, the whole
    # set should land well under a second per name)
    assert took < 60, took


@pytest.mark.timeout(180)
def test_anycast_survives_dead_active(cluster):
    """Anycast answers while one of the three actives is down."""
    nodes, client = cluster
    ack = client.create_name("any", actives=[0, 1, 2], timeout=30)
    assert ack and ack.get("ok"), ack
    assert client.send_request_sync("any", "warm", timeout=15) is not None

    # kill a NON-coordinator active outright (server + transport): the
    # group keeps committing; a dead COORDINATOR additionally needs the
    # election plus a client retransmit, which single-shot anycast
    # deliberately doesn't do (parity: the reference's anycast is also a
    # single send; liveness there comes from app-level retries)
    mgr0 = nodes[0].servers[0].manager
    row = mgr0.names["any"]
    coord = mgr0.coordinator_of_row(row)
    dead = (coord + 1) % 3
    nodes[dead].stop()
    time.sleep(0.5)

    got = []
    ev = threading.Event()

    def cb(rid, resp, error):
        got.append((resp, error))
        ev.set()

    rid = client.send_request_anycast("any", "hello", cb)
    assert rid is not None
    assert ev.wait(30), "no anycast response with one active dead"
    resp, error = got[0]
    assert error is None and resp is not None, got[0]

    # exactly-once despite fan-out: a second anycast with the SAME id is
    # answered from the response cache, not re-executed
    ev2 = threading.Event()
    out2 = []
    client.send_request_anycast(
        "any", "hello", lambda r, rp, e: (out2.append((rp, e)), ev2.set()),
        request_id=rid,
    )
    assert ev2.wait(15)
    assert out2[0][1] is None
