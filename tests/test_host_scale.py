"""Host-path scale: the manager's per-tick host work must be bounded by
ACTIVITY, not by G (the reference's 2M-idle-instance story,
``MultiArrayMap.java:41`` / VERDICT r2 weak #3).  The engine step itself
is O(G) on-device by design; everything around it (queues, execution,
journaling, accessors) must not walk idle groups or re-transfer whole
arrays per call."""

import time

import numpy as np

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models.apps import NoopPaxosApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster


def tick_host_cost(G, n_ticks=12, warmup=3):
    """Mean host-side tick cost (total tick minus the jitted engine step)
    for a single idle manager with a handful of live groups."""
    from gigapaxos_tpu.utils.profiler import DelayProfiler

    cfg = EngineConfig(n_groups=G, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, NoopPaxosApp)
    for i in range(8):
        c.create(f"g{i}", members=[0, 1, 2])
    c.run(warmup)
    host_costs = []
    for _ in range(n_ticks):
        t0 = time.perf_counter()
        before = DelayProfiler.get("engine_step")
        c.step_all()
        after = DelayProfiler.get("engine_step")
        total = time.perf_counter() - t0
        # 3 managers step per step_all; subtract their engine time
        host_costs.append(total - 3 * (after if after else 0))
    c.close()
    host_costs.sort()
    return host_costs[len(host_costs) // 2]  # median


def test_idle_group_host_cost_near_flat():
    """8x more idle rows must not inflate the host-side tick cost by more
    than ~3x (numpy O(G) masks are fine — per-group Python loops or
    per-call device syncs are not: those blow up 8x+)."""
    small = tick_host_cost(16_384)
    big = tick_host_cost(131_072)
    assert big < max(3.5 * small, small + 0.08), (
        f"host tick cost scales with G: {small * 1000:.1f}ms @16k -> "
        f"{big * 1000:.1f}ms @131k"
    )


def test_accessors_do_not_transfer_per_call():
    """Hot accessors must hit the host mirror, not the device: 10k calls
    against a G=131k manager complete in well under a second."""
    cfg = EngineConfig(n_groups=131_072, window=8, req_lanes=4, n_replicas=3)
    m = PaxosManager(0, NoopPaxosApp(), cfg)
    m.create_paxos_instance("svc", [0, 1, 2], row=7)
    m.coordinator_of_row(7)  # prime the mirror
    t0 = time.perf_counter()
    for _ in range(10_000):
        m.coordinator_of_row(7)
        m.current_epoch("svc")
        m.is_stopped("svc")
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"30k hot accessor calls took {dt:.2f}s"
