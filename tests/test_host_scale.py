"""Host-path scale: the manager's per-tick host work must be bounded by
ACTIVITY, not by G (the reference's 2M-idle-instance story,
``MultiArrayMap.java:41`` / VERDICT r2 weak #3).  The engine step itself
is O(G) on-device by design; everything around it (queues, execution,
journaling, accessors) must not walk idle groups or re-transfer whole
arrays per call."""

import time

import numpy as np

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models.apps import NoopPaxosApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster


def tick_host_cost(G, n_ticks=12, warmup=3):
    """Median host-side tick cost (total tick minus the jitted engine
    steps, measured per tick) for idle managers with a few live groups."""
    cfg = EngineConfig(n_groups=G, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, NoopPaxosApp)
    for i in range(8):
        c.create(f"g{i}", members=[0, 1, 2])
    c.run(warmup)
    host_costs = []
    for _ in range(n_ticks):
        t0 = time.perf_counter()
        c.step_all()
        total = time.perf_counter() - t0
        engine = sum(m.last_engine_step_s for m in c.managers)
        host_costs.append(total - engine)
    c.close()
    host_costs.sort()
    return host_costs[len(host_costs) // 2]  # median


def test_idle_group_host_cost_is_array_speed():
    """Idle groups must cost ARRAY speed on the host, not Python speed.

    The tick's host side legitimately moves O(G*W) bytes (the blob
    exchange IS the state transfer in host-exchange mode), so the bound
    is per-group cost: numpy-batch work runs ~1-2us/group for the whole
    3-replica round; per-group Python loops or per-call device syncs run
    5-10us+/group and blow the budget immediately."""
    per_group = tick_host_cost(131_072) / 131_072
    assert per_group < 4e-6, (
        f"host tick cost {per_group * 1e6:.2f}us/group at G=131k — "
        "something walks idle groups in Python"
    )


def test_accessors_do_not_transfer_per_call():
    """Hot accessors must hit the host mirror, not the device: 10k calls
    against a G=131k manager complete in well under a second."""
    cfg = EngineConfig(n_groups=131_072, window=8, req_lanes=4, n_replicas=3)
    m = PaxosManager(0, NoopPaxosApp(), cfg)
    m.create_paxos_instance("svc", [0, 1, 2], row=7)
    m.coordinator_of_row(7)  # prime the mirror
    t0 = time.perf_counter()
    for _ in range(10_000):
        m.coordinator_of_row(7)
        m.current_epoch("svc")
        m.is_stopped("svc")
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"30k hot accessor calls took {dt:.2f}s"


def test_throughput_survives_lagging_member():
    """VERDICT r2 weak #7: throughput under lag. With one member's
    delivery cut, the majority must keep committing at a comparable rate,
    and the jump-horizon write-off must keep payload retention bounded
    (a dead member must not pin every payload)."""
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)

    def run_commits(drop_member, n_rounds=60):
        c = ManagerCluster(cfg, NoopPaxosApp)
        c.create("svc", members=[0, 1, 2])
        delivery = np.zeros((3, 3), int)
        if drop_member is not None:
            delivery[drop_member, :] = 1
            delivery[:, drop_member] = 1
        done = {}
        live = [r for r in range(3) if r != drop_member]
        for i in range(n_rounds):
            c.submit("svc", f"v{i}", entry=live[0],
                     callback=lambda rid, r: done.setdefault(rid, r))
            c.step_all(delivery=delivery)
        c.run(10, delivery=delivery)
        n = len(done)
        retained = max(len(m.retained) for m in c.managers)
        c.close()
        return n, retained

    full, _ = run_commits(None)
    lagged, retained = run_commits(2)
    assert lagged >= 0.5 * full, (
        f"throughput collapsed under a dead member: {lagged} vs {full}"
    )
    # retention horizon: the dead member is written off, so payloads do
    # not accumulate without bound (4W default horizon)
    assert retained <= 8 * cfg.window, (
        f"{retained} retained payloads — dead member pins retention"
    )
