"""End-to-end manager tests: app execution, callbacks, forwarding,
exactly-once, checkpoint + crash recovery — the minimum end-to-end slice
(SURVEY.md §7 stage 6, ``tests/loopback_1_group`` parity in-process)."""

import numpy as np
import pytest

from gigapaxos_tpu.models import HashChainApp, NoopPaxosApp, StatefulAdderApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import DELIVER, DROP, ManagerCluster

CFG = EngineConfig(n_groups=6, window=8, req_lanes=4, n_replicas=3)


def test_end_to_end_commit_with_callback():
    c = ManagerCluster(CFG, NoopPaxosApp)
    c.create("svc")
    got = {}
    c.submit("svc", "hello", entry=0, callback=lambda rid, resp: got.update(
        {"rid": rid, "resp": resp}
    ))
    c.run(8)  # covers the forward-to-coordinator hop if entry != coord
    assert got.get("resp") == "noop-ack"
    assert (c.app_exec()[:, c.managers[0].names["svc"]] == 1).all()
    c.close()


def test_adder_consistency_across_replicas():
    c = ManagerCluster(CFG, StatefulAdderApp)
    c.create("acct")
    for i in range(10):
        c.submit("acct", str(i + 1), entry=i % 3)
        c.step_all()
    c.run(10)
    totals = [m.app.totals.get("acct", 0) for m in c.managers]
    assert totals == [55, 55, 55], totals
    c.close()


def test_hash_chain_rsm_invariant_under_drops():
    rng = np.random.default_rng(7)
    c = ManagerCluster(CFG, HashChainApp)
    c.create("chain")
    for i in range(20):
        delivery = np.where(rng.random((3, 3)) < 0.25, DROP, DELIVER)
        c.submit("chain", f"v{i}", entry=int(rng.integers(0, 3)))
        c.step_all(delivery=delivery)
    c.run(15)
    n = [m.app.n_executed.get("chain", 0) for m in c.managers]
    s = [m.app.state.get("chain") for m in c.managers]
    assert n[0] > 0 and n == [n[0]] * 3, n
    assert s == [s[0]] * 3, s
    c.close()


def test_exactly_once_response_cache():
    c = ManagerCluster(CFG, StatefulAdderApp)
    c.create("acct")
    responses = []
    cb = lambda rid, resp: responses.append(resp)
    vid = c.managers[0].propose("acct", "5", callback=cb, request_id=777)
    assert vid is not None
    c.run(8)
    assert responses == ["5"]
    # retransmission: same request_id must answer from cache, not re-add
    again = c.managers[0].propose("acct", "5", callback=cb, request_id=777)
    assert again is None
    assert responses == ["5", "5"]
    c.run(4)
    assert c.managers[0].app.totals["acct"] == 5  # executed exactly once
    c.close()


def test_checkpoint_and_crash_recovery(tmp_path):
    dirs = [str(tmp_path / f"n{i}") for i in range(3)]
    c = ManagerCluster(
        CFG, StatefulAdderApp, log_dirs=dirs, checkpoint_every=5
    )
    c.create("acct")
    for i in range(8):
        c.submit("acct", "10", entry=0)
        c.step_all()
    c.run(6)
    total_before = c.managers[1].app.totals["acct"]
    assert total_before == 80
    c.close()

    # restart all three from disk; totals and names must be restored
    c2 = ManagerCluster(
        CFG, StatefulAdderApp, log_dirs=dirs, checkpoint_every=5
    )
    assert "acct" in c2.managers[1].names
    c2.run(6)  # replay any post-checkpoint decisions through the engine
    totals = [m.app.totals.get("acct", 0) for m in c2.managers]
    assert totals == [80, 80, 80], totals
    # the recovered cluster keeps committing
    c2.submit("acct", "1", entry=1)
    c2.run(8)
    totals = [m.app.totals.get("acct", 0) for m in c2.managers]
    assert totals == [81, 81, 81], totals
    c2.close()


def test_stop_request_via_manager():
    c = ManagerCluster(CFG, NoopPaxosApp)
    c.create("ephemeral")
    c.submit("ephemeral", "a", entry=0)
    c.step_all()
    c.submit("ephemeral", "bye", entry=0, stop=True)
    c.run(8)
    row = c.managers[0].names["ephemeral"]
    for m in c.managers:
        assert int(np.asarray(m.state.stopped)[row]) == 1
    # post-stop proposals never commit
    before = c.frontiers()[:, row].copy()
    c.submit("ephemeral", "late", entry=0)
    c.run(5)
    assert (c.frontiers()[:, row] == before).all()
    c.close()


def test_pending_row_gates_admission_until_commit():
    """A start-epoch create is PENDING: proposals queue but nothing may
    commit until the reconfigurator's epoch_commit confirms the row
    (advisor r2: a pre-COMPLETE row move must never discard an
    acknowledged write)."""
    c = ManagerCluster(CFG, NoopPaxosApp)
    row = c.managers[0].default_row_for("pend")
    for m in c.managers:
        m.create_paxos_instance("pend", [0, 1, 2], row=row, pending=True)
    c.blobs = [m.blob() for m in c.managers]
    got = {}
    c.submit("pend", "v0", entry=0, callback=lambda rid, resp: got.update(r=resp))
    c.run(8)
    assert not got, "pending row executed a request before epoch_commit"
    assert (np.asarray([m.state.n_execd for m in c.managers])[:, row] == 0).all()
    for m in c.managers:
        m.commit_row("pend", 0)
    c.run(8)
    assert got.get("r") == "noop-ack"
    c.close()


def test_pending_row_move_carries_held_queue():
    """The probe moving a pending row recreates it at the new row; held
    requests follow the name and execute after the commit."""
    c = ManagerCluster(CFG, NoopPaxosApp)
    for m in c.managers:
        m.create_paxos_instance("mv", [0, 1, 2], row=1, pending=True)
    got = {}
    c.managers[0].propose("mv", "x", callback=lambda rid, resp: got.update(r=resp))
    for m in c.managers:
        assert m.create_paxos_instance("mv", [0, 1, 2], row=3, pending=True)
        assert m.names["mv"] == 3
        m.commit_row("mv", 0)
    c.blobs = [m.blob() for m in c.managers]
    c.run(10)
    assert got.get("r") == "noop-ack"
    c.close()


def test_executed_row_refuses_same_epoch_move():
    """A row that already executed decisions must refuse the move (raises,
    surfacing as a collision NACK so the RC's probe converges back here)."""
    c = ManagerCluster(CFG, NoopPaxosApp)
    c.create("ex")  # non-pending; commits flow
    row = c.managers[0].names["ex"]
    c.submit("ex", "w", entry=0)
    c.run(8)
    assert int(np.asarray(c.managers[0].state.n_execd)[row]) > 0
    with pytest.raises(RuntimeError, match="already executed"):
        c.managers[0].create_paxos_instance(
            "ex", [0, 1, 2], row=(row + 1) % CFG.n_groups, pending=True
        )
    c.close()


def test_pending_gate_survives_restart(tmp_path):
    """The propose-refusal gate is durable: a pending row recovers pending;
    an unpended row recovers live (UNPEND journal block)."""
    from gigapaxos_tpu.manager import PaxosManager

    d = str(tmp_path / "n0")
    cfg = EngineConfig(n_groups=6, window=8, req_lanes=4, n_replicas=3)
    m = PaxosManager(0, NoopPaxosApp(), cfg, log_dir=d)
    m.create_paxos_instance("a", [0, 1, 2], row=2, pending=True)
    m.create_paxos_instance("b", [0, 1, 2], row=4, pending=True)
    m.commit_row("b", 0, row=4)
    m.close()
    m2 = PaxosManager(0, NoopPaxosApp(), cfg, log_dir=d)
    assert m2.pending_rows == {2}
    m2.close()


def test_retransmit_reproposes_after_row_killed():
    """A queued-but-undecided request whose row is killed must not leave a
    dead inflight entry behind: the client's retransmit (same request id)
    has to RE-propose into the name's next incarnation and complete, not
    be deduped against the dead proposal forever (review find on the
    queue-drop sites)."""
    c = ManagerCluster(CFG, StatefulAdderApp)
    c.create("acct")
    rid = 987654321
    got = {}
    # queue on a NON-coordinator entry but don't tick: the vid sits in the
    # row's queue when the kill lands
    m = c.managers[0]
    row = m.names["acct"]
    m.propose("acct", "5", request_id=rid,
              callback=lambda r, resp: got.update({"first": resp}))
    assert m.queues.get(row), "setup: vid must be queued"
    for mm in c.managers:
        mm.kill("acct")
    assert rid not in m.inflight, "kill must release the inflight slot"
    # the name is re-created (fresh incarnation) and the client retransmits
    c.create("acct")
    m.propose("acct", "7", request_id=rid,
              callback=lambda r, resp: got.update({"second": resp}))
    c.run(10)
    assert got.get("second") == "7", got
    assert all(mm.app.totals.get("acct", 0) == 7 for mm in c.managers)
    c.close()
