"""Reconfiguration-layer integration tests — the ``loopback_rc_simple``
parity suite (ref: ``tests/loopback_rc_simple/`` +
``TESTReconfigurationClient.java:676-1078``): create a name through the
reconfigurators, run requests, migrate the replica set (epoch n -> n+1
with final-state handoff to a fresh active), verify state continuity and
old-epoch GC, delete the name; plus unit tests of the ring and records.
"""

import numpy as np
import pytest

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import (
    ConsistentHashing,
    RCState,
    ReconfigurationRecord,
)
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------

def test_consistent_hashing_stability_and_spread():
    ch = ConsistentHashing([0, 1, 2, 3, 4])
    names = [f"name{i}" for i in range(500)]
    place = {n: ch.get_node(n) for n in names}
    # deterministic
    assert place == {n: ch.get_node(n) for n in names}
    # k distinct replicas
    for n in names[:20]:
        reps = ch.get_replicated_servers(n, 3)
        assert len(reps) == len(set(reps)) == 3
    # removing a node only moves that node's names (ring locality)
    ch2 = ConsistentHashing([0, 1, 2, 3])
    moved = [n for n in names if place[n] != ch2.get_node(n) and place[n] != 4]
    assert len(moved) < len(names) * 0.2


def test_record_lifecycle():
    r = ReconfigurationRecord("svc", actives=[0, 1, 2], row=3)
    assert not r.stop_done()  # invalid from READY
    assert r.start_reconfigure([1, 2, 3], 9)
    assert not r.start_reconfigure([1, 2, 3], 9)  # not from WAIT_ACK_STOP
    assert r.stop_done() and r.complete()
    assert (r.epoch, r.actives, r.row, r.state) == (1, [1, 2, 3], 9, RCState.READY)
    assert r.start_delete() and r.finish_delete() and r.deleted
    rt = ReconfigurationRecord.from_json(r.to_json())
    assert rt == r


# ---------------------------------------------------------------------------
# integration: the loopback_rc_simple parity flow
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cluster():
    ar_cfg = EngineConfig(n_groups=32, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    yield c
    c.close()


def _run_requests(c, name, values, entry):
    done = {}
    mgr = c.ars.managers[entry]
    for v in values:
        mgr.propose(name, v, callback=lambda rid, resp: done.setdefault(rid, resp))
    for _ in range(40):
        if len(done) == len(values):
            break
        c.step()
    assert len(done) == len(values), f"only {len(done)}/{len(values)} executed"
    return done


def test_create_request_migrate_delete(cluster):
    c = cluster
    # --- create via the reconfigurators (any RC; forwarded to the owner) --
    c.client_request("create_service", {
        "name": "svc", "actives": [0, 1, 2], "initial_state": None,
    }, rc=0)
    ack = c.wait_for("create_ack")
    assert ack and ack["ok"], ack
    assert sorted(ack["actives"]) == [0, 1, 2] and ack["epoch"] == 0

    # --- request_actives read --------------------------------------------
    c.client_request("request_actives", {"name": "svc"}, rc=1)
    resp = c.wait_for("actives_response")
    assert resp["ok"] and sorted(resp["actives"]) == [0, 1, 2]

    # --- app requests through epoch 0 ------------------------------------
    _run_requests(c, "svc", [f"r{i}" for i in range(5)], entry=0)
    apps = [c.ars.managers[i].app for i in range(4)]
    h0 = apps[0].state["svc"]
    assert apps[1].state["svc"] == h0 and apps[2].state["svc"] == h0
    assert "svc" not in apps[3].state  # node 3 not a member yet

    # --- migrate [0,1,2] -> [1,2,3] (node 3 fetches the final state) -----
    c.client_request("reconfigure", {"name": "svc", "new_actives": [1, 2, 3]})
    ack = c.wait_for("reconfigure_ack", max_steps=120)
    assert ack and ack["ok"], ack
    assert sorted(ack["actives"]) == [1, 2, 3] and ack["epoch"] == 1

    # state continuity: the new epoch resumed from the stop-time hash chain
    for _ in range(30):  # let drops settle
        c.step()
    n1 = apps[1].n_executed["svc"]
    assert apps[3].state["svc"] == apps[1].state["svc"] == apps[2].state["svc"]
    # old epoch dropped: node 0's row freed, name forgotten
    assert c.ars.managers[0].names.get("svc") is None
    assert c.ars.managers[1].old_epochs == {}

    # --- requests keep flowing in epoch 1 (entry = node 1) ----------------
    _run_requests(c, "svc", [f"s{i}" for i in range(4)], entry=1)
    assert apps[1].n_executed["svc"] == n1 + 4
    assert apps[3].state["svc"] == apps[1].state["svc"]

    # --- two-phase delete -------------------------------------------------
    c.client_request("delete_service", {"name": "svc"})
    ack = c.wait_for("delete_ack", max_steps=120)
    assert ack and ack["ok"], ack
    for _ in range(5):
        c.step()
    for i in (1, 2, 3):
        assert c.ars.managers[i].names.get("svc") is None
    # record purged on every reconfigurator
    for rc in c.reconfigurators:
        assert rc.rc_app.get_record("svc") is None

    # --- name reusable after delete (create -> epoch 0 again) -------------
    c.client_request("create_service", {"name": "svc", "actives": [0, 2, 3]})
    ack = c.wait_for("create_ack", max_steps=120)
    assert ack and ack["ok"] and sorted(ack["actives"]) == [0, 2, 3]


def test_create_duplicate_rejected(cluster):
    c = cluster
    c.client_request("create_service", {"name": "dup"})
    ack = c.wait_for("create_ack", max_steps=120)
    assert ack and ack["ok"]
    c.client_request("create_service", {"name": "dup"})
    ack2 = c.wait_for("create_ack", max_steps=120)
    assert ack2 and not ack2["ok"] and ack2["reason"] == "exists"


def test_reconfigure_unknown_name_rejected(cluster):
    c = cluster
    c.client_request("reconfigure", {"name": "ghost", "new_actives": [0, 1, 2]})
    ack = c.wait_for("reconfigure_ack", max_steps=60)
    assert ack and not ack["ok"]


def test_stale_stop_epoch_cannot_stop_live_epoch(cluster):
    """A delayed duplicate stop_epoch(e) arriving after the move to e+1
    must not stop the live e+1 group (review finding: the stale stop would
    otherwise wedge the new epoch forever)."""
    c = cluster
    c.client_request("create_service", {"name": "stale", "actives": [0, 1, 2]})
    ack = c.wait_for("create_ack", max_steps=120)
    assert ack and ack["ok"]
    c.client_request("reconfigure", {"name": "stale", "new_actives": [1, 2, 3]})
    ack = c.wait_for("reconfigure_ack", max_steps=120)
    assert ack and ack["ok"] and ack["epoch"] == 1
    for _ in range(10):
        c.step()
    # replay the old epoch's stop at an active of the NEW epoch
    c.active_replicas[1].handle_message(
        "stop_epoch", {"name": "stale", "epoch": 0, "rc": ["RC", 0]}
    )
    for _ in range(10):
        c.step()
    mgr = c.ars.managers[1]
    assert not mgr.is_stopped("stale"), "stale stop wedged the live epoch"
    _run_requests(c, "stale", ["x", "y"], entry=1)  # still serving


def test_delete_completes_with_dead_active(monkeypatch):
    """A crashed active must not wedge the two-phase delete: the drop round
    expires best-effort and DELETE_FINAL still commits (MAX_FINAL_STATE_AGE
    age-out analog)."""
    from gigapaxos_tpu.reconfiguration import reconfigurator as rc_mod

    monkeypatch.setattr(rc_mod.DropEpochTask, "max_lifetime_s", 0.3)
    monkeypatch.setattr(rc_mod.DropEpochTask, "restart_period_s", 0.05)
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        c.client_request("create_service", {"name": "dd", "actives": [0, 1, 2]})
        ack = c.wait_for("create_ack", max_steps=120)
        assert ack and ack["ok"]
        # node 2 goes dark for the reconfiguration plane
        c.msg_filter = lambda dst, kind, body: dst != ("AR", 2)
        c.client_request("delete_service", {"name": "dd"})
        ack = c.wait_for("delete_ack", max_steps=300)
        assert ack and ack["ok"], ack
        for rc in c.reconfigurators:
            assert rc.rc_app.get_record("dd") is None
    finally:
        c.close()


def test_laggard_active_gets_late_start(monkeypatch):
    """An active whose start_epoch was lost while the majority completed
    the create must still be brought into the epoch afterwards
    (LateStartTask), not left permanently under-replicated."""
    from gigapaxos_tpu.reconfiguration import reconfigurator as rc_mod

    monkeypatch.setattr(rc_mod.LateStartTask, "restart_period_s", 0.02)
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        c.msg_filter = (
            lambda dst, kind, body: not (dst == ("AR", 2) and kind == "start_epoch")
        )
        c.client_request("create_service", {"name": "lag", "actives": [0, 1, 2]})
        ack = c.wait_for("create_ack", max_steps=120)
        assert ack and ack["ok"]
        assert c.ars.managers[2].names.get("lag") is None  # missed the birth
        c.msg_filter = None  # network heals
        for _ in range(60):
            if c.ars.managers[2].names.get("lag") is not None:
                break
            c.step()
        assert c.ars.managers[2].names.get("lag") is not None, \
            "laggard never received the late start_epoch"
        _run_requests(c, "lag", ["p", "q"], entry=2)  # fully participating
    finally:
        c.close()


def test_migration_survives_lossy_control_plane(monkeypatch):
    """Drop 30% of reconfiguration-plane messages: the WaitAck* tasks'
    retransmits must still drive the epoch change to completion (the
    reference's task restarts, ProtocolExecutor.java periodic restart)."""
    from gigapaxos_tpu.reconfiguration import active_replica as ar_mod
    from gigapaxos_tpu.reconfiguration import reconfigurator as rc_mod

    # fast retransmit so wall-clock restarts fire between test steps
    for cls in (rc_mod.StartEpochTask, rc_mod.StopEpochTask,
                rc_mod.DropEpochTask, rc_mod.EpochCommitTask,
                rc_mod.LateStartTask, ar_mod.WaitEpochFinalState):
        monkeypatch.setattr(cls, "restart_period_s", 0.02)

    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    c = ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp)
    try:
        # echo probing off: probe frames would consume draws from the
        # seeded drop rng below and re-roll which control messages die
        # (the recorded 30%-loss schedule this test pins)
        for rc in c.reconfigurators:
            rc.echo_probe_period_s = 0.0
        rng = np.random.RandomState(7)
        c.msg_filter = lambda dst, kind, body: rng.rand() > 0.3

        def request_with_retry(kind, body, ack_kind, tries=8, max_steps=60):
            # client-side retransmission (PaxosClientAsync timeout analog):
            # the op itself is idempotent on the record state machine
            for _ in range(tries):
                c.client_request(kind, dict(body))
                ack = c.wait_for(ack_kind, max_steps=max_steps)
                if ack is not None:
                    return ack
            return None

        ack = request_with_retry(
            "create_service", {"name": "lossy", "actives": [0, 1, 2]},
            "create_ack",
        )
        assert ack and ack["ok"], ack
        _run_requests(c, "lossy", ["a", "b", "c"], entry=1)
        ack = request_with_retry(
            "reconfigure", {"name": "lossy", "new_actives": [1, 2, 3]},
            "reconfigure_ack", max_steps=100,
        )
        assert ack and ack["ok"], ack
        apps = [m.app for m in c.ars.managers]
        for _ in range(20):
            c.step()
        assert apps[3].state["lossy"] == apps[1].state["lossy"]
    finally:
        c.close()
