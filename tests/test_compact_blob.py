"""Compact exchange format: codec round-trip property + safety parity.

The blob a replica publishes no longer ships absolute ``[G, W]`` slot and
ballot planes — slots are exec-anchored wrap deltas and the accepted
ballot is a delta off the promised ballot, bit-packed into ``lane_meta``
(``ops/engine.py`` module docstring).  Two properties pin the format:

* **Round trip** — ``expand_blob(make_blob(state))`` equals the legacy
  absolute-plane blob on every representable lane, and NULLs exactly the
  lanes the format declares unrepresentable (outside the ±WRAP_MAX ring
  epoch window / ballot delta beyond DELTA_MAX), over random valid states
  including NULL lanes, wrap boundaries, and all coordinator phases.
* **Safety parity** — there is ONE format (no dual path), so the whole
  existing engine/spmd invariant suite already runs through it; here a
  long-run cluster crosses the wrap-bias window many times and re-asserts
  the RSM invariant + committed-order property at high slot numbers.
"""

import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from gigapaxos_tpu.ops.ballot import NULL
from gigapaxos_tpu.ops.engine import (
    ACTIVE,
    DELTA_MAX,
    IDLE,
    PREPARING,
    WRAP_MAX,
    EngineConfig,
    EngineState,
    blob_vec_len,
    expand_blob,
    init_state,
    legacy_blob_vec_len,
    make_blob,
    pack_blob,
)
from gigapaxos_tpu.testing.sim import SimCluster

G, W, K, R = 6, 8, 4, 3
CFG = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
KBITS = W.bit_length() - 1


def random_state(rng: np.random.Generator) -> EngineState:
    """A structurally valid EngineState: ring-residue slots scattered
    around the frontier (some beyond the ±WRAP_MAX window), ballots with
    deltas straddling DELTA_MAX, NULL lanes, and mixed phases."""
    lanes = np.arange(W, dtype=np.int64)
    # keep slots non-negative even at epoch delta -(WRAP_MAX+5)
    exec_slot = rng.integers((WRAP_MAX + 8) * W, 10_000, size=G)
    ebase = exec_slot >> KBITS
    bal = rng.integers(DELTA_MAX + 10, 2 ** 24, size=G)

    def lane_slots(p_null: float) -> np.ndarray:
        """[G, W] ring-residue slots at epoch deltas in [-20, 20]."""
        eps = rng.integers(-(WRAP_MAX + 5), WRAP_MAX + 6, size=(G, W))
        s = ((ebase[:, None] + eps) << KBITS) | lanes[None, :]
        return np.where(rng.random((G, W)) < p_null, NULL, s)

    acc_slot = lane_slots(0.3)
    # ballot deltas 0..DELTA_MAX+big: some saturate, a few NULL
    acc_bal = bal[:, None] - rng.integers(0, DELTA_MAX + 100, size=(G, W))
    acc_bal = np.where(rng.random((G, W)) < 0.1, NULL, acc_bal)
    c_phase = rng.integers(0, 3, size=G)  # IDLE / PREPARING / ACTIVE

    st = init_state(CFG)
    i32 = lambda a: jnp.asarray(a, jnp.int32)
    return st._replace(
        tag=i32(rng.integers(1, 1000, size=G)),
        bal=i32(bal),
        exec_slot=i32(exec_slot),
        acc_bal=i32(acc_bal),
        acc_vid=i32(rng.integers(1, 2 ** 20, size=(G, W))),
        acc_slot=i32(acc_slot),
        dec_vid=i32(rng.integers(1, 2 ** 20, size=(G, W))),
        dec_slot=i32(lane_slots(0.3)),
        c_phase=i32(c_phase),
        c_bal=i32(rng.integers(0, 2 ** 24, size=G)),
        c_prop_vid=i32(rng.integers(1, 2 ** 20, size=(G, W))),
        c_prop_slot=i32(lane_slots(0.3)),
    )


def legacy_blob_planes(st: EngineState) -> dict:
    """What the pre-compact all-int32 blob shipped (absolute planes,
    phase-masked) — the round-trip oracle."""
    preparing = np.asarray(st.c_phase) == PREPARING
    active = np.asarray(st.c_phase) == ACTIVE
    act2 = active[:, None]
    return {
        "acc_bal": np.asarray(st.acc_bal),
        "acc_vid": np.asarray(st.acc_vid),
        "acc_slot": np.asarray(st.acc_slot),
        "dec_vid": np.asarray(st.dec_vid),
        "dec_slot": np.asarray(st.dec_slot),
        "prep_bal": np.where(preparing, st.c_bal, NULL),
        "prop_bal": np.where(active, st.c_bal, NULL),
        "prop_vid": np.where(act2, st.c_prop_vid, NULL),
        "prop_slot": np.where(act2, st.c_prop_slot, NULL),
    }


def representable(slot, exec_slot) -> np.ndarray:
    e = np.asarray(exec_slot)[:, None] >> KBITS
    d = (np.asarray(slot) >> KBITS) - e
    return (np.asarray(slot) != NULL) & (d >= -WRAP_MAX) & (d <= WRAP_MAX)


def test_roundtrip_random_states():
    rng = np.random.default_rng(7)
    for _ in range(20):
        st = random_state(rng)
        ex = expand_blob(make_blob(st))
        ref = legacy_blob_planes(st)

        np.testing.assert_array_equal(ex.tag, st.tag)
        np.testing.assert_array_equal(ex.bal, st.bal)
        np.testing.assert_array_equal(ex.exec_slot, st.exec_slot)
        np.testing.assert_array_equal(ex.prep_bal, ref["prep_bal"])
        np.testing.assert_array_equal(ex.prop_bal, ref["prop_bal"])

        # accepted lanes: slot in window AND ballot delta in [0, DELTA_MAX]
        delta = np.asarray(st.bal)[:, None] - ref["acc_bal"]
        a_ok = (
            representable(ref["acc_slot"], st.exec_slot)
            & (ref["acc_bal"] != NULL) & (delta >= 0) & (delta <= DELTA_MAX)
        )
        for got, want in (
            (ex.acc_slot, ref["acc_slot"]),
            (ex.acc_bal, ref["acc_bal"]),
            (ex.acc_vid, ref["acc_vid"]),
        ):
            np.testing.assert_array_equal(
                np.asarray(got), np.where(a_ok, want, NULL)
            )

        d_ok = representable(ref["dec_slot"], st.exec_slot)
        np.testing.assert_array_equal(
            np.asarray(ex.dec_slot), np.where(d_ok, ref["dec_slot"], NULL)
        )
        np.testing.assert_array_equal(
            np.asarray(ex.dec_vid), np.where(d_ok, ref["dec_vid"], NULL)
        )

        p_ok = representable(ref["prop_slot"], st.exec_slot)
        np.testing.assert_array_equal(
            np.asarray(ex.prop_slot), np.where(p_ok, ref["prop_slot"], NULL)
        )
        np.testing.assert_array_equal(
            np.asarray(ex.prop_vid), np.where(p_ok, ref["prop_vid"], NULL)
        )


def test_wrap_and_delta_boundaries():
    """Exactly-representable extremes survive; one past each NULLs."""
    st = init_state(CFG)
    exec_slot = (WRAP_MAX + 2) * 2 * W  # epoch base with room both ways
    ebase = exec_slot >> KBITS
    cases = [  # (epoch delta, bal delta, survives?)
        (0, 0, True),
        (WRAP_MAX, 0, True),
        (-WRAP_MAX, 0, True),
        (WRAP_MAX + 1, 0, False),
        (-(WRAP_MAX + 1), 0, False),
        (0, DELTA_MAX, True),
        (0, DELTA_MAX + 1, False),
    ]
    bal = DELTA_MAX + 7
    for eps, bd, survives in cases:
        lane = 3
        slot = ((ebase + eps) << KBITS) | lane
        s = st._replace(
            tag=st.tag.at[:].set(1),
            bal=st.bal.at[0].set(bal),
            exec_slot=st.exec_slot.at[0].set(exec_slot),
            acc_slot=st.acc_slot.at[0, lane].set(slot),
            acc_bal=st.acc_bal.at[0, lane].set(bal - bd),
            acc_vid=st.acc_vid.at[0, lane].set(42),
            dec_slot=st.dec_slot.at[0, lane].set(slot),
            dec_vid=st.dec_vid.at[0, lane].set(43),
        )
        ex = expand_blob(make_blob(s))
        if survives:
            assert int(ex.acc_slot[0, lane]) == slot, (eps, bd)
            assert int(ex.acc_bal[0, lane]) == bal - bd, (eps, bd)
            assert int(ex.acc_vid[0, lane]) == 42, (eps, bd)
            assert int(ex.dec_slot[0, lane]) == slot, (eps, bd)
        else:
            assert int(ex.acc_slot[0, lane]) == NULL, (eps, bd)
            assert int(ex.acc_bal[0, lane]) == NULL, (eps, bd)
            assert int(ex.acc_vid[0, lane]) == NULL, (eps, bd)
            if abs(eps) > WRAP_MAX:
                assert int(ex.dec_slot[0, lane]) == NULL, (eps, bd)


def test_coord_word_phases():
    st = init_state(CFG)
    st = st._replace(
        c_phase=jnp.asarray([IDLE, PREPARING, ACTIVE, IDLE, PREPARING,
                             ACTIVE], jnp.int32),
        c_bal=jnp.asarray([5, 6, 7, 8, 9, 10], jnp.int32),
    )
    ex = expand_blob(make_blob(st))
    np.testing.assert_array_equal(
        np.asarray(ex.prep_bal), [NULL, 6, NULL, NULL, 9, NULL]
    )
    np.testing.assert_array_equal(
        np.asarray(ex.prop_bal), [NULL, NULL, 7, NULL, NULL, 10]
    )


def test_wire_frame_roundtrip_and_version_skew():
    from gigapaxos_tpu.net.codec import (
        decode_blob,
        decode_blob_vec,
        encode_blob,
        encode_blob_vec,
    )

    st = random_state(np.random.default_rng(3))
    blob = make_blob(st)
    sender, tick, back = decode_blob(encode_blob(1, 9, blob), CFG)
    assert (sender, tick) == (1, 9)
    for a, b in zip(blob, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    vec = np.asarray(pack_blob(blob))
    assert vec.shape == (blob_vec_len(CFG),)
    s2, t2, v2 = decode_blob_vec(encode_blob_vec(2, 11, vec), CFG)
    assert (s2, t2) == (2, 11)
    np.testing.assert_array_equal(v2, vec)

    # a stale-schema frame (pre-compact 'C' / pre-tag 'B') must be refused
    # loudly, never parsed misaligned
    stale = b"C" + encode_blob_vec(2, 11, vec)[1:]
    with pytest.raises(ValueError, match="schema"):
        decode_blob_vec(stale, CFG)
    with pytest.raises(ValueError, match="schema"):
        decode_blob(b"B" + encode_blob(1, 9, blob)[1:], CFG)


def test_footprint_reduction_at_headline_shape():
    """The acceptance-criterion assert: compact blob bytes/replica at the
    headline bench shape are >= 40% below the all-int32 layout (pure
    arithmetic — runs on CPU, no TPU needed)."""
    cfg = EngineConfig(
        n_groups=1_048_576, window=32, req_lanes=16, n_replicas=3
    )
    compact = 4 * blob_vec_len(cfg)
    legacy = 4 * legacy_blob_vec_len(cfg)
    assert compact <= 0.60 * legacy, (compact, legacy)


def test_footprint_probe_script_runs():
    """CI hook for the budget: the probe prints one JSON line whose
    reduction field clears the 40% floor."""
    import json

    root = Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [sys.executable, str(root / "scripts" / "footprint_probe.py")],
        capture_output=True, text=True, timeout=120, check=True,
    )
    rec = json.loads(out.stdout.strip())
    assert rec["blob_reduction_pct"] >= 40.0, rec
    assert rec["blob_bytes_per_replica"] == 4 * blob_vec_len(
        EngineConfig(n_groups=1_048_576, window=32, req_lanes=16,
                     n_replicas=3)
    )


@pytest.mark.slow
def test_safety_parity_across_many_ring_wraps():
    """Long-run cluster: commit far past the ±WRAP_MAX epoch window so
    live traffic exercises wrap deltas at every bias repeatedly, then
    re-assert the RSM invariant and exact committed order — the compact
    path must be invisible at the safety level."""
    c = SimCluster(CFG)
    c.create_all_groups()
    vid = 1
    sent = []
    # (WRAP_MAX * 4) epochs of slots through group 0
    target = WRAP_MAX * 4 * W
    while True:
        arr = np.full((G, K), NULL, np.int32)
        vids = list(range(vid, vid + K))
        arr[0, :] = vids
        out = c.step_all(reqs={c.coordinator_of(0): arr})
        n = int(np.asarray(out[c.coordinator_of(0)].n_admitted)[0])
        sent.extend(vids[:n])
        vid += K
        if len(sent) >= target:
            break
    c.run(8)
    fr = c.exec_frontiers()
    assert (fr[:, 0] == fr[0, 0]).all(), fr
    assert int(fr[0, 0]) >= target
    c.assert_rsm_invariant()
    committed = [c.checker.chosen[(0, s)] for s in range(int(fr[0, 0]))]
    assert committed == sent[: len(committed)]
