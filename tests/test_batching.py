"""Request coalescing (the true RequestBatcher semantics): many client
requests decided as ONE consensus slot, unpacked at execution with
per-request dedup and callbacks.

Ref: ``RequestBatcher.java:40-158`` (entry batching with adaptive sleep),
``RequestPacket.java:189-246`` (nested `batched` array — up to
MAX_BATCH_SIZE=2000 requests per proposal), ``PaxosManager.java:1226``
(proposeBatched).  Without this, a group's throughput is capped at
req_lanes per tick; with it, at req_lanes * MAX_BATCH_SIZE per tick.
"""

import numpy as np
import pytest

from gigapaxos_tpu.manager import BATCH_BIT, decode_batch, encode_batch
from gigapaxos_tpu.models.apps import HashChainApp, NoopPaxosApp
from gigapaxos_tpu.ops.engine import STOP_BIT, EngineConfig
from gigapaxos_tpu.testing.cluster import ManagerCluster
from gigapaxos_tpu.utils.config import Config


def small_cfg():
    return EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)


def test_batch_codec_roundtrip():
    subs = [
        (1 << 61, 0, "plain"),
        (12345, 2, ""),
        ((1 << 53) + 7, 1, 'json {"a": [1, 2]} é中'),
    ]
    assert decode_batch(encode_batch(subs)) == subs


def test_hot_group_burst_commits_in_few_ticks():
    """500 requests to ONE group: without coalescing this needs >=125
    ticks (K=4 lanes); with it the whole burst rides a handful of slots.
    All callbacks must fire and the SHA-chained state must converge
    identically on every replica (ordering + exactly-once)."""
    c = ManagerCluster(small_cfg(), HashChainApp)
    c.create("hot", members=[0, 1, 2])
    done = {}
    N = 500
    for i in range(N):
        c.submit("hot", f"v{i}", entry=0,
                 callback=lambda rid, r: done.setdefault(rid, r))
    c.run(20)
    assert len(done) == N, f"only {len(done)}/{N} callbacks fired"
    # replica coordinating "hot" used batch vids (not 125+ singleton slots)
    frontier = int(np.asarray(c.managers[0].state.exec_slot)[
        c.managers[0].names["hot"]])
    assert frontier <= 40, f"{frontier} slots used for {N} requests"
    states = [m.app.state.get("hot") for m in c.managers]
    counts = [m.app.n_executed.get("hot") for m in c.managers]
    assert states[0] is not None and len(set(states)) == 1, states
    assert counts == [N, N, N], counts
    c.close()


def test_batched_requests_from_forwarding_entry():
    """Requests entering at a NON-coordinator replica are forwarded,
    coalesced by the coordinator, and their callbacks still fire at the
    original entry replica."""
    c = ManagerCluster(small_cfg(), HashChainApp)
    c.create("fwd", members=[0, 1, 2])
    coord = c.managers[0].coordinator_of_row(c.managers[0].names["fwd"])
    entry = (coord + 1) % 3
    done = {}
    N = 100
    for i in range(N):
        c.submit("fwd", f"v{i}", entry=entry,
                 callback=lambda rid, r: done.setdefault(rid, r))
    c.run(25)
    assert len(done) == N, f"only {len(done)}/{N} callbacks at entry"
    states = [m.app.state.get("fwd") for m in c.managers]
    assert len(set(states)) == 1
    c.close()


def test_stop_never_rides_a_batch():
    """A queue of plain requests plus an epoch-final stop: the stop is
    decided as its own slot (STOP_BIT and BATCH_BIT never combine) and
    the group ends stopped with every prior request executed."""
    c = ManagerCluster(small_cfg(), HashChainApp)
    c.create("s", members=[0, 1, 2])
    done = {}
    N = 40
    for i in range(N):
        c.submit("s", f"v{i}", entry=0,
                 callback=lambda rid, r: done.setdefault(rid, r))
    c.submit("s", "", entry=0, stop=True)
    c.run(25)
    m0 = c.managers[0]
    assert m0.is_stopped("s")
    assert len(done) == N
    # no vid in any journal/arena ever carried both bits
    for m in c.managers:
        for vid in list(m.arena) + list(m.retained):
            assert not ((vid & STOP_BIT) and (vid & BATCH_BIT)), hex(vid)
    counts = [m.app.n_executed.get("s") for m in c.managers]
    assert len(set(counts)) == 1, counts
    c.close()


def test_retransmit_of_batched_request_dedups():
    """A request id retransmitted while its original rides a batch must
    not execute twice; a retransmit after commit gets the cached
    response."""
    c = ManagerCluster(small_cfg(), HashChainApp)
    c.create("d", members=[0, 1, 2])
    rid = 1 << 55
    responses = []
    # enough neighbors to force coalescing of the tracked request
    for i in range(30):
        c.submit("d", f"n{i}", entry=0)
    c.managers[0].propose("d", "tracked", request_id=rid,
                          callback=lambda r, resp: responses.append(resp))
    # retransmit BEFORE commit: in-flight dedup repointed to the batch vid
    c.managers[0].propose("d", "tracked", request_id=rid,
                          callback=lambda r, resp: responses.append(resp))
    c.run(20)
    # retransmit AFTER commit: answered from the response cache
    c.managers[0].propose("d", "tracked", request_id=rid,
                          callback=lambda r, resp: responses.append(resp))
    c.run(2)
    assert len(responses) >= 2  # original + cached retransmit
    assert len(set(r for r in responses if r is not None)) == 1
    n = c.managers[0].app.n_executed["d"]
    assert n == 31, f"{n} executions for 31 logical requests"
    c.close()


def test_unbatched_mode_still_works():
    """BATCHING_ENABLED=false must fall back to one-request-per-slot."""
    Config.set("BATCHING_ENABLED", "false")
    try:
        c = ManagerCluster(small_cfg(), HashChainApp)
        c.create("u", members=[0, 1, 2])
        done = {}
        for i in range(20):
            c.submit("u", f"v{i}", entry=0,
                     callback=lambda rid, r: done.setdefault(rid, r))
        c.run(15)
        assert len(done) == 20
        for m in c.managers:
            for vid in list(m.retained):
                assert not (vid & BATCH_BIT)
        c.close()
    finally:
        Config.clear()


def test_batch_survives_crash_recovery(tmp_path):
    """Batch payloads are journaled like any payload: a replica restarted
    mid-stream replays decided batches and converges to the same chain."""
    dirs = [str(tmp_path / f"n{r}") for r in range(3)]
    cfg = small_cfg()
    c = ManagerCluster(cfg, HashChainApp, log_dirs=dirs)
    c.create("r", members=[0, 1, 2])
    for i in range(60):
        c.submit("r", f"v{i}", entry=0)
    c.run(15)
    states = [m.app.state.get("r") for m in c.managers]
    assert len(set(states)) == 1 and states[0] is not None
    c.close()

    from gigapaxos_tpu.manager import PaxosManager

    m = PaxosManager(0, HashChainApp(), cfg, log_dir=dirs[0])
    assert m.app.state.get("r") == states[0]
    assert m.app.n_executed.get("r") == 60
    m.close()


def test_forward_batch_preserves_fifo_around_stop():
    """A non-coordinator entry forwards its whole queue run as ONE
    forward_batch frame; requests queued BEFORE a stop must commit
    before it (proposing the stop first would bump the epoch and drop
    them as stale — review find on the batched forward path)."""
    cfg = small_cfg()
    c = ManagerCluster(cfg, HashChainApp)
    c.create("f", members=[0, 1, 2])
    row = c.managers[0].names["f"]
    coord = c.managers[0].coordinator_of_row(row)
    entry = (coord + 1) % 3  # a NON-coordinator entry replica
    for i in range(5):
        c.submit("f", f"pre{i}", entry=entry)
    c.submit("f", "", entry=entry, stop=True)
    c.run(20)
    for m in c.managers:
        # all five pre-stop requests executed (the chain advanced 5+ --
        # the stop itself also chains), and the group is stopped
        assert m.app.n_executed.get("f", 0) >= 5, m.app.n_executed
        assert int(np.asarray(m.state.stopped)[row]) == 1
    states = {m.app.state.get("f") for m in c.managers}
    assert len(states) == 1
    c.close()


def test_propose_batch_outcomes():
    """The batched ingress reports the same per-request outcomes the
    singleton path implements: queued, cached (callback fired from the
    response cache), inflight (callback re-registered), unknown."""
    cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=1)
    c = ManagerCluster(cfg, HashChainApp)
    m = c.managers[0]
    c.create("b", members=[0])
    rid = 1 << 56
    got = []
    res = m.propose_batch([
        ("b", "v0", rid, lambda r, resp: got.append(resp)),
        ("nope", "v1", rid + 1, None),
    ])
    assert [r[1] for r in res] == ["queued", "unknown"]

    # same id again while the original is still undecided -> inflight
    res = m.propose_batch([("b", "v0", rid, lambda r, resp: got.append(resp))])
    assert res[0][1] == "inflight"

    c.run(8)  # decide + execute
    assert got, "callback never fired"
    first_resp = got[-1]

    # after execution the id answers from the cache, callback fires
    res = m.propose_batch([("b", "v0", rid, lambda r, resp: got.append(resp))])
    assert res[0][1] == "cached" and res[0][2] == first_resp
    assert got[-1] == first_resp

    # vid-counter exhaustion fails PER ITEM: cached entries in the same
    # frame still answer (no whole-frame raise, no discarded responses)
    from gigapaxos_tpu.manager import VID_COUNTER_MASK

    m._next_counter = VID_COUNTER_MASK + 1
    res = m.propose_batch([
        ("b", "v0", rid, lambda r, resp: got.append(resp)),
        ("b", "fresh", rid + 7, None),
    ])
    assert [r[1] for r in res] == ["cached", "exhausted"]
    assert got[-1] == first_resp
    c.close()
