"""Runtime add/remove of reconfigurators (VERDICT r4 missing #1).

The reference can grow/shrink the control plane itself:
``Reconfigurator.handleReconfigureRCNodeConfig``
(ref ``Reconfigurator.java:1023-1075``), integration-tested as tests 31/32
(``TESTReconfigurationClient.java:676-1078``).  Here the record RSM stops
its current epoch and restarts under the target set (epoch-final stop ->
deterministic re-create -> RCJoinTask -> RC_NODE_DONE); ring ownership of
every record re-splits at the stop point.  These tests add a standby RC,
then remove a founding RC, and require records to stay consistent and
reachable throughout — including creates ingressing at the removed node.
"""

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import RCState
from gigapaxos_tpu.reconfiguration.reconfigurator import RC_GROUP
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


def _wait_ack(c, kind, budget=400):
    body = c.wait_for(kind, max_steps=budget)
    assert body is not None, f"no {kind} within {budget} steps"
    return body


def _records_agree(c, names, members):
    for nm in names:
        views = [c.reconfigurators[j].rc_app.get_record(nm) for j in members]
        datas = [None if v is None else v.to_json() for v in views]
        assert all(d == datas[0] for d in datas), (nm, datas)


def _make_cluster():
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=4)
    return ReconfigurableCluster(
        ar_cfg, rc_cfg, HashChainApp, rc_members=[0, 1, 2]
    )


def test_add_then_remove_reconfigurator():
    c = _make_cluster()
    try:
        names = [f"svc{i}" for i in range(4)]
        for nm in names:
            c.client_request("create_service", {"name": nm})
            body = _wait_ack(c, "create_ack")
            assert body["ok"], body

        # ---- test 31 analog: add the standby RC 3 at runtime ----------
        c.client_request("add_reconfigurator", {"id": 3})
        body = _wait_ack(c, "add_reconfigurator_ack")
        assert body["ok"], body
        assert body["reconfigurators"] == [0, 1, 2, 3]

        # every RC (including the joiner) hosts the record RSM's new epoch
        for _ in range(200):
            c.step()
            epochs = [
                c.rcs.managers[j].current_epoch(RC_GROUP) for j in range(4)
            ]
            if epochs == [1, 1, 1, 1]:
                break
        assert epochs == [1, 1, 1, 1], epochs
        # the joiner healed the record map through state transfer
        for _ in range(400):
            if all(c.reconfigurators[3].rc_app.get_record(nm) is not None
                   for nm in names):
                break
            c.step()
        _records_agree(c, names, members=[0, 1, 2, 3])
        # ring ownership re-split onto the grown set everywhere
        for j in range(4):
            assert c.reconfigurators[j].rc_ring.nodes == [0, 1, 2, 3]

        # records stay reachable: traffic + a migration through the new RC
        c.ars.managers[0].propose(names[0], "after-add")
        c.client_request("reconfigure",
                         {"name": names[0], "new_actives": [0, 1, 2]},
                         rc=3)
        body = _wait_ack(c, "reconfigure_ack")
        assert body["ok"], body

        # ---- test 32 analog: remove founding RC 0 at runtime ----------
        c.client_request("remove_reconfigurator", {"id": 0}, rc=1)
        body = _wait_ack(c, "remove_reconfigurator_ack")
        assert body["ok"], body
        assert body["reconfigurators"] == [1, 2, 3]

        for _ in range(200):
            c.step()
            epochs = [
                c.rcs.managers[j].current_epoch(RC_GROUP) for j in range(4)
            ]
            if epochs[0] is None and epochs[1:] == [2, 2, 2]:
                break
        assert epochs[0] is None and epochs[1:] == [2, 2, 2], epochs
        _records_agree(c, names, members=[1, 2, 3])
        for j in range(4):
            assert c.reconfigurators[j].rc_ring.nodes == [1, 2, 3], j

        # the removed node still forwards: a create ingressing at RC 0
        c.client_request("create_service", {"name": "post-remove"}, rc=0)
        body = _wait_ack(c, "create_ack")
        assert body["ok"], body
        rec = c.reconfigurators[1].rc_app.get_record("post-remove")
        assert rec is not None and rec.state is RCState.READY

        # and the data plane still settles: all records READY, RSM agrees
        for nm in names:
            rec = c.reconfigurators[1].rc_app.get_record(nm)
            assert rec is not None and rec.state in (
                RCState.READY, RCState.PAUSED
            ), (nm, rec.to_json())
    finally:
        c.close()


def test_add_reconfigurator_below_all_members():
    """Adding an RC whose id sorts FIRST (id 0 under members [1,2,3]):
    the phase-3 driver must come from the survivor set — deferring to the
    not-yet-joined node would deadlock the transition (review find)."""
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=3)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=4)
    c = ReconfigurableCluster(
        ar_cfg, rc_cfg, HashChainApp, rc_members=[1, 2, 3]
    )
    try:
        c.client_request("create_service", {"name": "low"}, rc=1)
        assert _wait_ack(c, "create_ack")["ok"]
        c.client_request("add_reconfigurator", {"id": 0}, rc=1)
        body = _wait_ack(c, "add_reconfigurator_ack")
        assert body["ok"] and body["reconfigurators"] == [0, 1, 2, 3], body
        for _ in range(200):
            c.step()
            epochs = [
                c.rcs.managers[j].current_epoch(RC_GROUP) for j in range(4)
            ]
            if epochs == [1, 1, 1, 1]:
                break
        assert epochs == [1, 1, 1, 1], epochs
        for _ in range(400):
            if c.reconfigurators[0].rc_app.get_record("low") is not None:
                break
            c.step()
        _records_agree(c, ["low"], members=[0, 1, 2, 3])
    finally:
        c.close()


def test_add_survives_driver_restart_after_stop():
    """The first-sorted survivor restarts AFTER executing the epoch-final
    stop but BEFORE its phase-2 epoch switch, losing the in-memory
    stop-time capture (``_rc_final``).  Peers defer phase 3 to it forever
    (it is alive and sorts first), so unless it can reconstruct the
    capture from its own stopped group, the whole transition wedges
    (review find).  The member set is immutable within an epoch, which is
    exactly what makes the reconstruction sound."""
    c = _make_cluster()
    try:
        c.client_request("create_service", {"name": "svc"}, rc=1)
        assert _wait_ack(c, "create_ack")["ok"]

        # node 0 (first-sorted survivor) drives phase 1 normally, then
        # "crashes" the instant its stop executes: it never runs phase 2
        rc0 = c.reconfigurators[0]
        orig = type(rc0)._advance_rc_transition

        def crashed_after_stop():
            if c.rcs.managers[0].is_stopped(RC_GROUP):
                return  # down from the stop execution onward
            orig(rc0)

        rc0._advance_rc_transition = crashed_after_stop

        c.client_request("add_reconfigurator", {"id": 3}, rc=1)
        for _ in range(300):
            c.step()
            if c.rcs.managers[0].is_stopped(RC_GROUP):
                break
        assert c.rcs.managers[0].is_stopped(RC_GROUP)

        # "restart": the in-memory scratch is gone; the durable state
        # (the stopped group itself) survives
        rc0._rc_final = None
        del rc0.__dict__["_advance_rc_transition"]

        body = _wait_ack(c, "add_reconfigurator_ack", budget=800)
        assert body["ok"] and body["reconfigurators"] == [0, 1, 2, 3], body
        for _ in range(300):
            c.step()
            epochs = [
                c.rcs.managers[j].current_epoch(RC_GROUP) for j in range(4)
            ]
            if epochs == [1, 1, 1, 1]:
                break
        assert epochs == [1, 1, 1, 1], epochs
        for _ in range(400):
            if c.reconfigurators[3].rc_app.get_record("svc") is not None:
                break
            c.step()
        _records_agree(c, ["svc"], members=[0, 1, 2, 3])
    finally:
        c.close()


def test_remove_reconfigurator_via_self():
    """A remove ingressing AT the node being removed, and a later re-add
    ingressing AT the (now non-member) removed node: both must forward to
    a live member — the target node never applies RC_NODE_DONE (its ack
    would leak), and a non-member's propose silently returns None
    (review finds)."""
    c = _make_cluster()
    try:
        c.client_request("remove_reconfigurator", {"id": 2}, rc=2)
        body = _wait_ack(c, "remove_reconfigurator_ack", budget=800)
        assert body["ok"] and body["reconfigurators"] == [0, 1], body

        c.client_request("add_reconfigurator", {"id": 2}, rc=2)
        body = _wait_ack(c, "add_reconfigurator_ack", budget=800)
        assert body["ok"] and body["reconfigurators"] == [0, 1, 2], body
        for _ in range(300):
            c.step()
            epochs = [
                c.rcs.managers[j].current_epoch(RC_GROUP) for j in range(3)
            ]
            if epochs == [2, 2, 2]:
                break
        assert epochs == [2, 2, 2], epochs
    finally:
        c.close()


def test_rc_membership_guards():
    c = _make_cluster()
    try:
        # duplicate add of an existing member: idempotent ok, no epoch bump
        c.client_request("add_reconfigurator", {"id": 1})
        body = _wait_ack(c, "add_reconfigurator_ack")
        assert body["ok"], body
        assert c.rcs.managers[0].current_epoch(RC_GROUP) == 0

        # removing down to one node is refused at the floor
        for nid, expect_ok in ((0, True), (1, True), (2, False)):
            c.client_request("remove_reconfigurator", {"id": nid}, rc=2)
            body = _wait_ack(c, "remove_reconfigurator_ack", budget=800)
            assert body["ok"] is expect_ok, (nid, body)
        assert c.reconfigurators[2].rc_ring.nodes == [2]
    finally:
        c.close()
