"""Checkpoint-transfer tests: a replica stranded beyond every peer's ring
window recovers via a state jump (StatePacket / ``handleCheckpoint``,
``PaxosInstanceStateMachine.java:1744``; ``PaxosAcceptor.jumpSlot:538``) —
the VERDICT r1 'straggler has no recovery story' gap."""

import os

import numpy as np

from gigapaxos_tpu.manager import PaxosManager
from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.testing.cluster import DELIVER, DROP, ManagerCluster


def _isolate(R, dead):
    d = np.full((R, R), DELIVER)
    d[dead, :] = DROP
    d[:, dead] = DROP
    return d


def _run_until_executed(c, name, vals, entry, delivery=None, max_steps=60):
    done = {}
    for v in vals:
        c.managers[entry].propose(
            name, v, callback=lambda r, resp: done.setdefault(r, resp)
        )
    for _ in range(max_steps):
        if len(done) == len(vals):
            return done
        c.step_all(delivery=delivery)
    raise AssertionError(f"{len(done)}/{len(vals)} executed")


def test_dead_replica_rejoins_via_checkpoint_jump(tmp_path):
    # batching off: this test drives the frontier far past the ring by
    # slot COUNT, and coalescing would pack each burst into ~2 slots
    from gigapaxos_tpu.utils.config import Config

    Config.set("BATCHING_ENABLED", "false")
    try:
        _jump_body(tmp_path)
    finally:
        Config.clear()


def _jump_body(tmp_path):
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    dirs = [os.path.join(str(tmp_path), f"n{i}") for i in range(3)]
    c = ManagerCluster(cfg, HashChainApp, log_dirs=dirs)
    c.create("svc", members=[0, 1, 2])
    row = c.managers[0].names["svc"]
    _run_until_executed(c, "svc", [f"a{i}" for i in range(4)], entry=0)

    # node 2 dies; peers advance FAR past the ring window (W=8) and past
    # the payload-retention horizon (4W=32), so nothing node 2 needs
    # survives in any ring or arena
    c.managers[2].close()
    dead = _isolate(3, 2)
    for batch in range(6):
        _run_until_executed(
            c, "svc", [f"b{batch}-{i}" for i in range(10)],
            entry=0, delivery=dead,
        )
    live_exec = int(np.asarray(c.managers[0].state.exec_slot)[row])
    dead_exec = int(np.asarray(c.managers[2].state.exec_slot)[row])
    assert live_exec - dead_exec > 5 * cfg.window
    # retention horizon: peers must NOT be pinning every payload for the
    # dead member (the watermark writes it off beyond the jump horizon)
    assert len(c.managers[0].arena) < 50

    # node 2 restarts from its own (stale) journal and rejoins
    c.managers[2] = PaxosManager(2, HashChainApp(), cfg, log_dir=dirs[2])
    c.blobs[2] = c.managers[2].blob()
    for _ in range(80):
        c.step_all()
        if int(np.asarray(c.managers[2].state.exec_slot)[row]) >= live_exec:
            break
    # reconverged: identical device hash chains and app state everywhere
    h = [int(np.asarray(m.state.app_hash)[row]) for m in c.managers]
    assert h[0] == h[1] == h[2], h
    apps = [m.app for m in c.managers]
    assert apps[2].state["svc"] == apps[0].state["svc"]
    assert apps[2].n_executed["svc"] == apps[0].n_executed["svc"]

    # and the rejoined replica participates in new traffic
    _run_until_executed(c, "svc", ["post-jump-1", "post-jump-2"], entry=2)
    assert apps[2].state["svc"] == apps[0].state["svc"]
    for m in c.managers:
        m.close()


def test_jump_not_triggered_within_window(tmp_path):
    """A replica only briefly behind (< W) must catch up through the rings,
    never through a jump (no state_request traffic)."""
    cfg = EngineConfig(n_groups=4, window=16, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, HashChainApp)
    c.create("svc", members=[0, 1, 2])
    row = c.managers[0].names["svc"]
    # drop node 2 for a couple of steps while a few slots commit
    dead = _isolate(3, 2)
    _run_until_executed(c, "svc", ["x1", "x2", "x3"], entry=0, delivery=dead)
    assert c.managers[2]._last_state_req == {}
    for _ in range(20):
        c.step_all()
    assert c.managers[2]._last_state_req == {}  # rings closed the gap
    h = [int(np.asarray(m.state.app_hash)[row]) for m in c.managers]
    assert h[0] == h[1] == h[2]
