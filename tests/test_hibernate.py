"""Hibernate (checkpoint + sleep on disk) and local restore — the
``PaxosManager.hibernate``/``restore`` analog (``PaxosManager.java:
2209-2252``) — plus the linwrites example (linearizable writes, local
reads: ``examples/linwrites/LinWritesLocReadsApp.java``)."""

import numpy as np

from gigapaxos_tpu.models.apps import HashChainApp, LinWritesLocReadsApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration.coordinator import PaxosReplicaCoordinator
from gigapaxos_tpu.testing.cluster import ManagerCluster


def _converged(c, name):
    states = {m.app.state.get(name) for m in c.managers}
    return states.pop() if len(states) == 1 else None


def test_hibernate_restore(tmp_path):
    cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    dirs = [str(tmp_path / f"n{r}") for r in range(3)]
    c = ManagerCluster(cfg, HashChainApp, log_dirs=dirs)
    try:
        c.create("svc", members=[0, 1, 2])
        for i in range(5):
            c.submit("svc", f"v{i}")
            c.run(4)
        for _ in range(40):
            c.run(1)
            h0 = _converged(c, "svc")
            if h0 is not None and all(
                m.app.n_executed.get("svc") == 5 for m in c.managers
            ):
                break
        assert h0 is not None

        # hibernate everywhere: rows freed, records journaled AND paged
        # out of RAM (demote), instance gone from the live tables
        for m in c.managers:
            assert m.hibernate("svc")
            assert m.names.get("svc") is None
            assert ("svc", 0) in m.paused
            assert m.paused.n_in_memory == 0  # sleeping on disk
        c.blobs = [m.blob() for m in c.managers]
        c.run(3)

        # a second hibernate (unknown name now) reports failure
        assert not c.managers[0].hibernate("svc")

        # local wake-up: full rollback to the snapshot, deterministic row
        for m in c.managers:
            assert m.restore("svc")
            assert m.names.get("svc") is not None
        c.blobs = [m.blob() for m in c.managers]
        c.run(5)
        assert _converged(c, "svc") == h0
        rows = {m.names["svc"] for m in c.managers}
        assert len(rows) == 1  # default_row_for realigned everyone

        # traffic resumes, exactly-once preserved
        c.submit("svc", "after")
        got = None
        for _ in range(60):
            c.run(1)
            got = _converged(c, "svc")
            if got is not None and got != h0 and all(
                m.app.n_executed.get("svc") == 6 for m in c.managers
            ):
                break
        assert got is not None and got != h0
        assert all(m.app.n_executed.get("svc") == 6 for m in c.managers)
        # restore of an already-awake name is a no-op success
        assert c.managers[0].restore("svc")
        # restore of an unknown name fails
        assert not c.managers[0].restore("nope")
    finally:
        c.close()


def test_linwrites_local_reads():
    cfg = EngineConfig(n_groups=4, window=8, req_lanes=4, n_replicas=3)
    c = ManagerCluster(cfg, LinWritesLocReadsApp)
    try:
        c.create("k", members=[0, 1, 2])
        coords = [
            PaxosReplicaCoordinator(m.app, m) for m in c.managers
        ]
        answers = []
        # coordinated write: goes through consensus, lands on every replica
        assert coords[0].coordinate_request(
            "k", "7", callback=lambda rid, resp: answers.append(resp)
        )
        for _ in range(40):
            c.run(1)
            if all(m.app.totals.get("k") == 7 for m in c.managers):
                break
        assert all(m.app.totals.get("k") == 7 for m in c.managers)
        assert answers == ["7"]

        # local read: answered immediately from THIS replica, no consensus
        # traffic (frontiers unchanged), re-sends just re-read
        row = c.managers[1].names["k"]
        fr_before = int(np.asarray(c.managers[1].state.exec_slot)[row])
        reads = []
        for _ in range(3):
            assert coords[1].coordinate_request(
                "k", LinWritesLocReadsApp.READ,
                callback=lambda rid, resp: reads.append(resp),
            )
        assert reads == ["7", "7", "7"]
        c.run(3)
        assert int(
            np.asarray(c.managers[1].state.exec_slot)[row]
        ) == fr_before  # reads never entered consensus
        # reads against an unknown name report failure
        assert not coords[1].coordinate_request(
            "nope", LinWritesLocReadsApp.READ
        )
    finally:
        c.close()
