"""RC primary failover (WaitPrimaryExecution analog) and demand-driven
reconfiguration (handleDemandReport -> AbstractDemandProfile ->
auto-migration)."""

import time

import pytest

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import RCState
from gigapaxos_tpu.reconfiguration.demand import (
    AbstractDemandProfile,
    AggregateDemandProfiler,
    DemandProfile,
)
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


def make_cluster(**kw):
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    return ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp, **kw)


def create(c, name, actives):
    c.client_request("create_service", {"name": name, "actives": actives})
    ack = c.wait_for("create_ack", max_steps=120)
    assert ack and ack["ok"], ack


def test_secondary_rc_completes_migration_after_primary_death(monkeypatch):
    """Kill the record's primary RC mid-migration: a secondary adopts the
    re-drive (primary_of skips dead RCs) and the migration completes
    (match WaitPrimaryExecution.java:60)."""
    from gigapaxos_tpu.reconfiguration import reconfigurator as rc_mod

    c = make_cluster()
    try:
        # fast re-drives so the takeover happens within test steps
        for rc in c.reconfigurators:
            rc.REDRIVE_EVERY = 4
        create(c, "ha", [0, 1, 2])
        primary = c.reconfigurators[0].primary_of("ha")
        # start a migration but IMMEDIATELY cut the primary off: its start
        # round dies with it, stranding the record mid-transition
        c.client_request("reconfigure", {"name": "ha", "new_actives": [1, 2, 3]},
                         rc=primary)
        for _ in range(3):
            c.step()
        c.dead_rcs.add(primary)
        c.msg_filter = lambda dst, kind, body: dst != ("RC", primary)
        # the layer object of the dead primary stops driving entirely
        dead_layer = c.reconfigurators[primary]
        monkeypatch.setattr(dead_layer, "tick", lambda now=None: None)
        monkeypatch.setattr(
            dead_layer, "handle_message", lambda *a, **k: None
        )

        deadline = time.time() + 30
        rec = None
        while time.time() < deadline:
            c.step()
            rec = c.reconfigurators[(primary + 1) % 3].rc_app.get_record("ha")
            if rec is not None and rec.state is RCState.READY \
                    and sorted(rec.actives) == [1, 2, 3]:
                break
        assert rec is not None and rec.state is RCState.READY, rec
        assert sorted(rec.actives) == [1, 2, 3]
        # the new epoch actually serves
        done = {}
        for _ in range(240):
            if done:
                break
            c.ars.managers[1].propose(
                "ha", "post-failover",
                callback=lambda rid, r: done.setdefault(rid, r),
            )
            c.step()
        assert done, "migrated group does not serve after RC failover"
    finally:
        c.close()


class HotSpotProfile(AbstractDemandProfile):
    """Test policy: once cumulative demand crosses a threshold, migrate to
    the configured target set (stands in for locality policies like the
    reference's GeoIpDemandProfile)."""

    THRESHOLD = 12
    TARGET = [1, 2, 3]

    def __init__(self, name):
        super().__init__(name)
        self.total = 0

    def combine(self, report):
        self.total += int(report.get("count", 0))

    def reconfigure(self, cur_actives, all_actives):
        if self.total >= self.THRESHOLD:
            return [a for a in self.TARGET if a in all_actives]
        return None

    def just_reconfigured(self):
        self.total = 0


def test_demand_report_drives_auto_migration():
    """Sustained load on a name auto-migrates it via the demand pipeline:
    AR counts -> demand_report -> primary's profile -> RECONFIGURE_INTENT
    (match Reconfigurator.java:311, AbstractDemandProfile.java:103-149)."""
    c = make_cluster(demand_profile_cls=HotSpotProfile)
    try:
        # fast demand flushes
        for ar in c.active_replicas:
            ar.demand_report_period_s = 0.05
        create(c, "hot", [0, 1, 2])
        done = {}
        deadline = time.time() + 40
        rec = None
        i = 0
        while time.time() < deadline:
            i += 1
            c.ars.managers[0].propose(
                "hot", f"v{i}", callback=lambda rid, r: done.setdefault(rid, r)
            )
            c.step()
            rec = c.reconfigurators[0].rc_app.get_record("hot")
            if rec.state is RCState.READY and sorted(rec.actives) == [1, 2, 3]:
                break
        assert rec is not None and sorted(rec.actives) == [1, 2, 3], (
            f"demand did not migrate: {rec and rec.to_json()}"
        )
        assert rec.epoch == 1
    finally:
        c.close()


def test_default_profile_measures_but_never_migrates():
    prof = DemandProfile("x")
    prof.combine({"count": 1000, "from": 0})
    assert prof.num_requests == 1000
    assert prof.reconfigure([0, 1, 2], [0, 1, 2, 3]) is None
    profiler = AggregateDemandProfiler(DemandProfile)
    p = profiler.combine("x", {"count": 5, "from": 1})
    assert p.num_requests == 5


def test_elastic_membership_remove_and_add_active():
    """Remove an active at runtime: the replicated AR set shrinks, rings
    refresh on every RC, and the removed node's groups auto-migrate off it
    (match Reconfigurator.java:1023-1075); re-adding restores the pool."""
    c = make_cluster()
    try:
        for rc in c.reconfigurators:
            rc.REDRIVE_EVERY = 4
        create(c, "el", [0, 1, 2])
        done = {}
        for i in range(3):
            c.ars.managers[0].propose(
                "el", f"v{i}", callback=lambda rid, r: done.setdefault(rid, r)
            )
        for _ in range(60):
            if len(done) == 3:
                break
            c.step()
        assert len(done) == 3

        c.client_request("remove_active", {"id": 0})
        ack = c.wait_for("remove_active_ack", max_steps=120)
        assert ack and ack["ok"], ack
        assert 0 not in ack["actives"]

        # the group migrates off node 0 via the re-drive scan
        deadline = time.time() + 30
        rec = None
        while time.time() < deadline:
            c.step()
            rec = c.reconfigurators[0].rc_app.get_record("el")
            if rec.state is RCState.READY and 0 not in rec.actives \
                    and len(rec.actives) == 3:
                break
        assert rec is not None and 0 not in rec.actives, rec.to_json()
        assert sorted(rec.actives) == [1, 2, 3]
        # old host dropped the group; survivors serve with state intact
        deadline = time.time() + 20
        while time.time() < deadline:
            if c.ars.managers[0].names.get("el") is None:
                break
            c.step()
        assert c.ars.managers[0].names.get("el") is None
        done2 = {}
        for _ in range(240):
            if done2:
                break
            c.ars.managers[1].propose(
                "el", "after", callback=lambda rid, r: done2.setdefault(rid, r)
            )
            c.step()
        assert done2, "group does not serve after membership removal"
        a1 = c.ars.managers[1].app
        assert a1.n_executed["el"] >= 4

        # re-admit node 0
        c.client_request("add_active", {"id": 0})
        ack = c.wait_for("add_active_ack", max_steps=120)
        assert ack and ack["ok"] and 0 in ack["actives"], ack
        # explicit migration back onto it works
        c.client_request("reconfigure", {"name": "el", "new_actives": [0, 1, 2]})
        ack = c.wait_for("reconfigure_ack", max_steps=200)
        assert ack and ack["ok"], ack
    finally:
        c.close()


def test_election_fires_for_alive_nonmember_coordinator():
    """Chaos-soak find (seed 20260730): elastic membership can leave a
    group whose ballot coordinator is ALIVE but no longer a member — it
    will never serve the group, yet no election fired because the node
    still answered pings.  A non-member coordinator must count as dead
    (long-dead included, so any member may run)."""
    import numpy as np

    from gigapaxos_tpu.failure_detection import FailureDetector
    from gigapaxos_tpu.ops.ballot import encode_ballot

    bal = np.array([int(encode_ballot(5, 2))])  # coordinator = node 2
    mask = np.array([0b011])                    # members {0, 1} only
    for me, expect in ((0, True), (1, True), (2, False)):
        fd = FailureDetector(me, [0, 1, 2])     # everyone recently heard
        want = fd.want_coord(bal, mask, 3)
        assert bool(want[0]) is expect, (me, want)
    # sanity: a MEMBER coordinator that is up triggers nothing
    bal_ok = np.array([int(encode_ballot(5, 1))])
    fd = FailureDetector(0, [0, 1, 2])
    assert not fd.want_coord(bal_ok, mask, 3).any()


def test_proximity_profile_migrates_toward_demand_region():
    """GeoIP-profile analog: with a REGION map configured and one entry
    active sourcing the dominant traffic share, the name migrates onto
    that active's region (ref: the fork's GeoIpDemandProfile.java:1-80)."""
    from gigapaxos_tpu.reconfiguration.demand import ProximityDemandProfile
    from gigapaxos_tpu.utils.config import Config

    Config.set("REGION.0", "east")
    Config.set("REGION.1", "east")
    Config.set("REGION.2", "west")
    Config.set("REGION.3", "west")
    try:
        c = make_cluster(demand_profile_cls=ProximityDemandProfile)
        try:
            for ar in c.active_replicas:
                ar.demand_report_period_s = 0.05
            # hosted mostly in the WEST, but all traffic enters via 0 (east)
            create(c, "geo", [0, 2, 3])
            deadline = time.time() + 40
            rec = None
            i = 0
            while time.time() < deadline:
                i += 1
                c.ars.managers[0].propose("geo", f"v{i}")
                c.step()
                rec = c.reconfigurators[0].rc_app.get_record("geo")
                if rec.state is RCState.READY and \
                        sorted(rec.actives) == [0, 1, 2]:
                    break
            # east region only has 2 actives; the top-up keeps size 3
            assert rec is not None and rec.epoch >= 1, rec and rec.to_json()
            assert 1 in rec.actives and 0 in rec.actives, rec.to_json()
            assert rec.actives[0] == 0  # anchored at the hot entry
        finally:
            c.close()
    finally:
        Config.clear()


def test_proximity_profile_measures_only_without_region_map():
    from gigapaxos_tpu.reconfiguration.demand import ProximityDemandProfile

    p = ProximityDemandProfile("x")
    for _ in range(10):
        p.combine({"count": 100, "from": 0})
    assert p.reconfigure([0, 1, 2], [0, 1, 2, 3]) is None
