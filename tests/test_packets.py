"""Packet serialization round-trips (mirrors ``RequestPacketTest.java``)."""

from gigapaxos_tpu.packets import (
    AcceptPacket,
    Ballot,
    FailureDetectionPacket,
    PaxosPacket,
    PaxosPacketType,
    PreparePacket,
    PrepareReplyPacket,
    PValuePacket,
    RequestPacket,
    packet_from_json,
)


def test_request_roundtrip_json():
    req = RequestPacket(
        paxos_id="svc0", version=3, request_value="hello world", stop=True,
        entry_replica=1, client_address=("127.0.0.1", 9999),
    )
    back = packet_from_json(req.to_json())
    assert isinstance(back, RequestPacket)
    assert back.paxos_id == "svc0" and back.version == 3
    assert back.request_value == "hello world"
    assert back.stop and back.entry_replica == 1
    assert back.client_address == ("127.0.0.1", 9999)
    assert back.request_id == req.request_id


def test_request_roundtrip_bytes():
    req = RequestPacket(paxos_id="x", request_value="v" * 100)
    data = req.to_bytes()
    back = PaxosPacket.from_bytes(data)
    assert isinstance(back, RequestPacket)
    assert back.request_value == req.request_value


def test_batched_requests():
    reqs = [RequestPacket(paxos_id="s", request_value=f"r{i}") for i in range(5)]
    head = reqs[0].latch_to_batch(reqs[1:])
    assert head.batch_size() == 5
    back = packet_from_json(packet_from_json(head.to_json()).to_json())
    assert back.batch_size() == 5
    assert [r.request_value for r in back.flatten()] == [f"r{i}" for i in range(5)]


def test_pvalue_and_accept():
    acc = AcceptPacket(
        paxos_id="g", slot=42, ballot_num=7, ballot_coord=2,
        request_value="payload", sender=0,
    )
    back = PaxosPacket.from_bytes(acc.to_bytes())
    assert isinstance(back, AcceptPacket)
    assert back.PACKET_TYPE == PaxosPacketType.ACCEPT
    assert back.slot == 42 and back.ballot == Ballot(7, 2)


def test_prepare_reply_accepted_map():
    pr = PrepareReplyPacket(
        paxos_id="g", acceptor=1, ballot_num=3, ballot_coord=0,
        accepted={5: PValuePacket(paxos_id="g", slot=5, ballot_num=2,
                                  ballot_coord=1, request_value="v5")},
    )
    back = packet_from_json(pr.to_json())
    assert isinstance(back, PrepareReplyPacket)
    assert back.accepted[5].request_value == "v5"
    assert back.accepted[5].slot == 5


def test_prepare_and_fd():
    p = PreparePacket(paxos_id="g", ballot_num=9, ballot_coord=1,
                      first_undecided_slot=17)
    assert packet_from_json(p.to_json()).first_undecided_slot == 17
    fd = FailureDetectionPacket(sender="AR0", responder="AR1", send_time=1.25)
    back = packet_from_json(fd.to_json())
    assert back.sender == "AR0" and back.send_time == 1.25


def test_ballot_ordering():
    assert Ballot(2, 1) > Ballot(1, 9)
    assert Ballot(2, 3) > Ballot(2, 1)
    assert Ballot.parse("5:2") == Ballot(5, 2)
