"""Placement plane (ProximateBalance analog + EchoRequest probing):
decision-level policy behavior (hot-spot spreading, hysteresis, cooldown),
the echo-probe RTT/load matrix, placement-driven migration end to end,
and stats surfacing through the ``stats`` admin op and the RC HTTP front.
"""

import json
import time
import urllib.request

import pytest

from gigapaxos_tpu.models.apps import HashChainApp
from gigapaxos_tpu.obs.metrics import MetricsRegistry
from gigapaxos_tpu.ops.engine import EngineConfig
from gigapaxos_tpu.reconfiguration import RCState
from gigapaxos_tpu.reconfiguration.placement import (
    PlacementEngine,
    ProximateBalancePolicy,
)
from gigapaxos_tpu.testing.rc_cluster import ReconfigurableCluster


class FakeProfile:
    """Stand-in demand profile: just the signal fields policies read."""

    def __init__(self, rate=30.0, num_requests=512, by_active=None):
        self.rate = rate
        self.num_requests = num_requests
        if by_active is not None:
            self.by_active = by_active


# ---- decision level: initial placement -------------------------------
def test_place_initial_prefers_least_loaded_then_nearest():
    e = PlacementEngine(0)
    e.note_echo(0, 0.030, names=50, rps=40.0)
    e.note_echo(1, 0.020, names=2, rps=0.0)
    e.note_echo(2, 0.010, names=2, rps=0.0)
    e.note_echo(3, 0.040, names=60, rps=80.0)
    target = e.place_initial("svc", [0, 1, 2, 3], 2)
    # the two lightly-loaded actives win; nearest (2) anchors first
    assert target == [2, 1]
    # deterministic: same signals -> same answer (assigned ticked up, but
    # both chosen actives moved together so the ORDER stays stable)
    assert e.place_initial("svc", [0, 1, 2, 3], 2) == [2, 1]


def test_place_initial_spreads_create_bursts_via_assigned():
    """With no load reports at all, a burst of creates must not pile onto
    one active: the decision-time `assigned` counter steers later creates
    toward actives earlier creates skipped."""
    e = PlacementEngine(0)
    per_active = {a: 0 for a in range(6)}
    for i in range(60):
        for a in e.place_initial(f"n{i}", list(range(6)), 3):
            per_active[a] += 1
    assert all(n > 0 for n in per_active.values()), per_active
    assert max(per_active.values()) <= 2 * min(per_active.values()), \
        per_active


# ---- decision level: hot-spot spreading ------------------------------
def test_hot_names_spread_across_actives():
    """The tentpole acceptance shape, decision level: >=64 hot names all
    sitting on the same three overloaded actives spread across the idle
    rest of the cluster via rebalance decisions."""
    m = MetricsRegistry(node=0)
    e = PlacementEngine(0, metrics=m)
    busy, idle = [0, 1, 2], [3, 4, 5, 6, 7]
    for a in busy:
        e.note_echo(a, 0.010, names=64, rps=50.0)
    for a in idle:
        e.note_echo(a, 0.010, names=0, rps=0.0)
    landed = {a: 0 for a in range(8)}
    moves = 0
    for i in range(64):
        prof = FakeProfile(rate=30.0, num_requests=512,
                           by_active={0: 40, 1: 30, 2: 30})
        target = e.rebalance(f"hot{i}", prof, list(busy), list(range(8)))
        if target is None:
            continue
        moves += 1
        for a in target:
            landed[a] += 1
    assert moves >= 64 * 3 // 4, f"only {moves}/64 names moved"
    touched = [a for a in idle if landed[a] > 0]
    assert len(touched) >= 3, (landed, "spread must reach >=3 actives")
    # balance: no idle active hoards the hot set
    per_idle = [landed[a] for a in idle]
    assert max(per_idle) <= 3 * (sum(per_idle) // len(per_idle) + 1), \
        landed
    assert m.get("placement_moves_proposed") == moves


def test_rebalance_hysteresis_no_flap_on_near_equal():
    """Near-equal candidates must not move a name at all — and a move
    that DID happen must not bounce back on the next report."""
    m = MetricsRegistry(node=0)
    e = PlacementEngine(0, metrics=m)
    e.cooldown_s = 0.0  # isolate hysteresis from the cooldown guard
    for a in (0, 1, 2):
        e.note_echo(a, 0.010, names=10, rps=10.0)
    for a in (3, 4, 5):
        e.note_echo(a, 0.010, names=9, rps=9.0)  # near-equal: within margin
    prof = FakeProfile(rate=30.0, num_requests=512)
    assert e.rebalance("n", prof, [0, 1, 2], list(range(6))) is None
    assert m.get("placement_suppressed_hysteresis") == 1
    # now a REAL imbalance: the name moves once...
    for a in (3, 4, 5):
        e.note_echo(a, 0.010, names=0, rps=0.0)
    target = e.rebalance("n", prof, [0, 1, 2], list(range(6)))
    assert target is not None and set(target) == {3, 4, 5}
    # ...and immediately re-evaluating from the NEW set proposes nothing
    # (the destination now carries the name: no flap back)
    assert e.rebalance("n", prof, target, list(range(6))) is None


def test_rebalance_cooldown_blocks_consecutive_moves():
    m = MetricsRegistry(node=0)
    e = PlacementEngine(0, metrics=m)  # default cooldown: 30s
    for a in (0, 1, 2):
        e.note_echo(a, 0.010, names=30, rps=50.0)
    for a in (3, 4, 5):
        e.note_echo(a, 0.010, names=0, rps=0.0)
    prof = FakeProfile(rate=30.0, num_requests=512)
    first = e.rebalance("n", prof, [0, 1, 2], list(range(6)))
    assert first is not None
    # the load picture still screams "move" — cooldown holds the name
    assert e.rebalance("n", prof, [0, 1, 2], list(range(6))) is None
    assert m.get("placement_suppressed_cooldown") == 1


def test_rebalance_keeps_dominant_entry_anchor():
    """PROXIMATE balance: the name's dominant-entry active (where its
    clients are) is never displaced for load — otherwise balance evicts
    the anchor that the locality profile re-adds on the next report and
    the two deciders oscillate the name forever."""
    e = PlacementEngine(0)
    e.cooldown_s = 0.0
    for a in (0, 1, 2):
        e.note_echo(a, 0.010, names=40, rps=50.0)  # all members loaded
    for a in (3, 4, 5):
        e.note_echo(a, 0.010, names=0, rps=0.0)
    prof = FakeProfile(rate=30.0, num_requests=512,
                       by_active={0: 90, 1: 5, 2: 5})
    target = e.rebalance("n", prof, [0, 1, 2], list(range(6)))
    # members 1 and 2 flee the load; the entry anchor 0 stays
    assert target is not None and 0 in target, target
    assert set(target) - {0} <= {3, 4, 5}, target


def test_rebalance_never_shrinks_set_on_membership_loss():
    """A member leaving the cluster must not let balance propose a
    SMALLER replica set (the never-shrink rule): rehoming after
    membership loss belongs to the READY re-drive, not placement."""
    m = MetricsRegistry(node=0)
    e = PlacementEngine(0, metrics=m)
    e.cooldown_s = 0.0
    e.note_echo(0, 0.010, names=40, rps=50.0)
    e.note_echo(1, 0.010, names=40, rps=50.0)
    e.note_echo(3, 0.010, names=0, rps=0.0)
    prof = FakeProfile(rate=30.0, num_requests=512)
    # active 2 is gone from the cluster: [0,1,2] filtered would be a
    # 2-replica proposal — must decline instead
    assert e.rebalance("n", prof, [0, 1, 2], [0, 1, 3]) is None
    assert m.get("placement_suppressed_short_set") == 1


def test_placement_avoids_stale_dead_actives():
    """An active whose echo replies STOPPED is not 'idle', it is likely
    down — its frozen near-zero load must not make it the preferred
    target for every create and hot-name move."""
    e = PlacementEngine(0)  # default probing: 5s period -> 20s staleness
    e.cooldown_s = 0.0
    for a in (0, 1, 2):
        e.note_echo(a, 0.010, names=20, rps=20.0)
    e.note_echo(3, 0.010, names=0, rps=0.0)
    e.loads[3].last_seen = time.time() - 999  # echoes stopped
    assert 3 not in e.place_initial("n", [0, 1, 2, 3], 3)
    # ...but freshness never shrinks the replica count: asking for 4
    # tops back up with the stale node rather than under-replicating
    assert sorted(e.place_initial("n4", [0, 1, 2, 3], 4)) == [0, 1, 2, 3]
    prof = FakeProfile(rate=30.0, num_requests=512)
    target = e.rebalance("n", prof, [0, 1, 2], [0, 1, 2, 3])
    assert target is None or 3 not in target
    # the node resurfaces (echo replies resume): eligible again
    e.note_echo(3, 0.010, names=0, rps=0.0)
    assert 3 in e.rebalance("n", prof, [0, 1, 2], [0, 1, 2, 3])


def test_rebalance_cold_names_stay_put():
    """Below the hot gates (count AND rate), balance never moves a name —
    locality/noise is the demand profile's business, not placement's."""
    e = PlacementEngine(0)
    for a in (0, 1, 2):
        e.note_echo(a, 0.010, names=50, rps=50.0)
    e.note_echo(3, 0.010, names=0, rps=0.0)
    assert e.rebalance(
        "cold", FakeProfile(rate=0.1, num_requests=512), [0, 1, 2],
        [0, 1, 2, 3],
    ) is None
    assert e.rebalance(
        "young", FakeProfile(rate=50.0, num_requests=8), [0, 1, 2],
        [0, 1, 2, 3],
    ) is None


# ---- locality-profile hysteresis (the flap regression) ----------------
def test_proximity_profile_hysteresis_no_alternation():
    """Regression for the demand-flap: two top entries within the margin
    must NOT alternate the replica set on successive reports; a decisive
    shift must still move it."""
    from gigapaxos_tpu.reconfiguration.demand import ProximityDemandProfile
    from gigapaxos_tpu.utils.config import Config

    Config.set("REGION.0", "east")
    Config.set("REGION.1", "east")
    Config.set("REGION.2", "west")
    Config.set("REGION.3", "west")
    p = ProximityDemandProfile("n")
    # anchored east: entry 0 dominates
    p.by_active = {0: 300, 2: 280}
    p.num_requests = 580
    assert p.reconfigure([0, 1, 2], [0, 1, 2, 3]) is None  # already right
    # the max tips to entry 2 by a hair (within margin): MUST stay put —
    # without the margin this proposed [2, 3, ...] and the next report
    # would tip back, flapping the set every report
    p.by_active = {0: 290, 2: 312}
    assert p.reconfigure([0, 1, 2], [0, 1, 2, 3]) is None
    # a decisive shift west still migrates, anchored at the hot entry
    p.by_active = {0: 50, 2: 550}
    target = p.reconfigure([0, 1, 2], [0, 1, 2, 3])
    assert target is not None and target[0] == 2 and 3 in target


# ---- echo probes in the loopback reconfiguration cluster --------------
def make_cluster(**kw):
    ar_cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=4)
    rc_cfg = EngineConfig(n_groups=8, window=8, req_lanes=4, n_replicas=3)
    return ReconfigurableCluster(ar_cfg, rc_cfg, HashChainApp, **kw)


def test_echo_probes_populate_rtt_matrix_before_traffic():
    """Every RC's placement engine learns RTT + load for every active
    from echo rounds alone — no client traffic anywhere."""
    c = make_cluster()
    try:
        for rc in c.reconfigurators:
            rc.echo_probe_period_s = 0.01
        for _ in range(8):
            c.step()
        for rc in c.reconfigurators:
            snap = rc.placement.snapshot()
            assert set(snap["probe_rtt_ms"]) == {"0", "1", "2", "3"}, snap
            assert set(snap["loads"]) == {"0", "1", "2", "3"}, snap
            for a in c.ar_ids:
                assert rc.placement.rtt.get(a) is not None
    finally:
        c.close()


# ---- placement-driven migration end to end ---------------------------
class EagerBalancePolicy(ProximateBalancePolicy):
    """Production policy with test-speed hot gates."""

    MIN_REQUESTS = 24


def test_placement_rebalance_migrates_hot_name_e2e():
    """Full pipeline: AR demand reports (with load summaries) + echo
    rounds -> the primary RC's placement engine -> RECONFIGURE_INTENT ->
    epoch migration.  A hot name sharing three loaded actives picks up
    the idle fourth via the placement plane's decision."""
    from gigapaxos_tpu.utils.config import Config

    Config.set("PLACEMENT_MIN_RATE_RPS", "0.1")
    c = make_cluster(placement_policy_cls=EagerBalancePolicy)
    try:
        for ar in c.active_replicas:
            ar.demand_report_period_s = 0.05
        for rc in c.reconfigurators:
            rc.echo_probe_period_s = 0.1
        # fillers load actives 0-2 (names-hosted signal); 3 stays idle
        for i in range(6):
            c.client_request(
                "create_service", {"name": f"bg{i}", "actives": [0, 1, 2]}
            )
            assert c.wait_for("create_ack", max_steps=120)["ok"]
        c.client_request(
            "create_service", {"name": "hx", "actives": [0, 1, 2]}
        )
        assert c.wait_for("create_ack", max_steps=120)["ok"]

        deadline = time.time() + 40
        rec = None
        i = 0
        while time.time() < deadline:
            i += 1
            c.ars.managers[0].propose("hx", f"v{i}")
            c.step()
            rec = c.reconfigurators[0].rc_app.get_record("hx")
            if rec.state is RCState.READY and rec.epoch >= 1 \
                    and 3 in rec.actives:
                break
        assert rec is not None and 3 in rec.actives, (
            f"placement never spread onto the idle active: {rec.to_json()}"
        )
        assert len(rec.actives) == 3
    finally:
        c.close()


# ---- stats surfacing over real sockets --------------------------------
def test_placement_stats_surface_admin_http_and_client_seeding():
    """One AR + one RC over loopback sockets: the RC's ``stats`` admin op
    carries the placement snapshot, the RC HTTP front serves it on
    /stats and its gauges on /metrics, and a client's echo probes seed
    the redirector BEFORE any request traffic."""
    from gigapaxos_tpu.clients import PaxosClientAsync
    from gigapaxos_tpu.clients.reconfigurable_client import (
        ReconfigurableAppClient,
    )
    from gigapaxos_tpu.models import NoopPaxosApp
    from gigapaxos_tpu.paxos_config import PC
    from gigapaxos_tpu.reconfigurable_node import ReconfigurableNode
    from gigapaxos_tpu.testing.ports import free_ports
    from gigapaxos_tpu.utils.config import Config

    ports = free_ports(2)
    Config.set("active.AR0", f"127.0.0.1:{ports[0]}")
    Config.set("reconfigurator.RC0", f"127.0.0.1:{ports[1]}")
    Config.set("ECHO_PROBE_PERIOD_S", "0.2")
    cfg = EngineConfig(n_groups=16, window=8, req_lanes=4, n_replicas=1)
    nodes = [
        ReconfigurableNode("AR0", NoopPaxosApp, ar_cfg=cfg, rc_cfg=cfg,
                           tick_interval=0.01),
        ReconfigurableNode("RC0", NoopPaxosApp, ar_cfg=cfg, rc_cfg=cfg,
                           tick_interval=0.01),
    ]
    for n in nodes:
        n.start()
    admin = PaxosClientAsync([("127.0.0.1", ports[1])])
    app_client = ReconfigurableAppClient(
        {0: ("127.0.0.1", ports[0])}, [("127.0.0.1", ports[1])]
    )
    try:
        # client orientation: probes seed the redirector with NO traffic
        assert app_client.probe_actives(wait_s=5.0) == 1
        assert app_client.redirector.rtt.get(0) is not None

        # RC stats admin op: placement snapshot with probe RTT + load
        deadline = time.time() + 30
        layer = None
        while time.time() < deadline:
            r = admin.admin_sync(0, {"op": "stats"}, timeout=10)
            layer = (r or {}).get("layer")
            if layer and layer["placement"]["probe_rtt_ms"].get("0"):
                break
            time.sleep(0.2)
        assert layer, "stats admin op never carried placement"
        placement = layer["placement"]
        assert placement["policy"] == "ProximateBalancePolicy"
        assert placement["probe_rtt_ms"].get("0") is not None
        assert placement["loads"].get("0") is not None

        # RC HTTP front: /stats (snapshot) + /metrics (gauges)
        http = ports[1] + Config.get_int(PC.HTTP_PORT_OFFSET)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http}/stats", timeout=10
        ) as resp:
            body = json.loads(resp.read())
        assert body["placement"]["probe_rtt_ms"].get("0") is not None
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert "gp_probe_rtt_ms_active_0" in text
        assert "gp_placement_echo_replies_total" in text
    finally:
        admin.close()
        app_client.close()
        for n in nodes:
            n.stop()
