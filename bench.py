"""Headline benchmark: committed Paxos decisions/second.

The reference's benchmark is an in-process capacity probe
(``TESTPaxosClient.probeCapacity``, ``TESTPaxosClient.java:799-895``): N
virtual nodes in one JVM, load raised until the response rate degrades.
The analog here: all R=3 replica engines advanced on one chip (the
single-chip vmap mode, the N-nodes-in-one-JVM counterpart), G groups
committing in lock-step, with the client/request path generated on-device
so the measurement isolates the consensus engine exactly like the
reference's in-JVM probe isolates its JVM path.

Metric: committed decisions/s = slots executed per second by one replica
(each slot is one agreed client request), across all groups.  The north
star (BASELINE.json) is >= 10M decisions/s over ~1M groups.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Modes (env):

* default — single-chip vmap bench (above).
* ``BENCH_MODE=failover`` — same, under continuous leadership churn.
* ``BENCH_G=2097152`` — the G=2M capacity run (the reference's
  ``PINSTANCES_CAPACITY`` wall): on a real chip the result (no_oom,
  dec/s, per-device HBM high-water) is appended to ``TPU_EVIDENCE.json``
  under ``capacity_runs``; a CPU run prints the same shape with
  ``platform`` marked and leaves the evidence file untouched.
* ``BENCH_MULTICHIP=1`` — the scale-out weak-scaling bench: the
  group-sharded unified step (zero cross-device collectives,
  ``parallel/spmd.py:make_step`` over a ``('g',)`` mesh) over
  1 -> 2 -> 4 -> 8 mesh devices at constant groups-per-device, emitting
  the curve (aggregate dec/s, per-device dec/s, per-device HBM
  high-water) to ``MULTICHIP_r06.json`` (override:
  ``BENCH_MULTICHIP_OUT``).  Off-TPU the same harness runs on a virtual
  CPU mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=8`` is
  forced) with ``platform`` marked in the artifact.
* ``BENCH_DISPATCH_ABLATION=1`` — the host-boundary residency ablation:
  N=1 vs N=8 ``steps_per_dispatch`` under identical offered load and an
  identical total substep budget.  Asserts per-step engine parity is
  bit-exact across N, counts host dispatches for the same decided work
  (~8x fewer at N=8), and measures end-to-end throughput for both arms.
  Emits ``BENCH_r06.json`` (override: ``BENCH_DISPATCH_OUT``).
"""

import json
import os
import subprocess
import sys
import time

NORTH_STAR = 10_000_000.0  # decisions/s, BASELINE.json
CAPACITY_G = 2_097_152     # the reference's PINSTANCES_CAPACITY wall


def bench_provenance(donate=None) -> dict:
    """Provenance stamp for bench artifacts (obs/device.py): jax/jaxlib
    versions, platform, XLA flags, donation.  A perf number without its
    software/hardware coordinates can't be compared across rounds —
    ``scripts/perf_baseline.py`` keys its trend series on this block.
    Never fails the bench: degrades to an ``error`` marker."""
    try:
        from gigapaxos_tpu.obs.device import provenance

        return provenance(donate=donate)
    except Exception as e:  # noqa: BLE001 — bench must still print its line
        return {"error": repr(e)}


def probe_tpu(timeout_s: float) -> tuple:
    """Probe whether the TPU backend can actually initialize — in a
    SUBPROCESS, because a broken tunnel makes backend init hang forever
    (not raise), and an in-process hang can't be timed out.  Returns
    (platform or None, error string)."""
    code = (
        "import jax; d = jax.devices(); "
        "import jax.numpy as jnp; "
        "jnp.ones((8, 8)).sum().block_until_ready(); "
        "print('PLATFORM=' + d[0].platform)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"TPU backend init hung > {timeout_s:.0f}s (tunnel down?)"
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], ""
    return None, (r.stderr or "no output").strip()[-2000:]


def probe_tpu_retrying(first_try_s: float, retry_s: float, tries: int,
                       gap_s: float) -> tuple:
    """A transient tunnel outage should not cost the round its TPU
    number: spread several probe attempts across the bench invocation
    before declaring fallback (VERDICT r3 #2).  The first attempt keeps
    the long budget (a slow-but-working backend init must not be
    misread as an outage); retries use a shorter one."""
    err = ""
    for i in range(max(1, tries)):
        platform, err = probe_tpu(first_try_s if i == 0 else retry_s)
        if platform is not None:
            return platform, ""
        print(
            f"BENCH WARNING: TPU probe attempt {i + 1}/{tries} failed: {err}",
            file=sys.stderr, flush=True,
        )
        if i + 1 < tries:
            time.sleep(gap_s)
    return None, err


def _append_evidence(entry: dict, key: str) -> None:
    """Append one entry under ``key`` in TPU_EVIDENCE.json — locked
    read-modify-write so concurrent bench invocations never drop a run.
    ONLY called for real on-chip results: a CPU run must leave the file
    untouched (the committed TPU numbers are the point of the file)."""
    import fcntl

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_EVIDENCE.json")
    with open(path + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                doc = {"what": "raw on-chip bench runs", "runs": []}
        except (OSError, ValueError):
            doc = {"what": "raw on-chip bench runs", "runs": []}
        runs = doc.setdefault(key, [])
        if not isinstance(runs, list):
            runs = doc[key] = []
        runs.append(entry)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)


def record_tpu_evidence(result: dict, wall_s: float) -> None:
    """Append a successful on-chip headline run to the evidence file so
    the number survives even if a later driver bench hits an outage."""
    _append_evidence({
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "device_platform": "tpu",
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
        "wall_s": round(wall_s, 1),
        "bench_json": result,
    }, key="runs")


def record_capacity_evidence(capacity: dict, wall_s: float) -> None:
    """Append a G=2M capacity verdict (no_oom + throughput + HBM
    high-water) — ROADMAP item 3 / PR-1's open on-chip verification."""
    _append_evidence({
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
        "wall_s": round(wall_s, 1),
        **capacity,
    }, key="capacity_runs")


def device_hbm_peak(devices) -> list:
    """Per-device HBM high-water (peak_bytes_in_use) where the backend
    reports it; None entries where it doesn't (the CPU backend)."""
    peaks = []
    for d in devices:
        try:
            ms = d.memory_stats()
            peaks.append(int(ms["peak_bytes_in_use"]) if ms else None)
        except Exception:
            peaks.append(None)
    return peaks


def _is_oom(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s)


def _run_group_sharded_point(n_devices: int, g_per_dev: int, W: int, K: int,
                             n_chunks: int) -> dict:
    """One weak-scaling point: the group-sharded SPMD step over the first
    ``n_devices`` devices at G = g_per_dev x n_devices, steady-state scan
    loop, measured aggregate + per-device dec/s and HBM high-water."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gigapaxos_tpu.ops.ballot import NULL
    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.parallel.mesh import make_group_mesh
    from gigapaxos_tpu.parallel.spmd import (
        build_replica_states,
        make_step,
        shard_group_inputs,
    )

    R = 3
    G = g_per_dev * n_devices
    devs = jax.devices()[:n_devices]
    mesh = make_group_mesh(n_devices)
    cfg = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
    states, _req0, _want0 = shard_group_inputs(
        mesh, cfg, build_replica_states(cfg),
        np.full((R, G, K), NULL, np.int32), np.zeros((R, G), bool),
    )
    Gp = _req0.shape[1]
    step_fn = make_step(cfg, mesh, 1)
    vids = jnp.arange(1, K + 1, dtype=jnp.int32)
    CHUNK = 10

    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(states):
        # sharded on-device request generation: the offered-request plane
        # materializes INSIDE the jitted chunk (GSPMD lays the constant out
        # per shard), so the steady-state loop moves zero host bytes
        req = jnp.broadcast_to(vids[None, None, :], (R, Gp, K))
        want = jnp.zeros((R, Gp), bool)

        def body(s, _i):
            s, out = step_fn(s, req, want)
            return s, out.n_committed[0].sum()

        states, committed = jax.lax.scan(
            body, states, jnp.arange(CHUNK, dtype=jnp.int32)
        )
        return states, committed.sum()

    # warmup: compile + pipeline fill — timed SEPARATELY so the artifact
    # splits one-time compile cost from the steady-state rate (a compile
    # regression and a throughput regression are different bugs)
    tw = time.perf_counter()
    states, _ = run_chunk(states)
    states, c = run_chunk(states)
    jax.block_until_ready(c)
    warmup_s = time.perf_counter() - tw

    t0 = time.perf_counter()
    total = 0
    for _ in range(n_chunks):
        states, c = run_chunk(states)
        total += int(jax.block_until_ready(c))
    dt = time.perf_counter() - t0

    rate = total / dt
    peaks = device_hbm_peak(devs)
    known = [p for p in peaks if p is not None]
    return {
        "n_devices": n_devices,
        "mesh_shape": {"g": n_devices},
        "G": G,
        "groups_per_device": g_per_dev,
        "aggregate_dec_per_s": round(rate, 1),
        "per_device_dec_per_s": round(rate / n_devices, 1),
        "per_device_hbm_peak_bytes": max(known) if known else None,
        "hbm_peak_bytes_by_device": peaks,
        "steps_timed": n_chunks * CHUNK,
        "warmup_s": round(warmup_s, 2),
        "wall_s": round(dt, 2),
    }


def _dispatch_arm(n_steps: int, G: int, W: int, K: int, R: int,
                  substeps: int) -> dict:
    """Time one ablation arm: the unified step at ``n_steps`` rounds per
    host dispatch, over ``substeps`` total engine steps of identical
    offered load.  The host touches the packed outputs once per dispatch
    (the decided-count reduction), exactly like the deployed post-step
    cycle's single transfer."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.parallel.spmd import build_replica_states, make_step

    cfg = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
    states = build_replica_states(cfg)
    step_fn = make_step(cfg, None, n_steps)
    vids = jnp.arange(1, K + 1, dtype=jnp.int32)
    if n_steps == 1:
        ring = jnp.broadcast_to(vids[None, None, :], (R, G, K))
    else:
        ring = jnp.broadcast_to(
            vids[None, None, None, :], (n_steps, R, G, K)
        )
    want = jnp.zeros((R, G), bool)
    dispatches = substeps // n_steps
    # warmup: compile + steady-state fill — timed into its own field so
    # compile cost never leaks into (or hides inside) the steady rate
    tw = time.perf_counter()
    for _ in range(2):
        states, out = step_fn(states, ring, want)
    jax.block_until_ready(out.n_committed)
    warmup_s = time.perf_counter() - tw

    t0 = time.perf_counter()
    decided = 0
    for _ in range(dispatches):
        states, out = step_fn(states, ring, want)
        # ONE host touch per dispatch: the packed reduction syncs the
        # device and is the only per-dispatch host<->device traffic
        decided += int(np.asarray(out.n_committed)[..., 0, :].sum())
    dt = time.perf_counter() - t0
    return {
        "steps_per_dispatch": n_steps,
        "host_dispatches": dispatches,
        "substeps": dispatches * n_steps,
        "decided": decided,
        "warmup_s": round(warmup_s, 3),
        "wall_s": round(dt, 3),
        "decided_per_s": round(decided / dt, 1),
        "dispatch_amortized_us": round(1e6 * dt / dispatches / n_steps, 1),
    }


def _dispatch_parity(G: int, W: int, K: int, R: int, substeps: int) -> dict:
    """Bit-exact check: N=8 residency vs 8x sequential N=1 from the same
    initial states — every state leaf and every StepOutputs field."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.parallel.spmd import build_replica_states, make_step

    N = 8
    cfg = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
    fn1 = make_step(cfg, None, 1, donate=False)
    fn8 = make_step(cfg, None, N, donate=False)
    vids = jnp.arange(1, K + 1, dtype=jnp.int32)
    req = jnp.broadcast_to(vids[None, None, :], (R, G, K))
    ring = jnp.broadcast_to(vids[None, None, None, :], (N, R, G, K))
    want = jnp.zeros((R, G), bool)

    s1 = build_replica_states(cfg)
    s8 = build_replica_states(cfg)
    dec1 = dec8 = 0
    bit_exact = True
    for _ in range(substeps // N):
        outs1 = []
        for _i in range(N):
            s1, o = fn1(s1, req, want)
            outs1.append(o)
        s8, o8 = fn8(s8, ring, want)
        dec1 += int(sum(int(np.asarray(o.n_committed)[0].sum())
                        for o in outs1))
        dec8 += int(np.asarray(o8.n_committed)[:, 0].sum())
        for i, o in enumerate(outs1):
            for a, b in zip(o, jax.tree.map(lambda x: x[i], o8)):
                if not (np.asarray(a) == np.asarray(b)).all():
                    bit_exact = False
        for a, b in zip(s1, s8):
            if not (np.asarray(a) == np.asarray(b)).all():
                bit_exact = False
    return {
        "substeps": substeps - substeps % N,
        "bit_exact": bit_exact,
        "decided_n1": dec1,
        "decided_n8": dec8,
    }


def dispatch_ablation_main() -> int:
    """BENCH_DISPATCH_ABLATION=1: the steps_per_dispatch residency
    ablation — N=1 vs N=8 under identical load (see module docstring)."""
    t_start = time.perf_counter()
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    on_cpu = platform.startswith("cpu")
    # CPU default sits in the dispatch-bound regime (small per-step device
    # time, like a TPU step over sharded groups) — that is the regime the
    # residency amortization targets; at compute-bound shapes the host
    # dispatch cost is noise either way
    G = int(os.environ.get("BENCH_G", 512 if on_cpu else 262_144))
    W = int(os.environ.get("BENCH_W", 8 if on_cpu else 32))
    K = int(os.environ.get("BENCH_K", 4 if on_cpu else 16))
    R = 3
    substeps = int(os.environ.get("BENCH_DISPATCH_SUBSTEPS", "480"))

    parity = _dispatch_parity(
        G, W, K, R, int(os.environ.get("BENCH_DISPATCH_PARITY_SUBSTEPS",
                                       "32")),
    )
    # best-of-trials, arms interleaved: the signal (dispatch overhead
    # amortization) is a few percent on CPU, below run-to-run OS noise
    trials = int(os.environ.get("BENCH_DISPATCH_TRIALS", "5"))
    arm1 = arm8 = None
    for _ in range(trials):
        a1 = _dispatch_arm(1, G, W, K, R, substeps)
        a8 = _dispatch_arm(8, G, W, K, R, substeps)
        if arm1 is None or a1["wall_s"] < arm1["wall_s"]:
            arm1 = a1
        if arm8 is None or a8["wall_s"] < arm8["wall_s"]:
            arm8 = a8
    result = {
        "metric": "dispatch_ablation",
        "platform": platform,
        "shape": {"G": G, "W": W, "K": K, "R": R},
        "arms": {"n1": arm1, "n8": arm8},
        "dispatch_count_ratio": round(
            arm1["host_dispatches"] / arm8["host_dispatches"], 2
        ),
        "throughput_ratio_n8_vs_n1": round(
            arm8["decided_per_s"] / arm1["decided_per_s"], 3
        ),
        "parity": parity,
        "provenance": bench_provenance(donate=True),
        "wall_s": round(time.perf_counter() - t_start, 1),
    }
    out_path = os.environ.get("BENCH_DISPATCH_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r06.json"
    )
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    os.replace(tmp, out_path)
    print(json.dumps(result))
    ok = (
        parity["bit_exact"]
        and parity["decided_n1"] == parity["decided_n8"]
        and result["dispatch_count_ratio"] >= 7.5
    )
    return 0 if ok else 1


def multichip_main() -> int:
    """BENCH_MULTICHIP=1: the weak-scaling headline — 1 -> 2 -> 4 -> 8
    devices, groups-per-device constant, group-sharded SPMD step.  Emits
    the curve to MULTICHIP_r06.json (BENCH_MULTICHIP_OUT overrides) and
    prints it as one JSON line."""
    import re

    t_start = time.perf_counter()
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    fallback = False
    if env_platforms and env_platforms != "cpu":
        platform_probe, err = probe_tpu_retrying(
            float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300")),
            float(os.environ.get("BENCH_TPU_PROBE_RETRY_TIMEOUT", "120")),
            int(os.environ.get("BENCH_TPU_PROBE_TRIES", "3")),
            gap_s=15.0,
        )
        if platform_probe is None:
            print(
                f"BENCH WARNING: TPU ({env_platforms}) unavailable: {err}\n"
                "BENCH WARNING: multichip bench falling back to the virtual "
                "CPU mesh — these numbers are NOT a TPU measurement.",
                file=sys.stderr, flush=True,
            )
            fallback = True
    on_cpu = fallback or env_platforms == "cpu" or not env_platforms
    if on_cpu:
        # the virtual mesh needs the device count forced BEFORE backend
        # init (and a site hook may pin the platform at config level, so
        # both the env var and the config write are required — the
        # dryrun_multichip / conftest pattern)
        n_virtual = int(os.environ.get("BENCH_MULTICHIP_DEVICES", "8"))
        flags = os.environ.get("XLA_FLAGS", "")
        count_flag = f"--xla_force_host_platform_device_count={n_virtual}"
        flags, n_sub = re.subn(
            r"--xla_force_host_platform_device_count=\d+", count_flag, flags
        )
        if not n_sub:
            flags = (flags + " " + count_flag).strip()
        os.environ["XLA_FLAGS"] = flags
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if on_cpu:
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    platform = devs[0].platform
    if fallback:
        platform = "cpu-fallback"
    n_avail = len(devs)
    cpu_plat = devs[0].platform.startswith("cpu")

    counts = [n for n in (1, 2, 4, 8) if n <= n_avail]
    g_per_dev = int(os.environ.get(
        "BENCH_G_PER_DEVICE", 8_192 if cpu_plat else 1_048_576
    ))
    W = int(os.environ.get("BENCH_W", 8 if cpu_plat else 32))
    K = int(os.environ.get("BENCH_K", 4 if cpu_plat else 16))
    n_chunks = int(os.environ.get("BENCH_MULTICHIP_CHUNKS",
                                  3 if cpu_plat else 5))

    curve = []
    for n in counts:
        pt = _run_group_sharded_point(n, g_per_dev, W, K, n_chunks)
        print(f"BENCH multichip point: {json.dumps(pt)}",
              file=sys.stderr, flush=True)
        curve.append(pt)

    base = curve[0]["aggregate_dec_per_s"]
    top = curve[-1]
    n_max = top["n_devices"]
    eff_parallel = top["aggregate_dec_per_s"] / (n_max * base)
    eff_serialized = top["aggregate_dec_per_s"] / base
    host_cores = os.cpu_count() or 1
    # on a virtual CPU mesh with fewer cores than devices the devices
    # TIME-SHARE the cores, so "linear" weak scaling is a flat aggregate
    # (the resource doesn't grow with n); on real parallel devices linear
    # is n x the single-device aggregate.  Both ratios are recorded; the
    # headline efficiency uses the model that matches the execution.
    serialized = cpu_plat and host_cores < n_max
    result = {
        "metric": "multichip_weak_scaling",
        "platform": platform,
        "host_cores": host_cores,
        "n_devices_available": n_avail,
        "mode": "group-sharded SPMD (zero cross-device collectives, "
                "all R replica rows device-local)",
        "shape": {"groups_per_device": g_per_dev, "W": W, "K": K,
                  "R": 3},
        "curve": curve,
        "scaling": {
            "at_n_devices": n_max,
            "efficiency_vs_linear": round(
                eff_serialized if serialized else eff_parallel, 3
            ),
            "linear_model": (
                f"host-serialized: {n_max} virtual devices time-share "
                f"{host_cores} core(s); linear = flat aggregate vs n=1"
            ) if serialized else (
                "parallel devices: linear = n x the n=1 aggregate"
            ),
            "efficiency_parallel_model": round(eff_parallel, 3),
            "efficiency_serialized_model": round(eff_serialized, 3),
        },
        "provenance": bench_provenance(donate=True),
        "wall_s": round(time.perf_counter() - t_start, 1),
    }
    out_path = os.environ.get("BENCH_MULTICHIP_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "MULTICHIP_r06.json"
    )
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    os.replace(tmp, out_path)
    print(json.dumps(result))
    return 0


def main() -> None:
    if os.environ.get("BENCH_MULTICHIP", "") not in ("", "0"):
        return multichip_main()
    if os.environ.get("BENCH_DISPATCH_ABLATION", "") not in ("", "0"):
        sys.exit(dispatch_ablation_main())
    # Decide the platform BEFORE any in-process backend init.  The env pins
    # JAX_PLATFORMS=axon via a site hook; if the chip can't init we must say
    # so loudly and fall back with a distinct marker — never silently.
    t_start = time.perf_counter()
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    fallback = False
    if env_platforms and env_platforms != "cpu":
        probe_timeout = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
        probe_retry_timeout = float(
            os.environ.get("BENCH_TPU_PROBE_RETRY_TIMEOUT", "120")
        )
        probe_tries = int(os.environ.get("BENCH_TPU_PROBE_TRIES", "3"))
        platform_probe, err = probe_tpu_retrying(
            probe_timeout, probe_retry_timeout, probe_tries, gap_s=15.0
        )
        if platform_probe is None:
            print(
                f"BENCH WARNING: TPU ({env_platforms}) unavailable: {err}\n"
                "BENCH WARNING: falling back to CPU — this number is NOT a "
                "TPU measurement.",
                file=sys.stderr, flush=True,
            )
            fallback = True
            os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if fallback or env_platforms == "cpu":
        jax.config.update("jax_platforms", "cpu")
    try:
        devs = jax.devices()
    except Exception as e:
        # a config-level platform pin (site hook) with a broken backend can
        # still raise here even when the env var was unset — fall back
        # loudly rather than dying without printing the JSON line
        print(
            f"BENCH WARNING: backend init failed in-process: {e!r}\n"
            "BENCH WARNING: falling back to CPU — this number is NOT a "
            "TPU measurement.",
            file=sys.stderr, flush=True,
        )
        fallback = True
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
    platform = devs[0].platform
    if fallback:
        platform = "cpu-fallback"

    import jax.numpy as jnp

    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.parallel.spmd import build_replica_states, make_step

    # ~1M groups on TPU HBM; smaller on CPU fallback so the line still prints.
    on_cpu = platform.startswith("cpu")
    G = int(os.environ.get("BENCH_G", 8_192 if on_cpu else 1_048_576))
    # steady-state commits/group/step reach the K ceiling only when the
    # ring covers the full in-flight pipeline; the step cost grows with W,
    # so on CPU shallow wins.  On the chip the r4 sweep at G=1M measured
    # W16/K8 80.1M, W32/K16 84.2M, W16/K16 75.7M, W32/K8 65.3M dec/s;
    # W64/K32 and G=2M OOM — W=32/K=16 is the headline shape.
    W = int(os.environ.get("BENCH_W", 8 if on_cpu else 32))
    K = int(os.environ.get("BENCH_K", 4 if on_cpu else 16))
    R = 3
    cfg = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
    states = build_replica_states(cfg)

    # On-device synthetic client load: K requests per group per step, sent to
    # the coordinator replica's request lanes (entry-replica batching analog;
    # coordinators are round-robin g % R, matching build_replica_states).
    rids = jnp.arange(R, dtype=jnp.int32)
    groups = jnp.arange(G, dtype=jnp.int32)
    vids = jnp.arange(1, K + 1, dtype=jnp.int32)  # constant vids; hashed anyway
    # requests offered at EVERY replica's lanes; only the group's ACTIVE
    # coordinator admits, so this models clients following the leader
    # (essential under failover churn: a new leader must find requests)
    req = jnp.broadcast_to(vids[None, None, :], (R, G, K))
    want = jnp.zeros((R, G), dtype=bool)
    step_fn = make_step(cfg, None, 1)

    # BENCH_MODE=failover (BASELINE config 5): continuous ballot
    # contention — leadership of every group is forced to rotate around
    # the replica ring (each group re-elects every ~16 steps, with the
    # electing 1/16 slice staggered per step), so the measured rate
    # includes constant preempt/election/carryover churn.
    failover = os.environ.get("BENCH_MODE", "steady") == "failover"

    CHUNK = 10

    from functools import partial

    # donate the states: the previous chunk's buffers are dead once the
    # next chunk starts, so XLA reuses them in place — without this the
    # bench holds TWO full state copies across the dispatch boundary,
    # which is half the G=2M headroom on a 16GB chip
    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(states, base):
        def body(s, i):
            if failover:
                t = base + i
                sl = (groups & jnp.int32(15)) == (t & jnp.int32(15))
                target = (groups % R + 1 + (t >> 4)) % R
                w = (target[None, :] == rids[:, None]) & sl[None, :]
            else:
                w = want
            s, out = step_fn(s, req, w)
            return s, out.n_committed[0].sum()  # each slot once
        states, committed = jax.lax.scan(
            body, states, jnp.arange(CHUNK, dtype=jnp.int32)
        )
        return states, committed.sum()

    # G=2M is the capacity run (the reference's PINSTANCES_CAPACITY wall):
    # an OOM there is a RESULT to record, not a crash to swallow.
    is_capacity = G == CAPACITY_G
    try:
        # Warmup: compile + reach steady state (pipeline fill) — timed
        # into its own artifact field, separate from the steady rate
        tw = time.perf_counter()
        states, _ = run_chunk(states, jnp.int32(0))
        states, c = run_chunk(states, jnp.int32(CHUNK))
        jax.block_until_ready(c)
        warmup_s = time.perf_counter() - tw

        t0 = time.perf_counter()
        total = 0
        n_chunks = 5
        for i in range(n_chunks):
            states, c = run_chunk(states, jnp.int32((2 + i) * CHUNK))
            total += int(jax.block_until_ready(c))
        dt = time.perf_counter() - t0
    except Exception as e:
        if not (is_capacity and _is_oom(e)):
            raise
        capacity = {
            "platform": platform, "G": G, "W": W, "K": K,
            "no_oom": False, "dec_per_s": None,
            "per_device_hbm_bytes": None,
            "error": f"{type(e).__name__}: {e}"[:500],
        }
        if platform == "tpu":
            try:
                record_capacity_evidence(
                    capacity, time.perf_counter() - t_start
                )
            except Exception as e2:
                print(f"BENCH WARNING: could not record evidence: {e2!r}",
                      file=sys.stderr, flush=True)
        print(json.dumps({
            "metric": "committed_decisions_per_s", "value": 0.0,
            "unit": f"decisions/s ({G} groups, 3 replicas, 1 chip, OOM, "
                    f"{platform})",
            "vs_baseline": 0.0, "capacity": capacity,
        }))
        return 1

    rate = total / dt
    mode = "failover-churn" if failover else "steady-state"
    result = {
        "metric": "committed_decisions_per_s",
        "value": round(rate, 1),
        "unit": f"decisions/s ({G} groups, 3 replicas, 1 chip, "
                f"{mode}, {platform})",
        "vs_baseline": round(rate / NORTH_STAR, 3),
        "warmup_s": round(warmup_s, 2),
        "steady_s": round(dt, 2),
        "provenance": bench_provenance(donate=True),
    }
    if is_capacity:
        peaks = [p for p in device_hbm_peak(devs[:1]) if p is not None]
        result["capacity"] = {
            "platform": platform, "G": G, "W": W, "K": K,
            "no_oom": True, "dec_per_s": round(rate, 1),
            "per_device_hbm_bytes": peaks[0] if peaks else None,
        }
        if platform == "tpu":
            # the pending PR-1 verification: the G=2M verdict lands in the
            # committed evidence file; a CPU run leaves the file UNTOUCHED
            # (never overwrite TPU numbers with host-platform stand-ins)
            try:
                record_capacity_evidence(
                    result["capacity"], time.perf_counter() - t_start
                )
            except Exception as e:
                print(f"BENCH WARNING: could not record evidence: {e!r}",
                      file=sys.stderr, flush=True)
    # headline evidence entries are only meaningful for headline-shaped
    # runs — a debug run with BENCH_G/W/K overridden must not pollute them
    headline_shape = not any(
        v in os.environ for v in ("BENCH_G", "BENCH_W", "BENCH_K")
    )
    if platform == "tpu" and headline_shape:
        try:
            record_tpu_evidence(result, time.perf_counter() - t_start)
        except Exception as e:
            print(f"BENCH WARNING: could not record evidence: {e!r}",
                  file=sys.stderr, flush=True)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
