"""Headline benchmark: committed Paxos decisions/second on one TPU chip.

The reference's benchmark is an in-process capacity probe
(``TESTPaxosClient.probeCapacity``, ``TESTPaxosClient.java:799-895``): N
virtual nodes in one JVM, load raised until the response rate degrades.
The analog here: all R=3 replica engines advanced on one chip (the
single-chip vmap mode, the N-nodes-in-one-JVM counterpart), G groups
committing in lock-step, with the client/request path generated on-device
so the measurement isolates the consensus engine exactly like the
reference's in-JVM probe isolates its JVM path.

Metric: committed decisions/s = slots executed per second by one replica
(each slot is one agreed client request), across all groups.  The north
star (BASELINE.json) is >= 10M decisions/s over ~1M groups.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

NORTH_STAR = 10_000_000.0  # decisions/s, BASELINE.json


def probe_tpu(timeout_s: float) -> tuple:
    """Probe whether the TPU backend can actually initialize — in a
    SUBPROCESS, because a broken tunnel makes backend init hang forever
    (not raise), and an in-process hang can't be timed out.  Returns
    (platform or None, error string)."""
    code = (
        "import jax; d = jax.devices(); "
        "import jax.numpy as jnp; "
        "jnp.ones((8, 8)).sum().block_until_ready(); "
        "print('PLATFORM=' + d[0].platform)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"TPU backend init hung > {timeout_s:.0f}s (tunnel down?)"
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], ""
    return None, (r.stderr or "no output").strip()[-2000:]


def probe_tpu_retrying(first_try_s: float, retry_s: float, tries: int,
                       gap_s: float) -> tuple:
    """A transient tunnel outage should not cost the round its TPU
    number: spread several probe attempts across the bench invocation
    before declaring fallback (VERDICT r3 #2).  The first attempt keeps
    the long budget (a slow-but-working backend init must not be
    misread as an outage); retries use a shorter one."""
    err = ""
    for i in range(max(1, tries)):
        platform, err = probe_tpu(first_try_s if i == 0 else retry_s)
        if platform is not None:
            return platform, ""
        print(
            f"BENCH WARNING: TPU probe attempt {i + 1}/{tries} failed: {err}",
            file=sys.stderr, flush=True,
        )
        if i + 1 < tries:
            time.sleep(gap_s)
    return None, err


def record_tpu_evidence(result: dict, wall_s: float) -> None:
    """Append a successful on-chip run to the committed evidence file so
    the number survives even if a later driver bench hits an outage."""
    import fcntl

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_EVIDENCE.json")
    # serialize concurrent bench invocations (e.g. steady + failover modes
    # in parallel): the read-modify-write below must not drop a run
    with open(path + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                doc = {"what": "raw on-chip bench runs", "runs": []}
        except (OSError, ValueError):
            doc = {"what": "raw on-chip bench runs", "runs": []}
        runs = doc.setdefault("runs", [])
        if not isinstance(runs, list):
            runs = doc["runs"] = []
        runs.append({
            "captured_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "device_platform": "tpu",
            "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
            "wall_s": round(wall_s, 1),
            "bench_json": result,
        })
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)


def main() -> None:
    # Decide the platform BEFORE any in-process backend init.  The env pins
    # JAX_PLATFORMS=axon via a site hook; if the chip can't init we must say
    # so loudly and fall back with a distinct marker — never silently.
    t_start = time.perf_counter()
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    fallback = False
    if env_platforms and env_platforms != "cpu":
        probe_timeout = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
        probe_retry_timeout = float(
            os.environ.get("BENCH_TPU_PROBE_RETRY_TIMEOUT", "120")
        )
        probe_tries = int(os.environ.get("BENCH_TPU_PROBE_TRIES", "3"))
        platform_probe, err = probe_tpu_retrying(
            probe_timeout, probe_retry_timeout, probe_tries, gap_s=15.0
        )
        if platform_probe is None:
            print(
                f"BENCH WARNING: TPU ({env_platforms}) unavailable: {err}\n"
                "BENCH WARNING: falling back to CPU — this number is NOT a "
                "TPU measurement.",
                file=sys.stderr, flush=True,
            )
            fallback = True
            os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if fallback or env_platforms == "cpu":
        jax.config.update("jax_platforms", "cpu")
    try:
        devs = jax.devices()
    except Exception as e:
        # a config-level platform pin (site hook) with a broken backend can
        # still raise here even when the env var was unset — fall back
        # loudly rather than dying without printing the JSON line
        print(
            f"BENCH WARNING: backend init failed in-process: {e!r}\n"
            "BENCH WARNING: falling back to CPU — this number is NOT a "
            "TPU measurement.",
            file=sys.stderr, flush=True,
        )
        fallback = True
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
    platform = devs[0].platform
    if fallback:
        platform = "cpu-fallback"

    import jax.numpy as jnp

    from gigapaxos_tpu.ops.engine import EngineConfig
    from gigapaxos_tpu.parallel.spmd import build_replica_states, single_chip_step

    # ~1M groups on TPU HBM; smaller on CPU fallback so the line still prints.
    on_cpu = platform.startswith("cpu")
    G = int(os.environ.get("BENCH_G", 8_192 if on_cpu else 1_048_576))
    # steady-state commits/group/step reach the K ceiling only when the
    # ring covers the full in-flight pipeline; the step cost grows with W,
    # so on CPU shallow wins.  On the chip the r4 sweep at G=1M measured
    # W16/K8 80.1M, W32/K16 84.2M, W16/K16 75.7M, W32/K8 65.3M dec/s;
    # W64/K32 and G=2M OOM — W=32/K=16 is the headline shape.
    W = int(os.environ.get("BENCH_W", 8 if on_cpu else 32))
    K = int(os.environ.get("BENCH_K", 4 if on_cpu else 16))
    R = 3
    cfg = EngineConfig(n_groups=G, window=W, req_lanes=K, n_replicas=R)
    states = build_replica_states(cfg)

    # On-device synthetic client load: K requests per group per step, sent to
    # the coordinator replica's request lanes (entry-replica batching analog;
    # coordinators are round-robin g % R, matching build_replica_states).
    rids = jnp.arange(R, dtype=jnp.int32)
    groups = jnp.arange(G, dtype=jnp.int32)
    vids = jnp.arange(1, K + 1, dtype=jnp.int32)  # constant vids; hashed anyway
    # requests offered at EVERY replica's lanes; only the group's ACTIVE
    # coordinator admits, so this models clients following the leader
    # (essential under failover churn: a new leader must find requests)
    req = jnp.broadcast_to(vids[None, None, :], (R, G, K))
    want = jnp.zeros((R, G), dtype=bool)
    step_fn = single_chip_step(cfg)

    # BENCH_MODE=failover (BASELINE config 5): continuous ballot
    # contention — leadership of every group is forced to rotate around
    # the replica ring (each group re-elects every ~16 steps, with the
    # electing 1/16 slice staggered per step), so the measured rate
    # includes constant preempt/election/carryover churn.
    failover = os.environ.get("BENCH_MODE", "steady") == "failover"

    CHUNK = 10

    from functools import partial

    # donate the states: the previous chunk's buffers are dead once the
    # next chunk starts, so XLA reuses them in place — without this the
    # bench holds TWO full state copies across the dispatch boundary,
    # which is half the G=2M headroom on a 16GB chip
    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(states, base):
        def body(s, i):
            if failover:
                t = base + i
                sl = (groups & jnp.int32(15)) == (t & jnp.int32(15))
                target = (groups % R + 1 + (t >> 4)) % R
                w = (target[None, :] == rids[:, None]) & sl[None, :]
            else:
                w = want
            s, out = step_fn(s, req, w)
            return s, out.n_committed[0].sum()  # each slot once
        states, committed = jax.lax.scan(
            body, states, jnp.arange(CHUNK, dtype=jnp.int32)
        )
        return states, committed.sum()

    # Warmup: compile + reach steady state (pipeline fill).
    states, _ = run_chunk(states, jnp.int32(0))
    states, c = run_chunk(states, jnp.int32(CHUNK))
    jax.block_until_ready(c)

    t0 = time.perf_counter()
    total = 0
    n_chunks = 5
    for i in range(n_chunks):
        states, c = run_chunk(states, jnp.int32((2 + i) * CHUNK))
        total += int(jax.block_until_ready(c))
    dt = time.perf_counter() - t0

    rate = total / dt
    mode = "failover-churn" if failover else "steady-state"
    result = {
        "metric": "committed_decisions_per_s",
        "value": round(rate, 1),
        "unit": f"decisions/s ({G} groups, 3 replicas, 1 chip, "
                f"{mode}, {platform})",
        "vs_baseline": round(rate / NORTH_STAR, 3),
    }
    # evidence entries are only meaningful for headline-shaped runs —
    # a debug run with BENCH_G/W/K overridden must not pollute the file
    headline_shape = not any(
        v in os.environ for v in ("BENCH_G", "BENCH_W", "BENCH_K")
    )
    if platform == "tpu" and headline_shape:
        try:
            record_tpu_evidence(result, time.perf_counter() - t_start)
        except Exception as e:
            print(f"BENCH WARNING: could not record evidence: {e!r}",
                  file=sys.stderr, flush=True)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
