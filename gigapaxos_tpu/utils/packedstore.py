"""PackedSpillStore — segment-file spill store for the paused-group table.

The file-per-key :class:`~gigapaxos_tpu.utils.diskmap.DiskMap` layout
collapses at density scale: a cold tail of millions of paused names
costs millions of inodes, one open/write/close per spill, and random
reads on wake.  This store keeps the same capacity-bounded mapping
contract (LRU memory tier, explicit ``demote``, ``peek_items``) but
pages cold entries into **recency-ordered segment files**:

* spills APPEND length+CRC framed records to the current tail segment,
  so a pause burst is one sequential write stream, not N file creates;
* segments fan over hashed subdirectories (``SPILL_SUBDIRS``) so no
  directory ever holds more than segments/subdirs entries — bounded
  inodes regardless of key count (one segment covers thousands of keys);
* the in-RAM index is ``key -> (segment, offset, length)`` — the only
  per-paused-name RAM cost, measured by ``footprint_probe.py --paused``;
* wakes of names paused together (the recency pattern: a restart hot
  set, a rotating Zipfian head) read one segment sequentially —
  ``restore_batch`` sorts its reads by (segment, offset);
* deleting/restoring marks records dead; a segment whose dead fraction
  crosses ``compact_ratio`` is compacted (live records re-appended to
  the tail, file unlinked), so disk stays O(live records).

Not a durability mechanism — exactly like DiskMap, the spill directory
is scratch owned by one process incarnation (the journal's PAUSE blocks
are the durable copy); stale contents are wiped at construction.  A
torn tail (failed append: ENOSPC, crash mid-write) can therefore only
be produced by THIS process, and the append path truncates back to the
last good offset so one failed spill never corrupts its segment.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import zlib
from collections import OrderedDict
from collections.abc import MutableMapping
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

# record frame: u32 payload length, u32 crc32(payload), payload bytes
_HDR = struct.Struct("<II")


class SpillCorruption(KeyError):
    """A spilled record failed its CRC/length check on read."""


def _key_to_wire(key: Any):
    """JSON-stable form of a key (tuples round-trip as lists)."""
    return list(key) if isinstance(key, tuple) else key


def _key_from_wire(k: Any):
    return tuple(k) if isinstance(k, list) else k


class PackedSpillStore(MutableMapping):
    def __init__(
        self,
        directory: str,
        capacity: int = 65536,
        serialize: Callable[[Any], str] = lambda v: json.dumps(v),
        deserialize: Callable[[str], Any] = lambda s: json.loads(s),
        segment_bytes: int = 4 * 1024 * 1024,
        compact_ratio: float = 0.5,
        subdirs: int = 64,
    ):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.dir = directory
        self.capacity = int(capacity)
        self._ser = serialize
        self._de = deserialize
        self.segment_bytes = max(4096, int(segment_bytes))
        self.compact_ratio = min(1.0, max(0.05, float(compact_ratio)))
        self.subdirs = max(1, int(subdirs))
        self._mem: "OrderedDict[Any, Any]" = OrderedDict()  # LRU: MRU last
        # key -> (segment id, payload offset, payload length)
        self._index: Dict[Any, Tuple[int, int, int]] = {}
        # segment id -> {"live": n, "dead": n, "bytes": n}
        self._segments: Dict[int, Dict[str, int]] = {}
        self._seg_id = 0          # current tail segment
        self._tail: Optional[Any] = None  # open append handle
        self._tail_off = 0        # committed end of the tail segment
        self.compactions = 0      # lifetime compacted segments (stats)
        # scratch semantics: wipe any previous incarnation's spills —
        # both this layout and a legacy flat/sharded DiskMap layout (a
        # deployment switching PACKED_SPILL reuses the same directory)
        if os.path.isdir(directory):
            for entry in os.listdir(directory):
                p = os.path.join(directory, entry)
                try:
                    if os.path.isdir(p):
                        shutil.rmtree(p)
                    elif entry.endswith((".dm", ".seg")):
                        os.remove(p)
                except OSError:
                    pass
        os.makedirs(directory, exist_ok=True)

    # ---- segment plumbing ---------------------------------------------
    def _seg_path(self, seg: int) -> str:
        sub = os.path.join(self.dir, f"{seg % self.subdirs:02x}")
        return os.path.join(sub, f"seg{seg:08d}.seg")

    def _open_tail(self):
        if self._tail is None:
            path = self._seg_path(self._seg_id)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._tail = open(path, "ab")
            self._tail_off = self._tail.tell()
            self._segments.setdefault(
                self._seg_id, {"live": 0, "dead": 0, "bytes": self._tail_off}
            )
        return self._tail

    def _roll_if_full(self) -> None:
        if self._tail_off >= self.segment_bytes:
            if self._tail is not None:
                self._tail.close()
                self._tail = None
            self._seg_id += 1
            self._tail_off = 0

    def _append_one(self, key: Any, value: Any) -> None:
        """Append one record to the tail.  Write-before-pop with torn-
        tail repair: on ANY failure the segment truncates back to the
        committed offset and the entry stays in memory — a failed spill
        surfaces to the caller without corrupting the segment."""
        payload = self._ser([_key_to_wire(key), value]).encode("utf-8")
        f = self._open_tail()
        try:
            f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
            f.write(payload)
            f.flush()
        except OSError:
            # torn tail: drop the partial record so later appends start
            # at a clean frame boundary
            try:
                f.truncate(self._tail_off)
            except OSError:
                pass
            raise
        off = self._tail_off + _HDR.size
        self._index[key] = (self._seg_id, off, len(payload))
        self._tail_off = off + len(payload)
        seg = self._segments[self._seg_id]
        seg["live"] += 1
        seg["bytes"] = self._tail_off
        del self._mem[key]
        self._roll_if_full()

    def _read_record(self, seg: int, off: int, length: int) -> Any:
        with open(self._seg_path(seg), "rb") as f:
            f.seek(off - _HDR.size)
            hdr = f.read(_HDR.size)
            payload = f.read(length)
        if len(hdr) != _HDR.size or len(payload) != length:
            raise SpillCorruption(f"torn record in segment {seg} @ {off}")
        want_len, want_crc = _HDR.unpack(hdr)
        if want_len != length or zlib.crc32(payload) != want_crc:
            raise SpillCorruption(f"corrupt record in segment {seg} @ {off}")
        k, value = self._de(payload.decode("utf-8"))
        return _key_from_wire(k), value

    def _scan_segment(self, seg_id: int):
        """Yield (key, value, payload offset) for every intact record in
        a segment, in file order; stops cleanly at a torn tail."""
        try:
            f = open(self._seg_path(seg_id), "rb")
        except OSError:
            return
        with f:
            pos = 0
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                length, crc = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return  # torn tail
                k, value = self._de(payload.decode("utf-8"))
                yield _key_from_wire(k), value, pos + _HDR.size
                pos += _HDR.size + length

    def _mark_dead(self, key: Any) -> None:
        seg_id, _off, _len = self._index.pop(key)
        seg = self._segments.get(seg_id)
        if seg is None:
            return
        seg["live"] -= 1
        seg["dead"] += 1
        self._maybe_compact(seg_id)

    def _maybe_compact(self, seg_id: int) -> None:
        """Rewrite a dead-heavy NON-tail segment: live records re-append
        to the tail (they become the most recent stratum — they were
        touched last), the file unlinks.  O(segment) per trigger,
        amortized by the ratio gate."""
        if seg_id == self._seg_id:
            return  # never compact the open tail in place
        seg = self._segments.get(seg_id)
        if seg is None:
            return
        total = seg["live"] + seg["dead"]
        if total == 0 or seg["dead"] / total < self.compact_ratio:
            return
        # ONE sequential scan of the segment (dead records skip by frame,
        # never an O(index) sweep): a record is live iff the index still
        # points at its offset
        for key, value, off in self._scan_segment(seg_id):
            ent = self._index.get(key)
            if ent is None or ent[0] != seg_id or ent[1] != off:
                continue  # dead, or a newer copy lives elsewhere
            # stage through memory so _append_one's bookkeeping applies
            self._mem[key] = value
            del self._index[key]
            self._append_one(key, value)
        try:
            os.remove(self._seg_path(seg_id))
        except OSError:
            pass
        del self._segments[seg_id]
        self.compactions += 1

    # ---- spill / restore ----------------------------------------------
    def _spill_lru(self) -> None:
        """Page out the least-recently-used half (Deactivator batch) as
        one sequential append run."""
        n = max(1, len(self._mem) - self.capacity // 2)
        self.demote_batch(list(self._mem)[:n])

    def demote(self, key: Any) -> bool:
        """Page one entry out NOW (hibernate support).  Unknown keys
        return False; already-spilled keys are left alone."""
        if key not in self._mem:
            return key in self._index
        self._append_one(key, self._mem[key])
        return True

    def demote_batch(self, keys: Iterable[Any]) -> int:
        """Batched demote: one sequential append run over the tail
        segment(s) — the pause-burst fast path."""
        n = 0
        for key in keys:
            if key in self._mem:
                self._append_one(key, self._mem[key])
                n += 1
            elif key in self._index:
                n += 1
        return n

    def _restore(self, key: Any) -> Any:
        seg, off, ln = self._index[key]
        _k, value = self._read_record(seg, off, ln)
        self._mark_dead(key)
        self[key] = value  # promotes (and may re-spill others)
        return value

    def restore_batch(self, keys: List[Any]) -> Dict[Any, Any]:
        """Promote many spilled entries with sequential per-segment
        reads (sorted by (segment, offset)); in-memory keys ride along.
        Returns {key: value} for every key found; unknown keys are
        skipped.  ONE LRU spill pass runs at the end, so a wake burst
        does not thrash the memory tier per key."""
        out: Dict[Any, Any] = {}
        spilled = [(k, self._index[k]) for k in keys
                   if k not in self._mem and k in self._index]
        spilled.sort(key=lambda kv: (kv[1][0], kv[1][1]))
        for key, _stale in spilled:
            # re-resolve: a compaction triggered by an earlier restore in
            # THIS batch may have moved the record to the tail
            ent = self._index.get(key)
            if ent is None:
                continue
            seg, off, ln = ent
            _k, value = self._read_record(seg, off, ln)
            self._mark_dead(key)
            self._mem[key] = value
            self._mem.move_to_end(key)
            out[key] = value
        for key in keys:
            if key in self._mem and key not in out:
                self._mem.move_to_end(key)
                out[key] = self._mem[key]
        if len(self._mem) > self.capacity:
            self._spill_lru()
        return out

    # ---- MutableMapping ------------------------------------------------
    def __getitem__(self, key: Any) -> Any:
        if key in self._mem:
            self._mem.move_to_end(key)
            return self._mem[key]
        if key in self._index:
            return self._restore(key)
        raise KeyError(key)

    def __setitem__(self, key: Any, value: Any) -> None:
        if key in self._index:
            self._mark_dead(key)
        self._mem[key] = value
        self._mem.move_to_end(key)
        if len(self._mem) > self.capacity:
            self._spill_lru()

    def __delitem__(self, key: Any) -> None:
        if key in self._mem:
            del self._mem[key]
            return
        if key not in self._index:
            raise KeyError(key)
        self._mark_dead(key)

    def __contains__(self, key: Any) -> bool:
        return key in self._mem or key in self._index

    def __iter__(self) -> Iterator:
        yield from list(self._mem)
        yield from list(self._index)

    def __len__(self) -> int:
        return len(self._mem) + len(self._index)

    def peek_items(self) -> Iterator:
        """(key, value) over everything WITHOUT promoting spilled
        entries (checkpoint-style full iteration must not churn the
        memory tier); spilled records read in (segment, offset) order."""
        for key in list(self._mem):
            yield key, self._mem[key]
        for key, (seg, off, ln) in sorted(
            self._index.items(), key=lambda kv: (kv[1][0], kv[1][1])
        ):
            _k, value = self._read_record(seg, off, ln)
            yield key, value

    # ---- stats ---------------------------------------------------------
    @property
    def n_in_memory(self) -> int:
        return len(self._mem)

    @property
    def n_on_disk(self) -> int:
        return len(self._index)

    def stats(self) -> Dict[str, Any]:
        live = sum(s["live"] for s in self._segments.values())
        dead = sum(s["dead"] for s in self._segments.values())
        disk = sum(s["bytes"] for s in self._segments.values())
        return {
            "kind": "packed",
            "in_memory": len(self._mem),
            "on_disk": len(self._index),
            "segments": len(self._segments),
            "live_records": live,
            "dead_records": dead,
            "disk_bytes": disk,
            "bytes_per_record": round(disk / live, 1) if live else 0.0,
            "compactions": self.compactions,
        }

    def close(self) -> None:
        if self._tail is not None:
            self._tail.close()
            self._tail = None
