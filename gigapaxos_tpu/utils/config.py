"""Three-tier configuration/flag system: enum defaults < properties file < CLI.

Re-creation of the reference's ``utils/Config`` semantics
(``src/edu/umass/cs/utils/Config.java:15``, ``getGlobal*`` at 226-343,
``Config.register(args)`` used from ``PaxosServer.main:140``): flags are
declared as enum members carrying their default value; a properties file
(named by the ``GIGAPAXOS_CONFIG`` env var or ``-DgigapaxosConfig=...``-style
CLI arg, default ``gigapaxos.properties``) overrides defaults; explicit
``key=value`` CLI args / programmatic overrides take highest precedence.

Node addresses use the reference's scheme (``SURVEY.md`` §5): lines of the
form ``active.NAME=host:port`` and ``reconfigurator.NAME=host:port``.
"""

from __future__ import annotations

import enum
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple, Type

_TRUE = frozenset(("true", "1", "yes", "on"))
_FALSE = frozenset(("false", "0", "no", "off"))


class FlagEnum(enum.Enum):
    """Flag enum whose members carry their DEFAULT without enum aliasing.

    A plain ``enum.Enum`` treats members with equal values as ALIASES of
    one member — ``BATCHING_ENABLED = True`` and ``ENABLE_JOURNALING =
    True`` would be the SAME flag, so overriding one silently overrode
    every equal-valued sibling (this bit for real: setting
    ``BATCHING_ENABLED=false`` turned journaling off).  Members here get
    a unique ordinal ``value`` and keep the declared default in
    ``.default``."""

    def __new__(cls, default):
        obj = object.__new__(cls)
        obj._value_ = len(cls.__members__)  # unique ordinal: never aliases
        obj.default = default
        return obj


def flag_default(member: Any) -> Any:
    """The declared default of a flag member (FlagEnum or plain enum)."""
    return getattr(member, "default", member.value)


def _coerce(raw: str, default: Any) -> Any:
    """Coerce a string property to the type of the enum default."""
    if isinstance(default, bool):
        low = raw.strip().lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise ValueError(f"cannot parse boolean from {raw!r}")
    if isinstance(default, int) and not isinstance(default, bool):
        return int(raw, 0)
    if isinstance(default, float):
        return float(raw)
    return raw


def parse_properties(text: str) -> Dict[str, str]:
    """Parse a java-style .properties file body into a dict."""
    props: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        for sep in ("=", ":"):
            if sep in line:
                key, _, val = line.partition(sep)
                props[key.strip()] = val.strip()
                break
    return props


class Config:
    """Global registry of flag enums with three-tier override resolution."""

    _lock = threading.RLock()
    _defaults: Dict[str, Any] = {}  # "EnumClassName.MEMBER" and bare "MEMBER"
    _file_props: Dict[str, str] = {}
    _cli: Dict[str, str] = {}
    _registered: Dict[str, Type[enum.Enum]] = {}

    # ---- registration -------------------------------------------------
    @classmethod
    def register(cls, flag_enum: Type[enum.Enum]) -> None:
        """Register a flag enum whose member values are the defaults."""
        with cls._lock:
            cls._registered[flag_enum.__name__] = flag_enum
            for member in flag_enum:
                default = flag_default(member)
                cls._defaults[f"{flag_enum.__name__}.{member.name}"] = default
                # Bare name resolves too; a later-registered enum shadows an
                # earlier one (qualified "Enum.MEMBER" names never collide).
                cls._defaults[member.name] = default

    @classmethod
    def load_file(cls, path: str) -> None:
        with open(path, "r", encoding="utf-8") as f:
            props = parse_properties(f.read())
        with cls._lock:
            cls._file_props.update(props)

    @classmethod
    def register_args(cls, argv: Iterable[str]) -> Tuple[str, ...]:
        """Consume ``key=value`` CLI args (highest tier); return the rest.

        Mirrors ``Config.register(args)`` in the reference: non ``k=v`` args
        are passed through to the caller untouched.
        """
        rest = []
        with cls._lock:
            for arg in argv:
                if "=" in arg and not arg.startswith("-"):
                    key, _, val = arg.partition("=")
                    cls._cli[key.strip()] = val.strip()
                else:
                    rest.append(arg)
        return tuple(rest)

    @classmethod
    def set(cls, key: Any, value: Any) -> None:
        """Programmatic override (same tier as CLI)."""
        with cls._lock:
            cls._cli[cls._key_name(key)] = str(value)

    # ---- lookup -------------------------------------------------------
    @staticmethod
    def _key_name(key: Any) -> str:
        if isinstance(key, enum.Enum):
            return key.name
        return str(key)

    @classmethod
    def _lookup_raw(cls, key: Any) -> Tuple[Optional[str], Any]:
        """Return (raw_override_or_None, default)."""
        if isinstance(key, enum.Enum):
            names = (f"{type(key).__name__}.{key.name}", key.name)
            default = flag_default(key)
        else:
            names = (str(key),)
            default = cls._defaults.get(str(key))
        with cls._lock:
            for name in names:
                if name in cls._cli:
                    return cls._cli[name], default
            env = os.environ.get("GP_" + names[-1])
            if env is not None:
                return env, default
            for name in names:
                if name in cls._file_props:
                    return cls._file_props[name], default
        return None, default

    @classmethod
    def get(cls, key: Any) -> Any:
        raw, default = cls._lookup_raw(key)
        if raw is None:
            return default
        return _coerce(raw, default)

    @classmethod
    def is_set(cls, key: Any) -> bool:
        """True when a file/env/CLI tier explicitly provides the key
        (some behaviors — e.g. CLI-node durability — should only switch
        on for an operator's explicit choice, not an enum default)."""
        raw, _ = cls._lookup_raw(key)
        return raw is not None

    # Typed conveniences mirroring the reference's getGlobal{Int,Boolean,...}
    @classmethod
    def get_int(cls, key: Any) -> int:
        return int(cls.get(key))

    @classmethod
    def get_bool(cls, key: Any) -> bool:
        val = cls.get(key)
        if isinstance(val, bool):
            return val
        return str(val).strip().lower() in _TRUE

    @classmethod
    def get_float(cls, key: Any) -> float:
        return float(cls.get(key))

    @classmethod
    def get_str(cls, key: Any) -> str:
        return str(cls.get(key))

    # ---- node address book (active.NAME= / reconfigurator.NAME=) -----
    @classmethod
    def node_addresses(cls, prefix: str) -> Dict[str, Tuple[str, int]]:
        """Extract ``{prefix}.NAME=host:port`` entries from all tiers."""
        out: Dict[str, Tuple[str, int]] = {}
        with cls._lock:
            merged = dict(cls._file_props)
            merged.update(cls._cli)
        want = prefix + "."
        for key, val in merged.items():
            if key.startswith(want):
                name = key[len(want):]
                host, _, port = val.partition(":")
                out[name] = (host, int(port))
        return out

    @classmethod
    def overrides(cls) -> Dict[str, str]:
        """The merged file + programmatic/CLI override tiers, as raw
        strings — what a parent must ship to a spawned worker process
        (as ``key=value`` argv) for the child to see the same effective
        config without sharing a properties file."""
        with cls._lock:
            merged = dict(cls._file_props)
            merged.update(cls._cli)
        return merged

    @classmethod
    def clear(cls) -> None:
        """Reset all overrides (for tests)."""
        with cls._lock:
            cls._file_props.clear()
            cls._cli.clear()


def load_default_config_file() -> None:
    """Load the properties file named by GIGAPAXOS_CONFIG if present."""
    path = os.environ.get("GIGAPAXOS_CONFIG", "gigapaxos.properties")
    if os.path.exists(path):
        Config.load_file(path)
