from .config import Config
from .profiler import DelayProfiler

__all__ = ["Config", "DelayProfiler"]
