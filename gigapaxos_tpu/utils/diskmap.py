"""DiskMap — a capacity-bounded mapping that pages cold entries to disk.

API-parity target: ``utils/DiskMap`` (``DiskMap.java:97``): a map that
"pauses" idle entries to disk via commit/restore and transparently
restores them on access — the reference uses it for the journal's
per-group ``LogIndex`` and optionally the RC DB.  Here it bounds the RAM
of host-side per-group tables (e.g. the residency pause records: at the
1M-group design scale the paused-snapshot table must not live fully in
memory).

Not a durability mechanism: the journal/checkpoint own persistence; a
DiskMap's spill directory is scratch owned by one process instance.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from collections.abc import MutableMapping
from typing import Any, Callable, Iterator, Optional


class DiskMap(MutableMapping):
    def __init__(
        self,
        directory: str,
        capacity: int = 65536,
        serialize: Callable[[Any], str] = lambda v: json.dumps(v),
        deserialize: Callable[[str], Any] = lambda s: json.loads(s),
    ):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.dir = directory
        self.capacity = int(capacity)
        self._ser = serialize
        self._de = deserialize
        os.makedirs(directory, exist_ok=True)
        self._mem: "OrderedDict[Any, Any]" = OrderedDict()  # LRU: MRU last
        self._on_disk: dict = {}  # key -> filename
        # clear stale spills from a previous incarnation (scratch semantics)
        for f in os.listdir(directory):
            if f.endswith(".dm"):
                try:
                    os.remove(os.path.join(directory, f))
                except OSError:
                    pass

    # ---- spill machinery (commit/restore analog) -----------------------
    def _fname(self, key: Any) -> str:
        h = hashlib.blake2b(repr(key).encode(), digest_size=12).hexdigest()
        return f"{h}.dm"

    def _spill_one(self, key: Any) -> None:
        """Page one in-memory entry out.  Write-before-pop: a failed
        spill (ENOSPC) must not lose the entry — it stays in memory and
        the error surfaces to the caller."""
        value = self._mem[key]
        fname = self._fname(key)
        path = os.path.join(self.dir, fname)
        with open(path, "w", encoding="utf-8") as f:
            f.write(self._ser(value))
        del self._mem[key]
        self._on_disk[key] = fname

    def _spill_lru(self) -> None:
        """Page out the least-recently-used half (Deactivator batch)."""
        n = max(1, len(self._mem) - self.capacity // 2)
        for _ in range(n):
            self._spill_one(next(iter(self._mem)))

    def _restore(self, key: Any) -> Any:
        fname = self._on_disk.pop(key)
        path = os.path.join(self.dir, fname)
        with open(path, "r", encoding="utf-8") as f:
            value = self._de(f.read())
        os.remove(path)
        self[key] = value  # promotes (and may re-spill others)
        return value

    # ---- MutableMapping ------------------------------------------------
    def __getitem__(self, key: Any) -> Any:
        if key in self._mem:
            self._mem.move_to_end(key)
            return self._mem[key]
        if key in self._on_disk:
            return self._restore(key)
        raise KeyError(key)

    def __setitem__(self, key: Any, value: Any) -> None:
        if key in self._on_disk:
            fname = self._on_disk.pop(key)
            try:
                os.remove(os.path.join(self.dir, fname))
            except OSError:
                pass
        self._mem[key] = value
        self._mem.move_to_end(key)
        if len(self._mem) > self.capacity:
            self._spill_lru()

    def __delitem__(self, key: Any) -> None:
        if key in self._mem:
            del self._mem[key]
            return
        fname = self._on_disk.pop(key, None)
        if fname is None:
            raise KeyError(key)
        try:
            os.remove(os.path.join(self.dir, fname))
        except OSError:
            pass

    def __contains__(self, key: Any) -> bool:
        return key in self._mem or key in self._on_disk

    def __iter__(self) -> Iterator:
        yield from list(self._mem)
        yield from list(self._on_disk)

    def __len__(self) -> int:
        return len(self._mem) + len(self._on_disk)

    def peek_items(self) -> Iterator:
        """(key, value) over everything WITHOUT promoting spilled entries
        (plain items() restores each spilled key into memory — a full
        iteration, e.g. for checkpointing, would defeat the RAM bound and
        churn every spill file)."""
        for key in list(self._mem):
            yield key, self._mem[key]
        for key, fname in list(self._on_disk.items()):
            with open(os.path.join(self.dir, fname), "r",
                      encoding="utf-8") as f:
                yield key, self._de(f.read())

    def demote(self, key: Any) -> bool:
        """Explicitly page one entry out to disk NOW (hibernate support:
        the caller wants this entry's RAM back immediately instead of
        waiting for LRU pressure).  Returns False for unknown keys;
        already-spilled keys are left alone."""
        if key not in self._mem:
            return key in self._on_disk
        self._spill_one(key)
        return True

    @property
    def n_in_memory(self) -> int:
        return len(self._mem)

    @property
    def n_on_disk(self) -> int:
        return len(self._on_disk)
