"""DiskMap — a capacity-bounded mapping that pages cold entries to disk.

API-parity target: ``utils/DiskMap`` (``DiskMap.java:97``): a map that
"pauses" idle entries to disk via commit/restore and transparently
restores them on access — the reference uses it for the journal's
per-group ``LogIndex`` and optionally the RC DB.  Here it bounds the RAM
of host-side per-group tables (e.g. the residency pause records: at the
1M-group design scale the paused-snapshot table must not live fully in
memory).

Spill files fan over hash-sharded subdirectories (``ab/<hash>.dm``, 256
shards): a flat directory holding millions of file-per-key spills
degrades directory operations on most filesystems and was the density
campaign's first casualty.  Entries remember the relative path they were
written under, so the layout is self-describing; construction cleans up
BOTH layouts (legacy flat files from an older incarnation and the
sharded tree), and a restore probes the sharded path first with a
flat-path fallback — an old spill dir never strands records.

Not a durability mechanism: the journal/checkpoint own persistence; a
DiskMap's spill directory is scratch owned by one process instance.
For the paused table at density scale, prefer
:class:`~gigapaxos_tpu.utils.packedstore.PackedSpillStore` (segment
files, bounded inodes); this class remains the simple file-per-key
fallback (``PACKED_SPILL=false``).
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from collections.abc import MutableMapping
from typing import Any, Callable, Iterable, Iterator, Optional


class DiskMap(MutableMapping):
    def __init__(
        self,
        directory: str,
        capacity: int = 65536,
        serialize: Callable[[Any], str] = lambda v: json.dumps(v),
        deserialize: Callable[[str], Any] = lambda s: json.loads(s),
    ):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.dir = directory
        self.capacity = int(capacity)
        self._ser = serialize
        self._de = deserialize
        os.makedirs(directory, exist_ok=True)
        self._mem: "OrderedDict[Any, Any]" = OrderedDict()  # LRU: MRU last
        self._on_disk: dict = {}  # key -> relative spill path
        self._made_shards: set = set()  # shard subdirs known to exist
        # clear stale spills from a previous incarnation (scratch
        # semantics) — the legacy flat layout AND the sharded tree
        for f in os.listdir(directory):
            p = os.path.join(directory, f)
            if f.endswith(".dm"):
                try:
                    os.remove(p)
                except OSError:
                    pass
            elif len(f) == 2 and os.path.isdir(p):
                for g in os.listdir(p):
                    if g.endswith(".dm"):
                        try:
                            os.remove(os.path.join(p, g))
                        except OSError:
                            pass

    # ---- spill machinery (commit/restore analog) -----------------------
    def _fname(self, key: Any) -> str:
        """Relative sharded spill path: ``ab/<hash>.dm`` (first hash
        byte = shard, 256 subdirs — bounds any one directory's entry
        count regardless of key count)."""
        h = hashlib.blake2b(repr(key).encode(), digest_size=12).hexdigest()
        return os.path.join(h[:2], f"{h}.dm")

    def _abspath(self, fname: str) -> str:
        """Resolve a recorded relative spill path, with a legacy
        flat-layout fallback (migration: a record written flat by an
        older layout is still found by its basename)."""
        p = os.path.join(self.dir, fname)
        if os.sep in fname and not os.path.exists(p):
            flat = os.path.join(self.dir, os.path.basename(fname))
            if os.path.exists(flat):
                return flat
        return p

    def _ensure_shard(self, fname: str) -> None:
        shard = os.path.dirname(fname)
        if shard and shard not in self._made_shards:
            os.makedirs(os.path.join(self.dir, shard), exist_ok=True)
            self._made_shards.add(shard)

    def _spill_one(self, key: Any) -> None:
        """Page one in-memory entry out.  Write-before-pop: a failed
        spill (ENOSPC) must not lose the entry — it stays in memory and
        the error surfaces to the caller."""
        value = self._mem[key]
        fname = self._fname(key)
        self._ensure_shard(fname)
        with open(os.path.join(self.dir, fname), "w",
                  encoding="utf-8") as f:
            f.write(self._ser(value))
        del self._mem[key]
        self._on_disk[key] = fname

    def _spill_many(self, keys: Iterable[Any]) -> None:
        """Batched spill: one pass, shard dirs created at most once each
        (the per-key makedirs probe was measurable at pause-burst
        scale)."""
        for key in keys:
            if key in self._mem:
                self._spill_one(key)

    def _spill_lru(self) -> None:
        """Page out the least-recently-used half (Deactivator batch)."""
        n = max(1, len(self._mem) - self.capacity // 2)
        self._spill_many(list(self._mem)[:n])

    def _restore(self, key: Any) -> Any:
        fname = self._on_disk.pop(key)
        path = self._abspath(fname)
        with open(path, "r", encoding="utf-8") as f:
            value = self._de(f.read())
        os.remove(path)
        self[key] = value  # promotes (and may re-spill others)
        return value

    # ---- MutableMapping ------------------------------------------------
    def __getitem__(self, key: Any) -> Any:
        if key in self._mem:
            self._mem.move_to_end(key)
            return self._mem[key]
        if key in self._on_disk:
            return self._restore(key)
        raise KeyError(key)

    def __setitem__(self, key: Any, value: Any) -> None:
        if key in self._on_disk:
            fname = self._on_disk.pop(key)
            try:
                os.remove(self._abspath(fname))
            except OSError:
                pass
        self._mem[key] = value
        self._mem.move_to_end(key)
        if len(self._mem) > self.capacity:
            self._spill_lru()

    def __delitem__(self, key: Any) -> None:
        if key in self._mem:
            del self._mem[key]
            return
        fname = self._on_disk.pop(key, None)
        if fname is None:
            raise KeyError(key)
        try:
            os.remove(self._abspath(fname))
        except OSError:
            pass

    def __contains__(self, key: Any) -> bool:
        return key in self._mem or key in self._on_disk

    def __iter__(self) -> Iterator:
        yield from list(self._mem)
        yield from list(self._on_disk)

    def __len__(self) -> int:
        return len(self._mem) + len(self._on_disk)

    def peek_items(self) -> Iterator:
        """(key, value) over everything WITHOUT promoting spilled entries
        (plain items() restores each spilled key into memory — a full
        iteration, e.g. for checkpointing, would defeat the RAM bound and
        churn every spill file)."""
        for key in list(self._mem):
            yield key, self._mem[key]
        for key, fname in list(self._on_disk.items()):
            with open(self._abspath(fname), "r",
                      encoding="utf-8") as f:
                yield key, self._de(f.read())

    def demote(self, key: Any) -> bool:
        """Explicitly page one entry out to disk NOW (hibernate support:
        the caller wants this entry's RAM back immediately instead of
        waiting for LRU pressure).  Returns False for unknown keys;
        already-spilled keys are left alone."""
        if key not in self._mem:
            return key in self._on_disk
        self._spill_one(key)
        return True

    def demote_batch(self, keys: Iterable[Any]) -> int:
        """Batched demote (pause-burst path): spill every given
        in-memory key; already-spilled keys count as demoted."""
        n = 0
        for key in keys:
            if key in self._mem:
                self._spill_one(key)
                n += 1
            elif key in self._on_disk:
                n += 1
        return n

    @property
    def n_in_memory(self) -> int:
        return len(self._mem)

    @property
    def n_on_disk(self) -> int:
        return len(self._on_disk)

    def stats(self) -> dict:
        return {
            "kind": "file-per-key",
            "in_memory": len(self._mem),
            "on_disk": len(self._on_disk),
        }
