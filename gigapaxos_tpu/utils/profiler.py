"""Global EWMA metric registry — DelayProfiler analog.

Re-creation of ``src/edu/umass/cs/utils/DelayProfiler.java:11,61-165``:
string-keyed exponentially-weighted moving averages, rates, and counters,
dumped as a single stats line.  Used on the hot host path, so updates are
lock-light (a single dict with per-key tuples; GIL-atomic enough for stats).
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class DelayProfiler:
    _lock = threading.Lock()
    _avgs: Dict[str, float] = {}
    _counts: Dict[str, float] = {}
    _rates: Dict[str, tuple] = {}  # key -> (ewma_rate, last_ts)
    ALPHA = 1.0 / 16  # reference uses ~1/10..1/100 depending on call site

    @classmethod
    def update_delay(cls, key: str, t0: float, n: int = 1) -> None:
        """Record elapsed seconds since t0 (divided over n samples)."""
        cls.update_mov_avg(key, (time.monotonic() - t0) / max(n, 1))

    @classmethod
    def update_mov_avg(cls, key: str, sample: float) -> None:
        with cls._lock:
            old = cls._avgs.get(key)
            cls._avgs[key] = (
                sample if old is None else (1 - cls.ALPHA) * old + cls.ALPHA * sample
            )

    @classmethod
    def update_count(cls, key: str, n: float = 1) -> None:
        with cls._lock:
            cls._counts[key] = cls._counts.get(key, 0) + n

    @classmethod
    def update_rate(cls, key: str, n: int = 1) -> None:
        now = time.monotonic()
        with cls._lock:
            ewma, last = cls._rates.get(key, (0.0, now))
            dt = max(now - last, 1e-9)
            inst = n / dt
            cls._rates[key] = ((1 - cls.ALPHA) * ewma + cls.ALPHA * inst, now)

    @classmethod
    def get(cls, key: str) -> float:
        with cls._lock:
            if key in cls._avgs:
                return cls._avgs[key]
            if key in cls._counts:
                return cls._counts[key]
            if key in cls._rates:
                return cls._rates[key][0]
        return 0.0

    @classmethod
    def get_stats(cls) -> str:
        """One-line dump, like the reference's ``DelayProfiler.getStats()``."""
        with cls._lock:
            parts = [f"{k}:{v:.3g}" for k, v in sorted(cls._avgs.items())]
            parts += [f"#{k}:{v:.4g}" for k, v in sorted(cls._counts.items())]
            parts += [f"R({k}):{v:.4g}/s" for k, (v, _) in sorted(cls._rates.items())]
        return "[" + " ".join(parts) + "]"

    @classmethod
    def get_snapshot(cls) -> Dict[str, Dict[str, float]]:
        """Structured (JSON-safe) form of :meth:`get_stats` — the ``stats``
        admin op and the metrics endpoints ship this instead of making
        machine consumers parse the human one-liner."""
        with cls._lock:
            return {
                "avgs": dict(cls._avgs),
                "counts": dict(cls._counts),
                "rates": {k: v for k, (v, _) in cls._rates.items()},
            }

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._avgs.clear()
            cls._counts.clear()
            cls._rates.clear()
