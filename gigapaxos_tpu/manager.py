"""PaxosManager — per-node host orchestration of the batched engine.

API-parity target: ``PaxosManager`` (``PaxosManager.java:120`` —
createPaxosInstance / propose / proposeStop / kill, packet dispatch,
outstanding-request callbacks, response cache, recovery), re-architected
around the vectorized engine:

* All groups' consensus state lives on device ([G]/[G, W] arrays); the
  manager owns the *host* side: name → group-row allocation, the request
  payload arena, app execution, callbacks, durability, and the per-tick
  drive loop.
* Inter-replica consensus traffic is the engine blob (tensor exchange);
  the manager's host channel carries only what tensors can't: request
  payloads (vid → bytes), mirroring the reference's DIGEST_REQUESTS mode
  (``PaxosConfig.java:780``) where accepts carry digests and request
  bodies travel once.
* A replica that is not a group's coordinator forwards proposals to the
  believed coordinator (the unicast-PROPOSAL path,
  ``PaxosInstanceStateMachine.java:837-851``) via the host channel.

The tick cycle (one call to :meth:`tick`):
  1. drain per-group request queues into the [G, K] admission lanes;
  2. run the jitted engine step;
  3. journal the accept delta (log-before-send,
     ``AbstractPaxosLogger.logAndMessage`` rule) and new payloads;
  4. execute newly decided slots in order through the app (payload-gated:
     a slot whose payload hasn't arrived yet parks the group's cursor —
     the retry-forever analog of ``PaxosInstanceStateMachine.execute``);
  5. fire entry-replica callbacks / response cache;
  6. return the fresh blob + host-channel payload delta for publication.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .interfaces.app import Replicable
from .ops.ballot import NULL, ballot_coord, ballot_num, encode_ballot
from .packets.paxos_packets import (
    RequestPacket,
    StatePacket,
    SyncDecisionsPacket,
)
from .paxos_config import PC
from .utils.config import Config
from .ops.engine import (
    STOP_BIT,
    Blob,
    EngineConfig,
    EngineState,
    init_state,
    StepOutputs,
    make_blob,
    pack_blob,
    split_blob_vec,
    split_out_vec,
)
from .parallel.spmd import make_step
from .obs import gplog
from .obs.flight import FlightRecorder
from .obs.metrics import MetricsRegistry
from .obs.reqtrace import RequestTracer
from .ops.lifecycle import create_groups, kill_groups, restore_paused_rows
from .storage.logger import PaxosLogger
from .utils.profiler import DelayProfiler

# Every tick flavor steps through the ONE unified factory
# (parallel/spmd.py:make_step, io="packed_host").  The dispatch path
# donates the state: the manager owns it exclusively (every external view
# is an identity check or a host-side numpy copy), so the old buffers may
# be reused in place by the new state — on-device this halves state HBM;
# backends without donation support ignore it.  The Blob-exchange tick
# (_tick_locked, the test-cluster harness) uses a donate=False instance:
# that harness caches blob views aliasing the live state across ticks.
_pack_blob_jit = jax.jit(pack_blob)
# Blob of [R, ...] leaves -> [R, NB] packed rows (Blob._fields order, C
# ravel per leaf — each row identical to pack_blob of that replica's
# blob); the Blob-exchange tick packs its gathered blobs through this to
# reach the unified packed step.
_pack_rows_jit = jax.jit(
    lambda b: jnp.concatenate([x.reshape(x.shape[0], -1) for x in b], axis=1)
)


def _mix32(h: int, vid: int) -> int:
    """Host mirror of the engine's app-hash fold (int32 wraparound)."""
    with np.errstate(over="ignore"):
        h32 = np.int32(h)
        v32 = np.int32(vid)
        return int((h32 * np.int32(31) + v32) ^ (v32 << np.int32(7)))

def _instance_tag(name: str, epoch: int) -> int:
    """Deterministic nonzero int32 identity of (name, epoch) — the blob's
    cross-instance guard (engine ``tag`` lane).  Every replica computes it
    from the same create parameters, so tags agree without coordination;
    0 is reserved for inert rows."""
    import zlib

    t = zlib.crc32(f"{name}:{int(epoch)}".encode("utf-8")) & 0x7FFFFFFF
    return t or 1


# vid layout: [node_id : 5][counter : 24] under STOP_BIT (bit 30) and
# BATCH_BIT (bit 29) — the counter wraps per node at ~16M in-flight
# request payloads, far above the outstanding cap; node ids follow
# ballot.COORD_BITS (ids 0..31).  A BATCH vid's arena payload is not an
# app request but an encoded ORDERED LIST of client requests decided as
# one consensus value (the true RequestBatcher semantics: up to
# MAX_BATCH_SIZE requests per proposal, RequestPacket.java:189-246 nested
# `batched` array + PaxosManager.proposeBatched:1226); execution unpacks
# and runs each sub-request through the app with per-request dedup and
# callbacks.  STOP_BIT and BATCH_BIT never combine: an epoch-final stop
# is epoch-scoped and rides alone.
VID_NODE_SHIFT = 24
VID_COUNTER_MASK = (1 << VID_NODE_SHIFT) - 1
BATCH_BIT = 1 << 29


def encode_batch(subs: List[Tuple[int, int, str]]) -> str:
    """Encode [(request_id, entry_replica, value), ...] as one arena
    payload.  JSON keeps Python ints exact (client ids reach 2^62)."""
    return json.dumps(subs, separators=(",", ":"))


def decode_batch(payload: str) -> List[Tuple[int, int, str]]:
    return [(int(r), int(e), v) for r, e, v in json.loads(payload)]


class SlimRequest(RequestPacket):
    """Hot-path request object for decided-slot execution.

    Constructing the full ``RequestPacket`` dataclass (field machinery +
    ``__post_init__`` batched/address coercions) was the single biggest
    executor cost at batch scale — ~3 constructions per client request
    across a 3-replica group.  This subclass keeps ``isinstance(...,
    RequestPacket)`` contracts (the RC record app asserts it) but assigns
    only the consumed fields."""

    def __init__(self, paxos_id: str, request_id: int, request_value: str,
                 stop: bool = False):
        self.paxos_id = paxos_id
        self.version = -1
        self.request_id = request_id
        self.request_value = request_value
        self.stop = stop
        self.entry_replica = -1
        self.client_address = None
        self.response_value = None
        self.batched = []
        self.entry_time = 0.0


def execute_uncoordinated(app, names, name: str, value: str, request_id,
                          callback, gate=None) -> Optional[bool]:
    """Uncoordinated local execution (linearizable-writes / local-reads
    apps, ref ``LinWritesLocReadsApp.java:26-44``): when the app declares
    a request uncoordinated via ``is_coordinated``, answer it from THIS
    replica's state without entering consensus — no vid, no inflight
    slot, no dedup entry (a re-sent read just re-reads).  The ONE routing
    block shared by the coordinator and the server ingress paths.

    Returns ``True`` if executed locally, ``False`` if the request IS
    uncoordinated but ``name`` isn't hosted here, ``None`` if the app
    doesn't route or the request is coordinated (caller proposes
    normally)."""
    is_coord = getattr(app, "is_coordinated", None)
    if is_coord is None or is_coord(value):
        return None
    if names.get(name) is None:
        return False
    if gate is not None and not gate(name):
        # un-hydrated name (recovery plane): reading its app state now
        # would serve the pre-restore blank — fall through to the
        # coordinated path, which queues until hydration lands
        return None
    req = SlimRequest(name, int(request_id or 0), value)
    app.execute(req, do_not_reply_to_client=False)
    if callback is not None:
        callback(request_id, getattr(req, "response_value", None))
    return True


class Outstanding:
    """Entry-replica callback table with TTL GC (GCConcurrentHashMap analog,
    ``PaxosManager.java:192-207``)."""

    def __init__(self, timeout_s: Optional[float] = None):
        if timeout_s is None:
            timeout_s = Config.get_float(PC.REQUEST_TIMEOUT_S)
        self.timeout_s = timeout_s
        self._map: Dict[int, Tuple[float, Callable]] = {}

    def put(self, request_id: int, cb: Callable) -> None:
        self._map[request_id] = (time.time(), cb)

    def put_at(self, request_id: int, cb: Callable, t: float) -> None:
        """put() with a caller-shared timestamp (batched ingress)."""
        self._map[request_id] = (t, cb)

    def pop(self, request_id: int) -> Optional[Callable]:
        ent = self._map.pop(request_id, None)
        return ent[1] if ent else None

    def gc(self) -> int:
        cut = time.time() - self.timeout_s
        dead = [k for k, (t, _) in self._map.items() if t < cut]
        for k in dead:
            del self._map[k]
        return len(dead)

    def __len__(self) -> int:
        return len(self._map)


class PaxosManager:
    def __init__(
        self,
        my_id: int,
        app: Replicable,
        cfg: EngineConfig,
        log_dir: Optional[str] = None,
        sync_journal: Optional[bool] = None,
        checkpoint_every: Optional[int] = None,  # CHECKPOINT_INTERVAL analog
        jump_horizon: Optional[int] = None,      # slots; None -> flag * W
    ):
        self.my_id = int(my_id)
        self.app = app
        self.cfg = cfg
        G, W, K = cfg.n_groups, cfg.window, cfg.req_lanes
        # observability plane: structured log, bounded per-request trace
        # ring (DEBUG-gated; GP_TRACE=1 / GP_LOG=trace:DEBUG), and the
        # per-step engine metrics registry (always on — per-STEP numpy
        # reductions, never per-request work)
        self.log = gplog.node_logger("manager", my_id)
        self.tracer = RequestTracer(my_id)
        self.metrics = MetricsRegistry(node=my_id)
        # black-box flight recorder (obs/flight.py): always-on bounded
        # rings of per-step engine summaries + last-K decided
        # (group, slot, ballot, vid), dumped on divergence/exception/
        # `flightdump` — O(1) per tick, fed from _post_step_locked
        self.flight = FlightRecorder(my_id)
        # cross-node trace contexts: request_id -> (trace_id, origin,
        # hop) for requests sampled at their origin (GP_TRACE_SAMPLE).
        # Installed at propose time (client frame / forward) or from
        # payload gossip, read on the decide/execute/flush paths so
        # every hop's reqtrace events share the trace id.  Bounded FIFO;
        # mutations run under the state lock, the flush path's read is
        # a benign racy dict lookup (diagnostics only).
        self.trace_ctx: "Dict[int, Tuple[int, int, int]]" = {}
        self.TRACE_CTX_CAP = 8192
        # contexts installed HERE since the last tick, to gossip to
        # peers on the payloads frame (drained by _post_step_locked) —
        # peers need the context to stamp their decide/execute events
        self._tc_gossip: Dict[int, Tuple[int, int, int]] = {}
        # host cache of each row's last-known coordinator id (from the
        # promised ballot) — flip counting reads `bal` only on the rare
        # ticks where a ballot actually rose (bal_new nonzero)
        self._coord_cache = np.full(G, -1, np.int32)
        # host view of each row's promised ballot, under the same
        # discipline: seeded at create (the initial ballot is computed
        # host-side), refreshed from the one rise-tick `bal` pull.  The
        # decide events' (group, slot, ballot) attribution and the
        # flight recorder's decided ring read THIS, never the device —
        # a per-commit-tick `bal` pull costs a device sync per tick
        self._bal_host = np.full(G, NULL, np.int32)

        # explicit ctor args win; otherwise the three-tier flag system
        # decides (defaults < properties file < env/CLI — PaxosConfig.PC)
        if sync_journal is None:
            sync_journal = Config.get_bool(PC.SYNC_JOURNAL)
        if not Config.get_bool(PC.ENABLE_JOURNALING):
            log_dir = None
        self.logger: Optional[PaxosLogger] = (
            PaxosLogger(
                my_id, log_dir, sync=sync_journal,
                max_file_size=Config.get_int(PC.MAX_LOG_FILE_SIZE),
            ) if log_dir else None
        )
        self.checkpoint_every = (
            Config.get_int(PC.CHECKPOINT_INTERVAL)
            if checkpoint_every is None else checkpoint_every
        )
        # members lagging more than this many slots behind the majority
        # are written off for payload retention and recover via checkpoint
        # transfer; MAX_SYNC_DECISIONS_GAP caps the horizon outright (a
        # member further behind than the cap always jumps, never syncs —
        # PaxosInstanceStateMachine.java:130)
        self.jump_horizon = (
            min(
                Config.get_int(PC.JUMP_HORIZON_WINDOWS) * cfg.window,
                Config.get_int(PC.MAX_SYNC_DECISIONS_GAP),
            )
            if jump_horizon is None else int(jump_horizon)
        )
        # missing-decision count past which a straggler's pull flags
        # "missing too much" and peers prefer serving a checkpoint over
        # individual payloads (SYNC_THRESHOLD, :127)
        self.sync_threshold = max(
            cfg.window, Config.get_int(PC.SYNC_THRESHOLD)
        )
        # group-size ceiling (MAX_GROUP_SIZE, PaxosConfig.java:532); the
        # engine's member bitmask caps at 32 regardless
        self.max_group_size = min(32, Config.get_int(PC.MAX_GROUP_SIZE))
        # exactly-once dedup window: like the reference's TTL'd
        # GCConcurrentHashMap (PaxosManager.java:318-346), dedup is
        # guaranteed only within the cache's TTL+size window — a duplicate
        # re-proposal arriving after eviction can re-execute
        self.response_cache_ttl = Config.get_float(PC.RESPONSE_CACHE_TTL_S)
        self.response_cache_cap = Config.get_int(PC.RESPONSE_CACHE_SIZE)
        # admission back-pressure (MAX_OUTSTANDING_REQUESTS 8000 analog,
        # PaxosConfig.java:537): past this many in-flight requests the
        # entry path refuses with "overload" and clients back off
        self.max_outstanding = Config.get_int(PC.MAX_OUTSTANDING_REQUESTS)
        # request coalescing (RequestBatcher analog, RequestBatcher.java:40):
        # when a coordinated row's queue exceeds the lane count, consecutive
        # plain requests are packed into ONE consensus value (a BATCH vid)
        # of up to MAX_BATCH_SIZE sub-requests, so a hot group's throughput
        # is bounded by lanes*batch per tick, not lanes per tick
        self.batching_enabled = Config.get_bool(PC.BATCHING_ENABLED)
        self.max_batch_size = max(1, Config.get_int(PC.MAX_BATCH_SIZE))
        # minimum queued requests before coalescing bothers minting a batch
        # (MIN_PP_BATCH_SIZE gate analog, PaxosConfig.java:852)
        self.min_batch_trigger = max(2, Config.get_int(PC.MIN_PP_BATCH_SIZE))
        # multi-step device residency: N consensus rounds per host
        # dispatch over device-resident request/response rings — one
        # Python dispatch + sync + post-step cycle per N engine steps
        self.steps_per_dispatch = max(
            1, Config.get_int(PC.ENGINE_STEPS_PER_DISPATCH)
        )
        # the ONE unified step (parallel/spmd.py:make_step), packed-host
        # flavor; instances are memoized by (cfg, N, donate, heat), so
        # jit caches are shared across managers with the same shape.
        # heat=True threads the [G] device-resident activity accumulator
        # through every dispatch (decisions + admissions per group,
        # folded across substeps inside the device loop); the host pulls
        # it only at the stats cadence (pull_group_heat), never per tick
        self._dispatch_step = make_step(
            cfg, None, self.steps_per_dispatch, donate=True,
            io="packed_host", heat=True,
        )
        self._tick_step = make_step(
            cfg, None, self.steps_per_dispatch, donate=False,
            io="packed_host", heat=True,
        )
        # retrace sentinel bookkeeping (obs/device.py): the sentinels are
        # SHARED across managers of the same shape, so per-node metrics
        # count deltas against the last totals this manager saw; the
        # sentinels are marked warm after this manager's first completed
        # dispatch — any compile after that is a retrace (hard invariant:
        # the hot dispatch never retraces after warmup)
        self._compile_seen = 0
        self._retrace_seen = 0
        # device-resident [G] group-activity accumulator + the host-side
        # cumulative view refreshed by pull_group_heat at stats cadence
        self._heat_dev = jnp.zeros((G,), jnp.int32)
        self._heat_host = np.zeros(G, np.int64)
        # vids staged into the device request ring by the LAST dispatch
        # (the device_queue_depth gauge)
        self._last_ring_depth = 0
        # test/emulation modes (PaxosManager.java:1731-1778): UNREPLICATED
        # answers at the entry replica without consensus (isolates app+wire
        # cost); LAZY_PROPAGATION additionally still drives consensus but
        # replies on local execution instead of commit
        self.emulate_unreplicated = Config.get_bool(PC.EMULATE_UNREPLICATED)
        self.lazy_propagation = Config.get_bool(PC.LAZY_PROPAGATION)
        # request ids currently executing via an emulation mode (guards
        # a retransmit racing the out-of-lock execution)
        self._emulating: set = set()

        # host-side tables
        self.names: Dict[str, int] = {}        # service name -> CURRENT epoch row
        self.row_name: Dict[int, str] = {}     # occupancy: row -> name (or name@vE)
        # rows created by a start-epoch whose COMPLETE hasn't been confirmed
        # yet: proposals are accepted and QUEUED but never admitted to
        # consensus (build_requests skips pending rows), so nothing can
        # commit on a row the reconfigurator's probe may still move — the
        # recreate in _create_locked is only safe because of this gate, and
        # the held queue follows the name to the new row
        self.pending_rows: set = set()
        # stopped prior epochs kept until the reconfigurator drops them
        # (epoch final state may still be fetched from their app snapshot)
        self.old_epochs: Dict[Tuple[str, int], int] = {}  # (name, epoch) -> row
        # fired on EVERY replica when an epoch-final stop request executes
        # (the reconfiguration layer captures the final state here);
        # signature: (name, row, epoch)
        self.on_stop_executed: Optional[Callable[[str, int, int], None]] = None
        # residency (pause/unpause, PaxosManager.java:2264-2392 analog):
        # paused groups' snapshots, keyed (name, epoch) — their rows are
        # freed for reuse; reactivation restores at a freshly probed row.
        # With a journal, the table itself pages to disk (DiskMap analog,
        # DiskMap.java:97): at 1M groups the paused snapshots must not all
        # be RAM-resident (durability is the journal's job regardless)
        if log_dir:
            import os as _os

            spill_dir = _os.path.join(log_dir, "paused_spill")
            cap = Config.get_int(PC.PAUSE_BATCH_SIZE) * 4
            if Config.get_bool(PC.PACKED_SPILL):
                from .utils.packedstore import PackedSpillStore

                self.paused = PackedSpillStore(
                    spill_dir, capacity=cap,
                    segment_bytes=Config.get_int(PC.SPILL_SEGMENT_BYTES),
                    compact_ratio=Config.get_float(PC.SPILL_COMPACT_RATIO),
                    subdirs=Config.get_int(PC.SPILL_SUBDIRS),
                )
            else:
                from .utils.diskmap import DiskMap

                self.paused = DiskMap(spill_dir, capacity=cap)
        else:
            self.paused = {}
        # name -> {epoch} mirror of self.paused's keys: restore() must
        # find a hibernated name's epochs without an O(paused) key scan
        # (the paused table holds the COLD tail — millions of names)
        self._paused_by_name: Dict[str, set] = {}
        # name -> wall time of last resume/create activity relevant to
        # eviction hysteresis (a just-woken name must not be re-paused
        # by the next sweep even if its traffic burst already ended)
        self._resumed_at: Dict[str, float] = {}
        self.row_activity = np.zeros(G, np.float64)  # wall time of last use
        # per-name arriving-request counts since the last demand report
        # (updateDemandStats analog; drained by the ActiveReplica layer)
        self.demand_counts: Dict[str, int] = {}
        self.demand_backlog = 0  # total unreported requests (flush trigger)
        self.arena: Dict[int, str] = {}        # vid -> request payload (json str)
        self.vid_meta: Dict[int, Tuple[int, int]] = {}  # vid -> (entry_replica, request_id)
        self.outstanding = Outstanding()
        # request_id -> (time, response).  Ids are unique in practice,
        # not by construction: node-minted ids ((nonce<<24)|counter, up
        # to ~2^61) OVERLAP the client range [2^53, 2^62) — collisions
        # are tolerated probabilistically, exactly like the reference's
        # random 63-bit ids (RequestPacket.java:83).
        # Consulted at propose (fast dedup) AND at execution (a client
        # retransmitting to a different entry replica creates a second
        # proposal for the same logical request; every replica sees the
        # same decided sequence, so skipping re-execution of a seen id is
        # deterministic across the group — at-least-once commit,
        # exactly-once execution; ref: PaxosManager.java:318-346).
        # request_id -> (time, response, name-of-execution).  The name
        # tag makes state-transfer dedup SOUND: a donor ships only
        # entries executed in the groups whose app state it serves — an
        # entry for any other group would suppress an execution the
        # receiver's state does not contain, while OMITTING an entry the
        # adopted state does contain lets a re-proposed duplicate
        # re-execute; both directions diverge the RSM (each was caught
        # by the chaos soak).  Names, not rows: the tag must survive
        # migrations that re-home a name to a new row.
        self.response_cache: Dict[int, Tuple[float, Optional[str], str]] = {}
        # in-flight dedup (the reference's outstanding-table propose dedup,
        # PaxosManager.java:1209): a retransmitted request id whose original
        # proposal is still queued locally must not mint a second vid —
        # duplicate decisions of one logical request are legal but wasteful,
        # and post-jump replicas can't dedup them (no cache entry yet)
        self.inflight: Dict[int, int] = {}  # request_id -> queued vid
        self._next_counter = 1
        # node-minted request-id namespace: (boot nonce << 24) | counter,
        # < 2^61 (disjoint from reserved-bit-62 stop ids; client ids are
        # random 53+ bit — collision odds negligible either way)
        import random as _random

        self._rid_nonce = _random.randrange(1 << 20, 1 << 37)
        self.queues: Dict[int, List[int]] = {}  # group row -> pending vids
        # vid -> (name, epoch) it was proposed under (admission guard)
        self.vid_scope: Dict[int, Tuple[str, int]] = {}
        self.forward_out: List[Tuple[int, str, Dict]] = []  # (dst, kind, body)
        self._fired_callbacks: List[Tuple[Callable, int, Optional[str]]] = []
        self.app_exec_slot = np.zeros(G, np.int64)  # host app cursor per group
        # rows whose app cursor moved since the last gossip: the cursor
        # delta ships SPARSE (a full [G] list per tick is O(G) host work
        # and wire bytes for idle groups)
        self._app_exec_dirty: set = set()
        self.pending_exec: Dict[int, Dict[int, int]] = {}  # g -> slot -> vid
        # executed payloads retained for straggler pulls until every live
        # member's frontier passes the slot (sync/catch-up analog; a peer
        # down past a checkpoint catches up via checkpoint transfer instead)
        self.retained: Dict[int, Tuple[int, int]] = {}  # vid -> (row, slot)
        self._min_exec = np.zeros(G, np.int64)
        self._zero_cursors = np.zeros(G, np.int64)
        self.peer_app_exec: Dict[int, np.ndarray] = {}  # rid -> [G] cursors
        self._tick_no = 0
        self.total_executed = 0
        self._slots_since_ckpt = 0
        self.last_engine_step_s = 0.0
        # last tick where the engine made observable progress (admissions,
        # accepts, commits, ballot movement) — the server's event-kicked
        # cadence falls back to the timer when in-flight work stalls (a
        # minority partition must not busy-spin the loop)
        self.last_progress_tick = 0
        self._last_state_req: Dict[int, int] = {}  # row -> tick of last pull
        # rows whose app cursor is parked on a missing payload, and since
        # which tick: a payload GONE everywhere (GC'd before this member
        # joined) can park a cursor at a gap SMALLER than the ring/jump
        # horizons — after enough blocked ticks the state pull fires
        # regardless of gap size
        self._payload_blocked: Dict[int, Tuple[int, int]] = {}
        # rows whose DEVICE frontier has sat strictly behind the majority
        # frontier without progress: if the decisions they need left every
        # peer's window (majority paused + resumed at a higher frontier),
        # no gap is small enough to heal through the rings — after enough
        # stalled ticks the row both fires a state pull and ACCEPTS a
        # small-gap jump (chaos find).  Vectorized (arm tick / armed
        # slot per row; -1 = disarmed): during a mass catch-up every
        # lagging row updates each tick, which a Python dict cannot afford
        self._stall_since = np.full(G, -1, np.int64)
        self._stall_slot = np.full(G, -1, np.int64)
        # rows that joined an epoch > 0 WITHOUT state (membership heal /
        # resume fallback): their logical app state is the previous
        # epoch's final state, which no frontier counter reflects — with
        # zero post-join traffic the frontiers MATCH and the ordinary
        # straggler pull never fires.  Flagged rows pull state and adopt
        # a donor's app state even at EQUAL frontiers.
        self._needs_state: set = set()

        # recovery plane: rows whose app state is still on disk (their
        # checkpoint shard is the idle form, like a paused group's
        # journal record) — gated out of admission, execution, local
        # reads, pause snapshots, checkpoint writes, and donor serving
        # until the hydrator restores them
        self.hydrating_rows: set = set()
        self.hydrator = None  # recovery.hydration.Hydrator while cold
        self._recovery_stats: Dict[str, Any] = {}

        # serializes self.state replacement between the tick loop and
        # lifecycle ops arriving on transport threads (create/kill/recover)
        self._state_lock = threading.RLock()
        # double-buffered dispatch (serving pipeline): True from
        # step_dispatch until step_complete's post-step lands.  The HOT
        # transport entry points (propose / payload gossip) interleave
        # freely with the in-flight device step — only ops that REPLACE
        # engine state or read step-ordering-sensitive tables wait on the
        # condition (they would otherwise race the post-step bookkeeping
        # for rows the step just committed)
        self._step_cv = threading.Condition(self._state_lock)
        self._step_inflight = False
        self._step_thread: Optional[int] = None  # owner of the in-flight step
        # host mirror of engine leaves, keyed by state identity: hot
        # accessors (coordinator_of_row / current_epoch / is_stopped, the
        # propose path) must not force a whole-array device->host transfer
        # per CALL — that is O(calls * G) traffic (VERDICT r2 weak #3)
        self._np_cache: Dict[str, np.ndarray] = {}
        self._np_cache_state: Optional[EngineState] = None
        self.state: EngineState = init_state(cfg)
        self._recover()

    def _np(self, leaf: str) -> np.ndarray:
        """Cached host copy of an engine leaf for the CURRENT state object
        (one transfer per leaf per state version, not per accessor call).
        Takes the state lock: an unlocked reader racing the tick thread's
        state replacement could otherwise store an OLD state's array under
        the NEW state's cache and poison every later reader.

        The returned array is a PRIVATE copy when np.asarray would be a
        zero-copy view of the device buffer (`.base` set — the CPU
        backend): the dispatch step donates the state, so a view held by
        a transport thread past its lock region would read buffers a
        later tick overwrites in place.  Device backends already transfer
        into a fresh host buffer (`.base` None)."""
        with self._state_lock:
            if self._np_cache_state is not self.state:
                self._np_cache = {}
                self._np_cache_state = self.state
            arr = self._np_cache.get(leaf)
            if arr is None:
                arr = np.asarray(getattr(self.state, leaf))
                if arr.base is not None:
                    arr = arr.copy()
                self._np_cache[leaf] = arr
            return arr

    # ------------------------------------------------------------------
    # recovery (initiateRecovery analog, PaxosManager.java:1832-2035)
    # ------------------------------------------------------------------
    def _recover(self) -> None:
        if self.logger is None:
            return
        t_recover = time.monotonic()
        seed = {k: np.asarray(v).copy() for k, v in self.state._asdict().items()}
        rec = self.logger.recover(
            self.cfg.window, seed_arrays=seed, my_id=self.my_id,
            defer_app_states=Config.get_bool(PC.RECOVERY_LAZY_HYDRATION),
        )
        if rec.arrays is None:
            return
        # checkpoints written before the tag lane existed lack the key —
        # seed zeros here; the authoritative recompute below overwrites
        rec.arrays.setdefault("tag", seed["tag"])
        self.state = EngineState(
            **{k: jnp.asarray(v) for k, v in rec.arrays.items()}
        )
        meta = rec.meta
        for k, v in (meta.get("arena") or {}).items():
            self.arena.setdefault(int(k), v)
        for k, v in (meta.get("vid_meta") or {}).items():
            self.vid_meta.setdefault(int(k), (v[0], v[1]))
        self.arena.update(rec.payloads)  # journal blocks are newer
        for k, v in rec.payload_meta.items():
            self.vid_meta.setdefault(int(k), (int(v[0]), int(v[1])))
        for rid_s, ent in (meta.get("response_cache") or {}).items():
            # exactly-once dedup survives restarts (the restored app
            # state's history includes these executions)
            self.response_cache.setdefault(
                int(rid_s), (float(ent[0]), ent[1], str(ent[2]))
            )
        self.names = {str(k): int(v) for k, v in meta.get("names", {}).items()}
        self.old_epochs = {
            (str(n), int(e)): int(r)
            for n, e, r in meta.get("old_epochs", [])
        }
        versions = self._np("version")
        masks = self._np("member_mask")
        journal_inits: Dict[str, Optional[str]] = {}
        for nm, ents in rec.names.items():  # creates after the checkpoint
            # entries replay in journal order; a later entry for the same
            # name is an epoch upgrade — the prior epoch's row is demoted
            # to old_epochs exactly as the live create path does
            for ent in ents:
                prev_row = self.names.get(nm)
                if prev_row is not None and prev_row != int(ent["row"]):
                    self.old_epochs[(nm, int(versions[prev_row]))] = prev_row
                self.names[nm] = int(ent["row"])
            journal_inits[nm] = ents[-1].get("init")
        # Interleaved KILL blocks (epoch drops / deletes) zeroed the killed
        # rows' member_mask in the arrays but the replay above can't see
        # them — filter mappings whose row was killed, and old-epoch claims
        # on rows that another (newer) name now occupies.
        self.names = {
            n: r for n, r in self.names.items() if int(masks[r]) != 0
        }
        live_rows = set(self.names.values())
        self.old_epochs = {
            (n, e): r for (n, e), r in self.old_epochs.items()
            if int(masks[r]) != 0 and r not in live_rows
        }
        self.row_name = {v: k for k, v in self.names.items()}
        for (nm, e), r in self.old_epochs.items():
            self.row_name[r] = nm
        self.pending_rows = {
            int(r) for r in rec.pending_rows if r in live_rows
        }
        # blank-join rows still awaiting a donor's state survive restarts:
        # seed from the checkpoint meta, plus infer journal-replayed
        # creates at epoch > 0 with no initial state (a legit None final
        # state just costs one redundant pull that adopts the same None)
        self._needs_state = {
            int(r) for r in (meta.get("needs_state") or [])
            if int(r) in live_rows
        }
        for nm, init in journal_inits.items():
            r = self.names.get(nm)
            if r is not None and init is None and int(versions[r]) > 0:
                self._needs_state.add(r)
        self._next_counter = int(meta.get("next_counter", 1))
        for vid in rec.payloads:
            base = vid & ~(STOP_BIT | BATCH_BIT)
            if (base >> VID_NODE_SHIFT) == self.my_id:
                self._next_counter = max(
                    self._next_counter, (base & VID_COUNTER_MASK) + 1
                )
        ae = meta.get("app_exec_slot")
        if ae is not None:
            self.app_exec_slot = np.asarray(ae, np.int64)
        else:
            self.app_exec_slot = (
                self._np("exec_slot").astype(np.int64).copy()
            )
        for g_str, pend in (meta.get("pending_exec") or {}).items():
            self.pending_exec[int(g_str)] = {
                int(s_): int(v) for s_, v in pend.items()
            }
        # stopped prior epochs never execute further on the host: the new
        # epoch's restore subsumed their trailing slots, and re-executing
        # them here would double-apply onto the restored app state
        exec_np = self._np("exec_slot")
        for (_nm, _e), r in self.old_epochs.items():
            self.app_exec_slot[r] = int(exec_np[r])
            self.pending_exec.pop(r, None)
        app_states = meta.get("app_states") or {}
        # lazy mode: the checkpoint's app states stayed on disk
        # (rec.view); its NAME DOMAIN still decides precedence exactly as
        # the eager restore would (checkpoint state wins over a replayed
        # create's init), the restore itself just happens at hydration
        ck_domain = (
            set(rec.view.meta.get("names") or ())
            if rec.view is not None else set()
        )
        for name, state_str in app_states.items():
            if name in self.names:
                self.app.restore(name, state_str)
        for name, init in journal_inits.items():
            if name not in app_states and name not in ck_domain:
                self.app.restore(name, init)
        # residency: fold pause records (LAST — checkpoint app-state and
        # cursor restoration above must not overwrite the fold).  A name
        # live at the same epoch was RESUMED: the pause record's frontier /
        # ballot / app state must survive (the resume-create replays empty,
        # and a forgotten promise could accept an older-ballot proposal).
        # A name not live stays paused and reactivates from self.paused.
        arrays = None
        fold_restored: set = set()  # names whose app state the fold set
        for (nm, e), prec in rec.pause_records.items():
            r = self.names.get(nm)
            if r is not None and int(versions[r]) == e:
                if arrays is None:
                    arrays = {
                        k: np.asarray(v).copy()
                        for k, v in self.state._asdict().items()
                    }
                # the safety bits (promised ballot, accepted/decided window
                # remnants) fold even at EQUAL frontiers — a record with
                # exec == replayed frontier can still carry a promise the
                # replayed create forgot (bal bumped without execution)
                if int(prec["exec"]) >= int(arrays["exec_slot"][r]):
                    arrays["bal"][r] = max(int(arrays["bal"][r]), int(prec["bal"]))
                    for slot, b, vid in prec.get("acc") or []:
                        lane = slot % self.cfg.window
                        if slot > int(arrays["acc_slot"][r, lane]):
                            arrays["acc_slot"][r, lane] = slot
                            arrays["acc_bal"][r, lane] = b
                            arrays["acc_vid"][r, lane] = vid
                    for slot, vid in prec.get("dec") or []:
                        lane = slot % self.cfg.window
                        if slot > int(arrays["dec_slot"][r, lane]):
                            arrays["dec_slot"][r, lane] = slot
                            arrays["dec_vid"][r, lane] = vid
                if int(prec["exec"]) > int(arrays["exec_slot"][r]):
                    arrays["exec_slot"][r] = int(prec["exec"])
                    arrays["app_hash"][r] = int(prec["app_hash"])
                    arrays["n_execd"][r] = int(prec["n_execd"])
                    self.app.restore(nm, prec.get("app_state"))
                    fold_restored.add(nm)
                    # the snapshotted app state corresponds to the
                    # record's APP cursor, which a forced pause can leave
                    # behind the device frontier; pairing the state with
                    # "exec" would skip the gap's executions silently.
                    # The stranded gap is unexecutable locally (see the
                    # resume_group comment) — park for a donor pull
                    self.app_exec_slot[r] = int(
                        prec.get("app_exec", prec["exec"])
                    )
                    if int(self.app_exec_slot[r]) < int(prec["exec"]):
                        self._needs_state.add(r)
                    self.pending_exec.pop(r, None)
                    for rid_s, ent in (prec.get("dedup") or {}).items():
                        self.response_cache.setdefault(
                            int(rid_s), (float(ent[0]), ent[1], str(ent[2]))
                        )
            elif nm not in self.names:
                self._paused_put((nm, e), prec)
        # Roll the execute frontier forward through EVERY journaled
        # decision (the rings only hold the last W per group — a group
        # that decided more than W slots since its checkpoint would
        # otherwise wedge at the snapshot frontier forever).  The device
        # hash chain advances with the same fold the engine uses; host
        # execution happens via pending_exec on the first ticks.
        if rec.decisions:
            if arrays is None:
                arrays = {
                    k: np.asarray(v).copy()
                    for k, v in self.state._asdict().items()
                }
            old_rows = set(self.old_epochs.values())
            for g, decs in rec.decisions.items():
                if int(masks[g]) == 0 or g in old_rows:
                    continue  # killed / stopped-prior-epoch rows stay put
                s = int(arrays["exec_slot"][g])
                h = int(arrays["app_hash"][g])
                ne = int(arrays["n_execd"][g])
                base = s
                while s in decs:
                    vid = decs[s]
                    if vid > 0:
                        h = _mix32(h, vid)
                        ne += 1
                    s += 1
                if s > base:
                    arrays["exec_slot"][g] = s
                    arrays["app_hash"][g] = h
                    arrays["n_execd"][g] = ne
                    arrays["c_next_slot"][g] = max(
                        int(arrays["c_next_slot"][g]), s
                    )
                pend = self.pending_exec.setdefault(g, {})
                cursor = int(self.app_exec_slot[g])
                for slot, vid in decs.items():
                    if slot >= cursor:
                        pend.setdefault(slot, vid)
                if not pend:
                    del self.pending_exec[g]
        if arrays is not None:
            self.state = EngineState(
                **{k: jnp.asarray(v) for k, v in arrays.items()}
            )
        # instance tags are derivable state — recompute from the restored
        # name map rather than trusting the checkpoint (also upgrades
        # checkpoints written before the tag lane existed, which restore
        # as zeros and would freeze every group's consensus)
        tags = np.asarray(self.state.tag).copy()
        versions = self._np("version")
        for nm, r in self.names.items():
            tags[r] = _instance_tag(nm, int(versions[r]))
        for (nm, e), r in self.old_epochs.items():
            tags[r] = _instance_tag(nm, int(e))
        self.state = self.state._replace(tag=jnp.asarray(tags))
        # ---- lazy hydration plan (recovery plane) ---------------------
        # Checkpoint-domain names not already restored above go COLD:
        # their rows gate out of admission/execution/reads until the
        # hydrator restores them.  The recency-ordered hot set (manifest
        # hints) hydrates NOW — that is the bounded restart-to-serving
        # window — and the rest restores in the background.
        hot_hydrated = 0
        if rec.view is not None:
            from .recovery.hydration import Hydrator

            hyd = Hydrator(
                self, rec.view,
                batch=Config.get_int(PC.RECOVERY_HYDRATION_BATCH),
            )
            # the view's engine arrays were already folded into
            # self.state above; keeping them pinned through the whole
            # hydration window would carry a duplicate [G,...] host
            # copy (hundreds of MB at 256k groups) for nothing
            rec.view.arrays = {}
            ck_rows = rec.view.meta.get("names") or {}
            for nm, row in self.names.items():
                if nm in fold_restored or nm not in ck_domain:
                    continue
                self.hydrating_rows.add(row)
                hyd.add_cold(
                    nm, rec.view.shard_of_row(int(ck_rows.get(nm, row)))
                )
            hot_budget = Config.get_int(PC.RECOVERY_HOT_NAMES)
            for row in rec.view.meta.get("hot_rows") or ():
                if hot_budget <= 0 or not hyd.backlog:
                    break
                nm = self.row_name.get(int(row))
                if nm is not None and int(row) in self.hydrating_rows:
                    hyd.hydrate_name_locked(nm)
                    hot_budget -= 1
                    hot_hydrated += 1
            # hint-less checkpoints (pre-manifest or first generation)
            # still serve a bounded hot set, in shard order
            while hot_budget > 0 and hyd.backlog:
                nm = hyd._pop()
                if nm is None:
                    break
                hyd.hydrate_name_locked(nm)
                hot_budget -= 1
                hot_hydrated += 1
            if hyd.backlog:
                self.hydrator = hyd
        # synchronous rollforward through the app (initiateRecovery parity
        # for everything hydrated; cold rows park their decided slots in
        # pending_exec until hydration); slots whose payloads are not
        # local stay pending and heal via runtime peer pulls
        self._drain_pending_exec()
        self._fired_callbacks.clear()  # no clients to answer at recovery
        # first tick gossips a cursor baseline for everything live here
        self._app_exec_dirty.update(self.names.values())
        self._app_exec_dirty.update(self.old_epochs.values())
        # recovery accounting: the obs counters + `stats` phase surface
        st = dict(getattr(rec, "stats", None) or {})
        st["time_to_first_serve_s"] = time.monotonic() - t_recover
        st["hot_hydrated"] = hot_hydrated
        st["cold_backlog_at_serve"] = (
            self.hydrator.backlog if self.hydrator else 0
        )
        self._recovery_stats = st
        mx = self.metrics
        mx.count("recovery_segments_replayed", st.get("segments", 0))
        mx.count("recovery_blocks_replayed", st.get("blocks", 0))
        mx.gauge("recovery_replay_s", st.get("replay_s", 0.0))
        mx.gauge("recovery_time_to_first_serve_s",
                 st["time_to_first_serve_s"])
        mx.gauge("recovery_hydration_backlog", st["cold_backlog_at_serve"])
        if self.hydrator is not None:
            self.hydrator.start_background()

    # ------------------------------------------------------------------
    # recovery-plane surface (phase + stats + read gate)
    # ------------------------------------------------------------------
    @property
    def recovery_phase(self) -> str:
        """``recovering`` while any name's app state is still on disk;
        ``serving`` once hydration drained.  The launcher's readiness
        wait and the ``stats`` admin op read this."""
        return "recovering" if self.hydrating_rows else "serving"

    def recovery_stats(self) -> Dict[str, Any]:
        out = dict(self._recovery_stats)
        out["phase"] = self.recovery_phase
        out["hydration_backlog"] = (
            self.hydrator.backlog if self.hydrator else 0
        )
        out["hydrated"] = (
            self.hydrator.n_hydrated if self.hydrator
            else self._recovery_stats.get("hot_hydrated", 0)
        )
        return out

    def mesh_info(self) -> Dict[str, Any]:
        """{n_devices, shape, platform} of the devices backing the engine
        state — surfaced on the ``stats`` admin op so an accidentally
        unsharded deployment (a G meant for a mesh sitting on one device)
        is visible at runtime, not discovered in an OOM."""
        from .parallel.mesh import describe_state_mesh

        return describe_state_mesh(self.state.bal)

    # ------------------------------------------------------------------
    # device-plane observatory (obs/device.py)
    # ------------------------------------------------------------------
    def pull_group_heat(self) -> np.ndarray:
        """Drain the device-resident ``[G]`` activity accumulator.

        THE one sanctioned device pull outside the `_np` leaf cache —
        stats-cadence only (the server's stats line / the `stats` admin
        op), never from a hot-path function: it synchronizes with an
        in-flight dispatch.  Returns the per-group delta since the last
        pull, folds it into the cumulative host view and the
        ``group_heat*`` metrics, and resets the device accumulator."""
        from .obs.device import HEAT_BOUNDS, heat_summary

        with self._state_lock:
            arr = np.asarray(self._heat_dev)  # syncs; GIL released
            if arr.base is not None:
                # the next dispatch donates this buffer — copy first
                arr = arr.copy()
            self._heat_dev = jnp.zeros(
                (self.cfg.n_groups,), jnp.int32
            )
            delta = arr.astype(np.int64)
            self._heat_host += delta
            cum = self._heat_host
        mx = self.metrics
        total = int(delta.sum())
        if total:
            mx.count("group_heat_total", total)
            mx.observe_bulk(
                "group_heat", delta[delta > 0], bounds=HEAT_BOUNDS
            )
        summ = heat_summary(cum)
        mx.gauge("group_heat_active_groups", summ["active_groups"])
        mx.gauge(
            "group_heat_top1pct_share",
            summ["hot_set"]["traffic_share"],
        )
        return delta

    def group_heat_stats(self, topk: Optional[int] = None) -> Dict:
        """The ``engine.heat`` stats block: top-K rows by cumulative
        activity (named where this node hosts the row) and the hot-set
        estimate the density campaign reads.  Pure host arithmetic over
        the last pulled view — call :meth:`pull_group_heat` first for a
        fresh one."""
        from .obs.device import heat_summary

        if topk is None:
            topk = Config.get_int(PC.GROUP_HEAT_TOPK)
        with self._state_lock:
            cum = self._heat_host.copy()
        return heat_summary(cum, topk=topk, name_of=self.row_name.get)

    def engine_compile_stats(self) -> Dict:
        """The ``engine.compile`` stats block: compile/retrace counts of
        this manager's two step instances (shared across same-shape
        managers in-process) plus their last recorded events."""
        return {
            "dispatch": self._dispatch_step.stats(),
            "tick": self._tick_step.stats(),
        }

    def local_read_ok(self, name: str) -> bool:
        """Gate for the uncoordinated local-read fast path: False while
        the name's app state is un-hydrated (and promotes it to the
        front of the hydration queue — a request touched it), and False
        while a transaction holds the name locked/staged (txn/app.py) —
        the read then serializes through consensus, where it is refused
        retryably until the transaction's decision lands."""
        blocked = getattr(self.app, "txn_local_read_blocked", None)
        if blocked is not None and blocked(name):
            return False
        row = self.names.get(name)
        if row is None or row not in self.hydrating_rows:
            return True
        if self.hydrator is not None:
            self.hydrator.request(name)
        return False

    def hydrate_all(self, deadline_s: Optional[float] = None) -> bool:
        """Drain the hydration backlog synchronously (close paths,
        tests); True when nothing is left cold."""
        if self.hydrator is None:
            return not self.hydrating_rows
        return self.hydrator.drain(deadline_s)

    # ------------------------------------------------------------------
    # lifecycle (createPaxosInstance / kill, PaxosManager.java:611,2142)
    # ------------------------------------------------------------------
    def default_row_for(self, name: str) -> int:
        """Deterministic row proposal: stable hash + linear probe over THIS
        node's occupancy.  Only valid on the node initiating the create —
        the chosen row must then be propagated in the create request so
        every member maps the name to the SAME row (rows are the
        cross-replica alignment key of the batched arrays; the reference
        needs no such step because it keys everything by paxosID string)."""
        import zlib

        if name in self.names:
            return self.names[name]  # idempotent re-create (e.g. recovery)
        G = self.cfg.n_groups
        row = zlib.crc32(name.encode("utf-8")) % G
        for _ in range(G):
            if row not in self.row_name:
                return row
            row = (row + 1) % G
        raise RuntimeError("group capacity exhausted")

    def create_paxos_instance(
        self,
        name: str,
        members: List[int],
        initial_state: Optional[str] = None,
        version: int = 0,
        row: Optional[int] = None,
        pending: bool = False,
        dedup: Optional[Dict] = None,
    ) -> bool:
        """``dedup`` carries the exactly-once entries snapshotted WITH
        ``initial_state`` (an epoch-final-state handoff).  They install
        IF AND ONLY IF this call adopts the state — every install pairs
        with its restore.  An unpaired install (entries present, state
        not adopted) skip-executes decisions the app state does not
        contain and diverges the RSM (chaos seed 662625602)."""
        with self._state_lock:
            self._await_step_locked()
            return self._create_locked(
                name, members, initial_state, version, row, pending,
                dedup=dedup,
            )

    def _create_locked(
        self, name, members, initial_state, version, row, pending=False,
        dedup=None,
    ) -> bool:
        if len(members) > self.max_group_size:
            # MAX_GROUP_SIZE ceiling (PaxosConfig.java:532): an oversized
            # group would also overflow the 32-bit member mask
            return False
        # requests held behind the pending gate on a row the probe moved:
        # they follow the name to its new row (vids/payloads stay live)
        held_vids: List[int] = []
        if name in self.names:
            cur_row = self.names[name]
            cur_ver = int(self._np("version")[cur_row])
            if version < cur_ver:
                return False
            if version == cur_ver:
                if row is None or int(row) == cur_row:
                    # idempotent re-create (start-epoch retransmit); a
                    # committed retransmit (late-start) confirms the row
                    if not pending and cur_row in self.pending_rows:
                        self._unpend_locked(cur_row)
                    return True
                # Same-epoch row change: the reconfigurator's row probe
                # moved to a fresh row after a collision NACK from some
                # member.  Only safe while the row is still PENDING (the
                # admission gate guarantees nothing committed here); a
                # confirmed (unpended) or executed row must refuse as a
                # collision so the RC's probe converges back to this row.
                if cur_row not in self.pending_rows or \
                        int(self._np("n_execd")[cur_row]):
                    raise RuntimeError(
                        f"row move for {name!r} v{version} refused: row "
                        f"{cur_row} is confirmed or already executed"
                    )
                held_vids = list(self.queues.get(cur_row, []))
                self._kill_locked(name, release_queue=False)
            else:
                # Epoch upgrade (reconfiguration): the stopped prior epoch's
                # row stays resident under (name, old_epoch) until the
                # reconfigurator drops it; the name re-maps to the new row
                # (PaxosManager's paxosID+version instance keying analog).
                if not int(self._np("stopped")[cur_row]):
                    return False  # old epoch must stop before the next starts
                self.old_epochs[(name, cur_ver)] = cur_row
                # row_name keeps the REAL name (occupancy only needs the key);
                # trailing executions of the old row must see the true
                # paxos_id, not a mangled alias
                del self.names[name]
                # The new epoch's initial state (the stop-time final state)
                # subsumes any of the old row's decided-but-unexecuted slots;
                # executing them after the restore would double-apply them.
                self.pending_exec.pop(cur_row, None)
                self._payload_blocked.pop(cur_row, None)
                self._stall_since[cur_row] = -1
                self._stall_slot[cur_row] = -1
                self._needs_state.discard(cur_row)
                # epoch upgrade supersedes a cold row's checkpoint state
                # (the new epoch restores from the stop-time final state)
                self.hydrating_rows.discard(cur_row)
                self.app_exec_slot[cur_row] = int(
                    self._np("exec_slot")[cur_row]
                )
        row = self.default_row_for(name) if row is None else int(row)
        if row in self.row_name:
            # collision-NACK path: the name (if it was re-homed above) is
            # already killed and cannot be re-queued here — release its
            # held vids so client retransmits re-propose after the RC's
            # next probe lands, instead of deduping against dead vids
            for vid in held_vids:
                self._release_vid(vid)
            raise RuntimeError(
                f"row {row} already hosts {self.row_name[row]!r} (create for "
                f"{name!r} must carry the creator's row)"
            )
        self.names[name] = row
        self.row_name[row] = name
        if pending:
            self.pending_rows.add(row)
        mask = 0
        for m in members:
            mask |= 1 << m
        coord0 = members[row % len(members)]
        self.state = create_groups(
            self.state, np.array([row]), np.array([mask]),
            np.array([coord0]), my_id=self.my_id, version=version,
            tag=_instance_tag(name, version),
        )
        # the implicit initial ballot (0, coord0) is known host-side:
        # seed the decide-attribution view without touching the device
        self._bal_host[row] = encode_ballot(0, coord0)
        self.app_exec_slot[row] = 0
        self._release_row_queue(row)  # stale leftovers of a prior tenant
        self.pending_exec.pop(row, None)
        # gossiped peer cursors for this row described its PREVIOUS
        # tenant (the merge is max-only); keeping them would both pin the
        # payload-retention watermark wrongly and false-arm the
        # frontier-stall detector against a frontier that never existed
        for arr in self.peer_app_exec.values():
            arr[row] = 0
        self._stall_since[row] = -1
        self._stall_slot[row] = -1
        self.row_activity[row] = time.time()
        if held_vids:
            self.queues[row] = held_vids
        if self.logger:
            self.logger.log_create(
                np.array([row]), np.array([mask]),
                np.array([version]), np.array([coord0]),
                names=[name], inits=[initial_state], pendings=[pending],
            )
        if self.my_id in members:
            self.app.restore(name, initial_state)
            # install paired with the restore just above — and ONLY here:
            # the idempotent/early returns above adopt no state, so
            # installing there would be the unpaired-install breach.  A
            # None state pairs too: its dedup snapshot describes the
            # history that ENDED in None, exactly what members who lived
            # through the epoch hold
            if dedup:
                self.install_dedup(dedup)
        return True

    def create_paxos_batch(
        self,
        names: List[str],
        members: List[int],
        initial_states: Optional[Dict[str, Optional[str]]] = None,
    ) -> int:
        """Bulk epoch-0 creation: ONE vectorized engine update and ONE
        journal block pair for N fresh names (the bootstrap/bench path —
        per-name creates cost a device dispatch each, which at 256k
        groups is minutes of pure dispatch overhead).  Names already
        present are skipped; returns how many were created."""
        if len(members) > self.max_group_size:
            return 0
        initial_states = initial_states or {}
        mask = 0
        for mem in members:
            mask |= 1 << mem
        with self._state_lock:
            self._await_step_locked()
            rows, coords, tags, fresh = [], [], [], []
            try:
                for name in names:
                    if name in self.names:
                        continue
                    row = self.default_row_for(name)
                    self.names[name] = row
                    self.row_name[row] = name
                    rows.append(row)
                    coords.append(members[row % len(members)])
                    tags.append(_instance_tag(name, 0))
                    fresh.append(name)
            except RuntimeError:
                # capacity exhausted mid-batch: the names mapped so far
                # have NO engine rows / journal entries yet — unwinding
                # them keeps the table consistent (nothing durable or
                # on-device happened), then the caller sees the error
                for name, row in zip(fresh, rows):
                    del self.names[name]
                    del self.row_name[row]
                raise
            if not fresh:
                return 0
            rows_np = np.array(rows, np.int32)
            self.state = create_groups(
                self.state, rows_np, np.full(len(rows), mask, np.int32),
                np.array(coords, np.int32), my_id=self.my_id, version=0,
                tag=np.array(tags, np.int32),
            )
            self.app_exec_slot[rows_np] = 0
            self._stall_since[rows_np] = -1
            self._stall_slot[rows_np] = -1
            self.row_activity[rows_np] = time.time()
            for arr in self.peer_app_exec.values():
                arr[rows_np] = 0
            for row in rows:
                self._release_row_queue(row)
                self.pending_exec.pop(row, None)
            if self.logger:
                self.logger.log_create(
                    rows_np, np.full(len(rows), mask, np.int32),
                    np.zeros(len(rows), np.int32),
                    np.array(coords, np.int32),
                    names=fresh,
                    inits=[initial_states.get(n) for n in fresh],
                )
            if self.my_id in members:
                for name in fresh:
                    self.app.restore(name, initial_states.get(name))
            return len(fresh)

    def commit_row(self, name: str, epoch: int, row: Optional[int] = None) -> None:
        """The reconfigurator's COMPLETE confirmed (name, epoch) at `row`:
        clear the admission gate (durably).  The row check matters: a
        laggard still holding a LOSING row for this epoch must not un-pend
        it — that row may alias another group on its peers; the committed
        late-start recreates it at the winning row instead."""
        with self._state_lock:
            cur = self.names.get(name)
            if cur is None or cur not in self.pending_rows:
                return
            if int(self._np("version")[cur]) != int(epoch):
                return
            if row is not None and int(row) >= 0 and int(row) != cur:
                return
            self._unpend_locked(cur)

    def _unpend_locked(self, row: int) -> None:
        self.pending_rows.discard(row)
        if self.logger:
            self.logger.log_unpend(np.array([row]))

    def _release_vid(self, vid: int) -> None:
        """Release one dead proposal's scheduling state so a retransmitted
        request id RE-PROPOSES instead of being deduped against it forever
        (the propose gate treats any vid still in vid_meta as live).
        Decided vids stay owned by retention GC."""
        if vid in self.retained:
            return
        payload = self.arena.pop(vid, None)
        if (vid & BATCH_BIT) and payload is not None:
            # release every member request's in-flight gate so their
            # retransmits re-propose instead of waiting on a dead batch
            try:
                for rid, _entry, _value in decode_batch(payload):
                    if self.inflight.get(rid) == vid:
                        del self.inflight[rid]
            except (ValueError, TypeError):
                pass  # undecodable batch: the %64 inflight sweep heals
        self.vid_scope.pop(vid, None)
        _entry, rid = self.vid_meta.pop(vid, (None, None))
        if rid is not None and self.inflight.get(rid) == vid:
            del self.inflight[rid]

    def _release_row_queue(self, row: int) -> None:
        """Drop a row's queue, releasing every queued vid."""
        for vid in self.queues.pop(row, None) or []:
            self._release_vid(vid)

    def kill(self, name: str) -> bool:
        with self._state_lock:
            self._await_step_locked()
            return self._kill_locked(name)

    def _kill_locked(self, name: str, release_queue: bool = True) -> bool:
        # release_queue=False is for pause / re-home callers, which have
        # snapshotted the queue for later re-queueing and need the vids'
        # scheduling state (meta, inflight dedup, callbacks) to survive
        row = self.names.pop(name, None)
        if row is None:
            return False
        del self.row_name[row]
        self.pending_rows.discard(row)
        self.hydrating_rows.discard(row)  # killed cold name: state is moot
        self._payload_blocked.pop(row, None)
        self._stall_since[row] = -1
        self._stall_slot[row] = -1
        self._needs_state.discard(row)
        self.state = kill_groups(self.state, np.array([row]))
        if self.logger:
            self.logger.log_kill(np.array([row]))
        if release_queue:
            self._release_row_queue(row)
        else:
            self.queues.pop(row, None)
        self.pending_exec.pop(row, None)
        return True

    def kill_epoch(self, name: str, epoch: int) -> bool:
        """Free a stopped prior epoch's row (DropEpochFinalState analog:
        the reconfigurator garbage-collects the old epoch once the new one
        is running)."""
        with self._state_lock:
            self._await_step_locked()
            # a paused group being deleted has no row — drop the record
            # with a journal tombstone (else the PAUSE block resurrects it
            # on recovery, and a later re-created incarnation of the name
            # could restore the dead incarnation's state)
            prec = self._paused_pop((name, int(epoch)))
            if prec is not None:
                # its shadow queue dies with it: release so retransmits of
                # those request ids re-propose into the next incarnation
                for vid in prec.get("held_vids") or []:
                    self._release_vid(vid)
                if self.logger:
                    self.logger.log_pause({
                        "name": name, "epoch": int(epoch), "dropped": True,
                    })
            row = self.old_epochs.pop((name, epoch), None)
            if row is None:
                # dropping the current epoch is only legal if it's stopped
                # and matches (delete-service path)
                cur = self.names.get(name)
                if cur is None:
                    return False
                if int(self._np("version")[cur]) != epoch:
                    return False
                if not int(self._np("stopped")[cur]):
                    return False  # never kill a live, unstopped group
                return self._kill_locked(name)
            del self.row_name[row]
            self.pending_rows.discard(row)
            self._payload_blocked.pop(row, None)
            self._stall_since[row] = -1
            self._stall_slot[row] = -1
            self._needs_state.discard(row)
            self.state = kill_groups(self.state, np.array([row]))
            if self.logger:
                self.logger.log_kill(np.array([row]))
            self._release_row_queue(row)
            self.pending_exec.pop(row, None)
            return True

    # ------------------------------------------------------------------
    # residency: pause / resume (syncAndDeactivate + unpause analog,
    # PaxosManager.java:2264-2392,2786-2881 — RC-coordinated here because
    # rows must stay aligned across replicas for the blob exchange)
    # ------------------------------------------------------------------
    def _paused_put(self, key: Tuple[str, int], rec: Dict) -> None:
        """Insert a pause record, keeping the by-name epoch mirror in
        sync (every ``self.paused`` mutation goes through _paused_put /
        _paused_pop — restore() resolves a name's epochs through the
        mirror instead of scanning millions of cold keys)."""
        self.paused[key] = rec
        self._paused_by_name.setdefault(key[0], set()).add(int(key[1]))

    def _paused_pop(self, key: Tuple[str, int]) -> Optional[Dict]:
        rec = self.paused.pop(key, None)
        if rec is not None:
            eps = self._paused_by_name.get(key[0])
            if eps is not None:
                eps.discard(int(key[1]))
                if not eps:
                    del self._paused_by_name[key[0]]
        return rec

    def pause_group(self, name: str, epoch: int, force: bool = False) -> str:
        """Free (name, epoch)'s row, snapshotting its state to the journal
        and `self.paused`.  Returns "ok", "unknown" (not hosted here — an
        already-paused or never-started member just acks), or "busy"
        (non-quiescent and not forced: traffic resumed, pause should be
        cancelled).  `force` carries window remnants into the record (used
        by re-homing, where quiescence can't be awaited)."""
        with self._state_lock:
            self._await_step_locked()
            row = self.names.get(name)
            if row is None:
                return "ok" if (name, int(epoch)) in self.paused else "unknown"
            if int(self._np("version")[row]) != int(epoch):
                return "unknown"
            if int(self._np("stopped")[row]):
                return "busy"  # stopping group: the delete path owns it
            if row in self.hydrating_rows:
                # a pause record snapshots app state — un-hydrated, the
                # snapshot would capture the pre-restore blank.  Busy is
                # transient: background hydration clears it
                return "busy"
            exec_now = int(self._np("exec_slot")[row])
            quiescent = (
                not self.queues.get(row)
                and not self.pending_exec.get(row)
                and int(self.app_exec_slot[row]) == exec_now
                and int(self._np("acc_slot")[row].max()) < exec_now
            )
            if not quiescent and not force:
                return "busy"
            rec = self._extract_record(name, int(epoch), row)
            held = list(self.queues.get(row, []))
            if held:
                # unadmitted requests survive the pause in the record's
                # shadow queue (journaled WITH the record — a crash while
                # paused must not drop them); the resume re-queues them.
                # Their admission scopes ride along: vid_scope is in-memory
                # only, and a scope-less resumed vid would bypass the
                # stale-vid admission guard after a crash
                rec["held_vids"] = held
                rec["held_scopes"] = {
                    str(v): list(self.vid_scope[v])
                    for v in held if v in self.vid_scope
                }
            if self.logger:
                self.logger.log_pause(rec)
            self._paused_put((name, int(epoch)), rec)
            self._kill_locked(name, release_queue=False)
            if not force:
                # a non-forced pause is the sweeper's capacity eviction
                # (forced ones are re-homes/hibernates, not evictions)
                self.metrics.count("pause_evictions")
            return "ok"

    def _extract_record(
        self, name: str, epoch: int, row: int,
        dedup: Optional[Dict] = None,
    ) -> Dict:
        """Snapshot one row for pause/re-home (HotRestoreInfo analog).
        Reads go through the ``_np`` leaf cache — one host transfer per
        leaf per state version, not per paused name (the old per-call
        ``np.asarray(leaf)`` copied whole [G, W] planes per pause; a
        density sweep pays extraction thousands of times per state).
        ``dedup`` lets a batch caller supply this name's exactly-once
        entries from ONE grouped response-cache pass instead of the
        per-name O(cache) scan of :meth:`dedup_for_name`."""
        exec_now = int(self._np("exec_slot")[row])
        acc = []
        dec = []
        acc_slot = self._np("acc_slot")[row]
        acc_bal = self._np("acc_bal")[row]
        acc_vid = self._np("acc_vid")[row]
        dec_slot = self._np("dec_slot")[row]
        dec_vid = self._np("dec_vid")[row]
        for lane in range(self.cfg.window):
            if int(acc_slot[lane]) >= exec_now:
                acc.append([int(acc_slot[lane]), int(acc_bal[lane]),
                            int(acc_vid[lane])])
            if int(dec_slot[lane]) >= exec_now:
                dec.append([int(dec_slot[lane]), int(dec_vid[lane])])
        return {
            "name": name, "epoch": epoch,
            "exec": exec_now,
            "bal": int(self._np("bal")[row]),
            "app_hash": int(self._np("app_hash")[row]),
            "n_execd": int(self._np("n_execd")[row]),
            "app_state": self.app.checkpoint(name),
            "app_exec": int(self.app_exec_slot[row]),
            "acc": acc, "dec": dec,
            "dedup": self.dedup_for_name(name) if dedup is None else dedup,
            # member set rides along so a LOCAL restore (hibernate wake-up)
            # needs no reconfigurator round to learn the group
            "members": self.get_replica_group(name),
        }

    def resume_group(
        self, name: str, epoch: int, members: List[int], row: int,
        pending: bool = True, initial_state: Optional[str] = None,
    ) -> bool:
        """Reactivate (name, epoch) at `row` (the RC's freshly probed row).

        Three cases: still hosting live (re-home: carry full state over),
        holding a pause record (restore it), or neither (fresh empty join —
        the straggler state-transfer heals it).  Raises RuntimeError when
        `row` is occupied by another group (-> collision NACK)."""
        epoch = int(epoch)
        with self._state_lock:
            self._await_step_locked()
            cur = self.names.get(name)
            if cur is not None:
                cur_ver = int(self._np("version")[cur])
                if cur_ver > epoch:
                    return False
                if cur_ver == epoch:
                    hosted = self.get_replica_group(name)
                    if int(row) == cur and hosted == sorted(
                        int(m) for m in members
                    ):
                        if not pending and cur in self.pending_rows:
                            self._unpend_locked(cur)
                        return True
                    # live re-home (new row) OR membership heal (same row,
                    # STALE member set — the record's actives are
                    # authoritative post-COMPLETE; a member keeping a
                    # divergent mask would ignore the true members' blobs
                    # forever): snapshot with window remnants, free the
                    # row, fall through to restore with the new set
                    if self.pause_group(name, epoch, force=True) != "ok":
                        return False
            rec = self._paused_pop((name, epoch))
            if int(row) in self.row_name:
                if rec is not None:
                    self._paused_put((name, epoch), rec)  # keep for next probe
                raise RuntimeError(
                    f"row {row} already hosts {self.row_name[int(row)]!r}"
                )
            if rec is None:
                # no local state at all: join with the birth state (if
                # the caller knows it) and heal via state transfer.
                # A REJOIN wipes the app back to the birth state, so
                # this member's OWN response-cache entries for the name
                # describe executions the adopted state does NOT contain
                # — kept, they would suppress re-executing those
                # decisions into the blank state and freeze the RSM
                # (audit-heal find: a rejoined member at exec==cursor
                # with an empty app state, forever).  Epoch>0 joins
                # adopt a donor's state+dedup wholesale via _needs_state;
                # epoch-0 rejoins rebuild by re-executing history.
                for rid in [
                    r for r, (_t, _resp, nm) in self.response_cache.items()
                    if nm == name
                ]:
                    del self.response_cache[rid]
                ok = self._create_locked(
                    name, members, initial_state, epoch, int(row), pending
                )
                if ok and epoch > 0 and initial_state is None:
                    # an epoch > 0 group's true app state is the previous
                    # epoch's final state — this join is BLANK and must
                    # adopt a donor's state even at equal frontiers
                    self._needs_state.add(int(row))
                return ok
            t0 = time.monotonic()
            ok = self._create_locked(
                name, members, rec.get("app_state"), epoch, int(row), pending
            )
            if not ok:
                self._paused_put((name, epoch), rec)
                return False
            r = int(row)
            # device install + host bookkeeping via the SAME helpers the
            # batch path uses: resume_group IS resume_group_batch at N=1
            # (bit-exact parity is pinned by tests/test_batched_unpause)
            self._install_records_device_locked([(r, rec)])
            self._resume_record_host_locked(r, rec, name, epoch)
            self.metrics.observe(
                "unpause_latency_s", time.monotonic() - t0
            )
            return True

    def _install_records_device_locked(
        self, batch: List[Tuple[int, Dict]]
    ) -> None:
        """Scatter N pause records' consensus remnants into rows JUST
        created by ``create_groups`` — ONE fused device update (one
        ``.at[rows].set`` per touched leaf) regardless of N.  The old
        per-name install round-tripped the WHOLE state through host
        numpy per resumed name; a 4096-name wake burst paid that 4096
        times."""
        n = len(batch)
        W = self.cfg.window
        rows = np.empty(n, np.int32)
        exec_ = np.empty(n, np.int32)
        bal = np.empty(n, np.int32)
        app_hash = np.empty(n, np.int32)
        n_execd = np.empty(n, np.int32)
        acc_bal = np.full((n, W), NULL, np.int32)
        acc_vid = np.full((n, W), NULL, np.int32)
        acc_slot = np.full((n, W), NULL, np.int32)
        dec_vid = np.full((n, W), NULL, np.int32)
        dec_slot = np.full((n, W), NULL, np.int32)
        for i, (r, rec) in enumerate(batch):
            rows[i] = r
            exec_[i] = int(rec["exec"])
            # the row's device ballot is the implicit initial (0, coord0)
            # from the create, mirrored host-side in _bal_host — the max
            # is computable without a device read
            bal[i] = max(int(self._bal_host[r]), int(rec["bal"]))
            app_hash[i] = int(rec["app_hash"])
            n_execd[i] = int(rec["n_execd"])
            for slot, b, vid in rec.get("acc") or []:
                lane = slot % W
                acc_slot[i, lane] = slot
                acc_bal[i, lane] = b
                acc_vid[i, lane] = vid
            for slot, vid in rec.get("dec") or []:
                lane = slot % W
                dec_slot[i, lane] = slot
                dec_vid[i, lane] = vid
        self.state = restore_paused_rows(
            self.state, rows, exec_, bal, app_hash, n_execd,
            acc_bal, acc_vid, acc_slot, dec_vid, dec_slot,
        )

    def _resume_record_host_locked(
        self, r: int, rec: Dict, name: str, epoch: int
    ) -> None:
        """Per-name host bookkeeping of a record restore (everything in
        the resume besides the device scatter).  Shared verbatim by the
        per-name and batched paths; item order in a batch matches the
        equivalent sequence of per-name resumes."""
        self.app_exec_slot[r] = int(rec.get("app_exec", rec["exec"]))
        self._app_exec_dirty.add(r)
        if int(self.app_exec_slot[r]) < int(rec["exec"]):
            # a FORCED pause snapshots non-quiescent rows, so the
            # record can carry app_exec < exec — but the decided
            # slots in between are in NEITHER the record (dec
            # remnants keep only >= exec) nor pending_exec (dropped
            # with the pause).  The cursor can never replay its way
            # forward, and the gap may sit under jump_horizon with
            # nothing payload-blocked, so no heal detector fires
            # (txn-soak find: a hibernated-mid-traffic member woke
            # with app_exec 24 slots behind a current device
            # frontier and stayed there forever).  Park the row as
            # needing donor state — the per-tick state pull + the
            # app_only adoption clause close the gap
            self._needs_state.add(r)
        # the resume ROLLS BACK to the snapshot, so this member's own
        # response-cache entries for executions AFTER the snapshot
        # describe state the restored app does not contain — kept, they
        # would skip-execute those decisions during catch-up and diverge
        # the RSM (txn-soak find: a forced mid-traffic hibernate on
        # one member, woken as a straggler, came back short one
        # committed transfer).  The snapshot's own paired dedup
        # reinstalls right below.
        for rid in [
            r2 for r2, (_t, _resp, nm) in self.response_cache.items()
            if nm == name
        ]:
            del self.response_cache[rid]
        self.install_dedup(rec.get("dedup"))
        # the _create_locked journal entry has the app state as init;
        # the consensus remnants need the pause record on replay too
        if self.logger:
            self.logger.log_pause(rec)
        held = rec.get("held_vids") or []
        if held:
            self.queues[r] = [v for v in held if v in self.arena]
            scopes = rec.get("held_scopes") or {}
            for v in self.queues[r]:
                sc = scopes.get(str(v))
                # pre-scope records default to the resumed instance's
                # own scope (they were queued on its row)
                self.vid_scope[v] = (
                    (str(sc[0]), int(sc[1])) if sc else (name, int(epoch))
                )
        # release ORPHANED vids: a proposal admitted from the queue
        # into the device ring before a FORCED pause is in neither
        # the held queue nor the record's window remnants — the
        # consensus copy is gone, but its scheduling state survived
        # the pause (release_queue=False).  Kept, the stale
        # inflight entry parks every retransmit of that request id
        # here AND poisons forward-dedup of fresh peer proposals
        # for the same id, wedging the group on it forever
        # (txn-soak find: a resolver's commit re-drive starved
        # through 4k+ retransmits).  Undecided-only: remnant and
        # retained (decided) vids keep their state
        # re-homed/preempted vids can sit in OTHER rows' queues —
        # anything still queued anywhere is live, not orphaned
        kept = {v for q in self.queues.values() for v in q}
        kept.update(v for _s, _b, v in (rec.get("acc") or []))
        kept.update(v for _s, v in (rec.get("dec") or []))
        for v in [
            v for v, (nm, _ep) in self.vid_scope.items()
            if nm == name and v not in kept and v not in self.retained
        ]:
            self._release_vid(v)
        now = time.time()
        self.row_activity[r] = now
        # eviction hysteresis: a just-woken name is exempt from the idle
        # sweep for PAUSE_EVICTION_HYSTERESIS_S even if its wake burst
        # already ended (pause/resume flap protection)
        self._resumed_at[name] = now

    def resume_group_batch(
        self,
        items: List[Tuple[str, int, List[int], int, bool]],
    ) -> Dict[str, bool]:
        """Batched unpause: wake N paused records in ONE fused device
        update — one ``create_groups`` + one ``restore_paused_rows``
        (two scatters per touched leaf total) instead of N per-name row
        installs.  ``items`` is ``[(name, epoch, members, row, pending)]``.

        Only the pure record-restore case batches (name not live here, a
        local pause record exists, the target row is free and unique
        within the batch); anything else — live re-home, recordless
        join, collisions — falls back to the per-name :meth:`resume_group`
        so the batch is an optimization, never a semantic fork.  Returns
        ``{name: ok}``."""
        t0 = time.monotonic()
        out: Dict[str, bool] = {}
        n_fast = 0
        deferred: List[Tuple[str, int, List[int], int, bool]] = []
        with self._state_lock:
            self._await_step_locked()
            fast: List[Tuple[str, int, List[int], int, bool]] = []
            claimed: set = set()
            for name, epoch, members, row, pending in items:
                epoch, row = int(epoch), int(row)
                members = [int(m) for m in members]
                if (
                    name not in self.names
                    and (name, epoch) in self.paused
                    and row not in self.row_name
                    and row not in claimed
                    and members
                    and len(members) <= self.max_group_size
                ):
                    claimed.add(row)
                    fast.append((name, epoch, members, row, bool(pending)))
                else:
                    deferred.append((name, epoch, members, row, pending))
            if fast:
                # fault the spilled records in with sorted sequential
                # segment reads, not one random read per name
                if hasattr(self.paused, "restore_batch"):
                    self.paused.restore_batch(
                        [(nm, ep) for nm, ep, _m, _r, _p in fast]
                    )
                batch: List[Tuple[int, Dict]] = []
                names_l: List[str] = []
                rows_l: List[int] = []
                masks: List[int] = []
                coords: List[int] = []
                vers: List[int] = []
                tags: List[int] = []
                pendings: List[bool] = []
                recs: List[Dict] = []
                metas: List[Tuple[str, int, List[int]]] = []
                for name, epoch, members, row, pending in fast:
                    rec = self._paused_pop((name, epoch))
                    if rec is None:  # vanished (concurrent drop): defer
                        deferred.append((name, epoch, members, row, pending))
                        continue
                    mask = 0
                    for m in members:
                        mask |= 1 << m
                    self.names[name] = row
                    self.row_name[row] = name
                    if pending:
                        self.pending_rows.add(row)
                    coord0 = members[row % len(members)]
                    self._bal_host[row] = encode_ballot(0, coord0)
                    self.app_exec_slot[row] = 0
                    self._release_row_queue(row)
                    self.pending_exec.pop(row, None)
                    for arr in self.peer_app_exec.values():
                        arr[row] = 0
                    self._stall_since[row] = -1
                    self._stall_slot[row] = -1
                    self.row_activity[row] = time.time()
                    names_l.append(name)
                    rows_l.append(row)
                    masks.append(mask)
                    coords.append(coord0)
                    vers.append(epoch)
                    tags.append(_instance_tag(name, epoch))
                    pendings.append(bool(pending))
                    recs.append(rec)
                    metas.append((name, epoch, members))
                    batch.append((row, rec))
                if batch:
                    rows_np = np.array(rows_l, np.int32)
                    self.state = create_groups(
                        self.state, rows_np,
                        np.array(masks, np.int32),
                        np.array(coords, np.int32),
                        my_id=self.my_id,
                        version=np.array(vers, np.int32),
                        tag=np.array(tags, np.int32),
                    )
                    if self.logger:
                        self.logger.log_create(
                            rows_np, np.array(masks, np.int32),
                            np.array(vers, np.int32),
                            np.array(coords, np.int32),
                            names=names_l,
                            inits=[rec.get("app_state") for rec in recs],
                            pendings=pendings,
                        )
                    for (name, _ep, members), rec in zip(metas, recs):
                        if self.my_id in members:
                            self.app.restore(name, rec.get("app_state"))
                    self._install_records_device_locked(batch)
                    for (row, rec), (name, epoch, _m) in zip(batch, metas):
                        self._resume_record_host_locked(
                            row, rec, name, epoch
                        )
                        out[name] = True
                    n_fast = len(batch)
        if n_fast:
            dt = time.monotonic() - t0
            # every name in the burst became available when the batch
            # completed: the burst wall time IS each name's wake latency
            # (deferred items observe inside their per-name resume)
            self.metrics.observe_bulk(
                "unpause_latency_s", [dt] * n_fast
            )
        # non-fast-path items: the per-name resume outside the batch
        # (it re-takes the lock; a collision NACK maps to False)
        for name, epoch, members, row, pending in deferred:
            try:
                out[name] = self.resume_group(
                    name, epoch, members, row, pending
                )
            except RuntimeError:
                out[name] = False
        return out

    # ------------------------------------------------------------------
    # hibernate / restore (checkpoint + sleep on disk; local wake-up —
    # PaxosManager.hibernate:2209-2227 / restore:2230-2252)
    # ------------------------------------------------------------------
    def hibernate(self, name: str) -> bool:
        """Checkpoint (name)'s current epoch durably and release the row
        AND its RAM — the instance sleeps on disk.  Unlike the
        RC-coordinated pause (capacity residency), this is a LOCAL op:
        the snapshot is forced (window remnants ride along), and
        :meth:`restore` wakes it locally from the journaled record with a
        full rollback to that snapshot, no reconfigurator round."""
        with self._state_lock:
            row = self.names.get(name)
            if row is None:
                return False
            epoch = int(self._np("version")[row])
        if self.pause_group(name, epoch, force=True) != "ok":
            return False
        # page the record out of RAM when the paused table can spill
        # (reference: softCrash removes the instance object entirely; the
        # journaled pause record is the disk copy that outlives us)
        if hasattr(self.paused, "demote"):
            self.paused.demote((name, epoch))
        return True

    def hibernate_batch(self, names: List[str]) -> int:
        """Hibernate MANY names: one batched extract off the cached host
        leaves, ONE fused ``kill_groups`` scatter, one sequential spill
        run.  Per-name :meth:`hibernate` costs a device kill dispatch per
        name — putting a 1M-name cold tail to sleep that way is minutes
        of pure dispatch overhead (the density campaign's boot path).
        Forced-pause semantics identical to :meth:`hibernate`: window
        remnants and held vids ride in the records.  Returns how many
        names went to sleep."""
        with self._state_lock:
            self._await_step_locked()
            versions = self._np("version")
            stopped = self._np("stopped")
            jobs: List[Tuple[str, int, int]] = []
            for name in names:
                row = self.names.get(name)
                if row is None or row in self.hydrating_rows:
                    continue  # not hosted / snapshot would be blank
                if int(stopped[row]):
                    continue  # stopping group: the delete path owns it
                jobs.append((name, int(versions[row]), row))
            if not jobs:
                return 0
            # ONE grouped pass over the response cache for every job's
            # dedup entries (the per-name scan is O(cache) each)
            wanted = {name for name, _e, _r in jobs}
            dedup_by_name: Dict[str, Dict] = {}
            for rid, (t, resp, nm) in self.response_cache.items():
                if nm in wanted:
                    dedup_by_name.setdefault(nm, {})[str(rid)] = [
                        t, resp, nm
                    ]
            rows_l: List[int] = []
            keys: List[Tuple[str, int]] = []
            for name, epoch, row in jobs:
                rec = self._extract_record(
                    name, epoch, row, dedup=dedup_by_name.get(name, {})
                )
                held = list(self.queues.get(row, []))
                if held:
                    rec["held_vids"] = held
                    rec["held_scopes"] = {
                        str(v): list(self.vid_scope[v])
                        for v in held if v in self.vid_scope
                    }
                if self.logger:
                    self.logger.log_pause(rec)
                self._paused_put((name, epoch), rec)
                rows_l.append(row)
                keys.append((name, epoch))
            rows_np = np.array(rows_l, np.int32)
            self.state = kill_groups(self.state, rows_np)
            if self.logger:
                self.logger.log_kill(rows_np)
            for name, _epoch, row in jobs:
                # host side of _kill_locked(release_queue=False), minus
                # the per-name device op the fused kill replaced
                self.names.pop(name, None)
                self.row_name.pop(row, None)
                self.pending_rows.discard(row)
                self.hydrating_rows.discard(row)
                self._payload_blocked.pop(row, None)
                self._stall_since[row] = -1
                self._stall_slot[row] = -1
                self._needs_state.discard(row)
                self.queues.pop(row, None)
                self.pending_exec.pop(row, None)
            # page the records out of RAM as one sequential append run
            if hasattr(self.paused, "demote_batch"):
                self.paused.demote_batch(keys)
            elif hasattr(self.paused, "demote"):
                for key in keys:
                    self.paused.demote(key)
            return len(jobs)

    def restore_batch(self, names: List[str]) -> int:
        """Wake MANY hibernated names via :meth:`resume_group_batch` —
        one fused device update for the whole burst, with the spilled
        records faulted in by sorted sequential segment reads.  Rows are
        the same deterministic ``default_row_for`` probe the per-name
        :meth:`restore` uses (intra-batch collisions probe onward).
        Returns how many names are awake afterward."""
        n_awake = 0
        items: List[Tuple[str, int, List[int], int, bool]] = []
        with self._state_lock:
            keys = []
            for name in names:
                if self.names.get(name) is not None:
                    n_awake += 1  # already awake
                    continue
                eps = self._paused_by_name.get(name)
                if eps:
                    keys.append((name, max(eps)))
            # fault the whole burst's records in sequentially, then read
            # the member sets the wake needs
            recs = (
                self.paused.restore_batch(keys)
                if hasattr(self.paused, "restore_batch")
                else {k: self.paused[k] for k in keys if k in self.paused}
            )
            import zlib

            G = self.cfg.n_groups
            claimed: set = set()
            for name, epoch in keys:
                rec = recs.get((name, epoch))
                members = rec.get("members") if rec else None
                if not members:
                    continue
                row = zlib.crc32(name.encode("utf-8")) % G
                for _ in range(G):
                    if row not in self.row_name and row not in claimed:
                        break
                    row = (row + 1) % G
                else:
                    break  # capacity exhausted: stop staging wakes
                claimed.add(row)
                items.append((name, epoch, members, row, False))
        if items:
            res = self.resume_group_batch(items)
            n_awake += sum(1 for ok in res.values() if ok)
        return n_awake

    def restore(self, name: str) -> bool:
        """Wake a hibernated instance: roll back to its journaled
        snapshot at a locally chosen row.  Row choice is the same
        deterministic ``default_row_for`` probe every member uses, so a
        cluster whose members hibernated/restored the same set of names
        re-aligns; deployments that cannot guarantee that use the
        RC-coordinated resume (which carries the row)."""
        with self._state_lock:
            if self.names.get(name) is not None:
                return True  # already awake
            # the by-name mirror, NOT a key scan: the paused table is the
            # cold tail (millions of names at density scale)
            epochs = self._paused_by_name.get(name)
        if not epochs:
            return False
        epoch = max(epochs)
        with self._state_lock:
            rec = self.paused.get((name, epoch))
            if rec is None:
                return False
            members = rec.get("members")
        if not members:
            return False
        try:
            row = self.default_row_for(name)
            return self.resume_group(name, epoch, members, row,
                                     pending=False)
        except RuntimeError:
            # capacity exhausted / row collision: a failed wake-up the
            # caller can retry after freeing rows, not a crash
            return False

    def pending_row_keys(self) -> List[Tuple[str, int, int]]:
        """(name, epoch, row) for every row still behind the pre-COMPLETE
        admission gate.  Normally transient; a row stuck here after its
        late-start retransmits expired is wedged (it refuses every
        proposal) and must ask the RC where the epoch really lives."""
        with self._state_lock:
            out = []
            versions = self._np("version")
            for row in self.pending_rows:
                name = self.row_name.get(row)
                if name is not None and self.names.get(name) == row:
                    out.append((name, int(versions[row]), int(row)))
            return out

    def drop_pending_row(self, name: str, epoch: int, row: int) -> None:
        """RC says this pending row's epoch is gone: free it."""
        with self._state_lock:
            self._await_step_locked()
            cur = self.names.get(name)
            if cur != int(row) or cur not in self.pending_rows:
                return
            if int(self._np("version")[cur]) != int(epoch):
                return
            self._kill_locked(name)

    def stopped_row_keys(self) -> List[Tuple[str, int]]:
        """(name, epoch) of CURRENT mappings whose epoch-final stop has
        executed.  A stopped current row is always awaiting an epoch
        transition (the delete's drop round, or an upgrade) — normally
        transient, but a drop can RACE residency: a member that acked
        the drop while paused (not hosting), then resumed and executed
        the stop, holds a live stopped row with no record and no
        bookkeeping left to clean it (chaos-sweep find: names lingering
        post-delete).  The epoch probe asks the RC about these."""
        out = []
        with self._state_lock:
            versions = self._np("version")
            stopped = self._np("stopped")
            for name, row in self.names.items():
                if int(stopped[row]):
                    out.append((name, int(versions[row])))
        return out

    def pause_record_keys(self) -> List[Tuple[str, int]]:
        """(name, epoch) of every locally held pause record (the AR layer
        probes the RC about them: a record the RC no longer knows is
        droppable; a record whose epoch is LIVE means an aborted pause
        round left this member frozen and it must rejoin)."""
        with self._state_lock:
            return [(str(n), int(e)) for (n, e) in self.paused]

    def drop_pause_record(self, name: str, epoch: int) -> None:
        with self._state_lock:
            self._paused_pop((name, int(epoch)))

    def dedup_for_name(self, name: str) -> Dict[str, list]:
        """This name's exactly-once entries, for shipping WITH any app
        -state handoff (epoch final state, pause record, state transfer):
        an adopted state without its dedup entries re-executes re-proposed
        duplicates; entries for other names suppress executions the
        adopted state lacks — both diverge the RSM."""
        with self._state_lock:
            return {
                str(rid): [t, resp, nm]
                for rid, (t, resp, nm) in self.response_cache.items()
                if nm == name
            }

    def install_dedup(self, entries: Optional[Dict]) -> None:
        now = time.time()
        with self._state_lock:
            for rid_s, ent in (entries or {}).items():
                self.response_cache.setdefault(
                    int(rid_s),
                    (min(float(ent[0]), now), ent[1], str(ent[2])),
                )

    def drain_demand(self) -> Dict[str, Tuple[int, int]]:
        """Take the per-name request counts since the last drain; returns
        {name: (count, epoch)} for current-epoch names."""
        with self._state_lock:
            counts, self.demand_counts = self.demand_counts, {}
            self.demand_backlog = 0
            versions = self._np("version")
            out = {}
            for name, n in counts.items():
                row = self.names.get(name)
                if row is not None:
                    out[name] = (n, int(versions[row]))
            return out

    def idle_names(self, idle_s: float) -> List[Tuple[str, int]]:
        """(name, epoch) of current-epoch groups with no traffic for
        `idle_s` seconds (Deactivator sweep candidates)."""
        out = []
        cut = time.time() - idle_s
        with self._state_lock:
            versions = self._np("version")
            for name, row in self.names.items():
                if row in self.pending_rows or self.queues.get(row):
                    continue
                if self.row_activity[row] < cut:
                    out.append((name, int(versions[row])))
        return out

    def eviction_candidates(
        self, idle_s: float, limit: Optional[int] = None,
    ) -> List[Tuple[str, int]]:
        """Admission-aware pause-eviction order for the idle sweeper:
        ``idle_names`` filtered and SORTED coldest-first — last-use wall
        time ascending, cumulative group heat (PR-18 telemetry) as the
        tiebreak — so a capped sweep (``limit``) always takes the truly
        cold tail and a name with queued admissions, undrained
        executions, an in-flight hydration, or recent traffic is never
        paused ahead of a colder one.  Names resumed within
        ``PAUSE_EVICTION_HYSTERESIS_S`` are exempt (pause/resume flap
        protection for a rotating hot set)."""
        now = time.time()
        cut = now - idle_s
        hyst = Config.get_float(PC.PAUSE_EVICTION_HYSTERESIS_S)
        scored = []
        with self._state_lock:
            versions = self._np("version")
            stopped = self._np("stopped")
            # prune the hysteresis ledger so it stays bounded by the
            # names that actually resumed recently
            for nm in [
                n for n, t in self._resumed_at.items() if now - t > hyst
            ]:
                del self._resumed_at[nm]
            for name, row in self.names.items():
                if row in self.pending_rows or self.queues.get(row):
                    continue  # queued admissions: definitionally not idle
                if self.pending_exec.get(row) or row in self.hydrating_rows:
                    continue  # undrained work / snapshot would be blank
                if int(stopped[row]):
                    continue  # the delete/upgrade path owns stopping rows
                if self.row_activity[row] >= cut:
                    continue
                t_res = self._resumed_at.get(name)
                if t_res is not None and now - t_res < hyst:
                    continue
                scored.append((
                    float(self.row_activity[row]),
                    int(self._heat_host[row]),
                    name, int(versions[row]),
                ))
        scored.sort(key=lambda s: (s[0], s[1]))
        if limit is not None:
            scored = scored[: max(0, int(limit))]
        return [(name, ep) for _t, _h, name, ep in scored]

    def residency_stats(self) -> Dict:
        """The ``stats`` admin op's ``residency`` block: where every name
        lives (engine rows vs paused-in-RAM vs paused-on-disk) plus the
        spill store's internals — and the gauge refresh for the
        ``paused_in_memory`` / ``paused_on_disk`` metrics (stats-cadence,
        like the group-heat pull)."""
        with self._state_lock:
            paused = self.paused
            in_mem = int(getattr(paused, "n_in_memory", len(paused)))
            on_disk = int(getattr(paused, "n_on_disk", 0))
            out = {
                "active_names": len(self.names),
                "pending_rows": len(self.pending_rows),
                "paused_names": len(paused),
                "paused_in_memory": in_mem,
                "paused_on_disk": on_disk,
                "hysteresis_tracked": len(self._resumed_at),
                "store": (
                    paused.stats() if hasattr(paused, "stats")
                    else {"kind": "dict", "in_memory": in_mem, "on_disk": 0}
                ),
            }
        self.metrics.gauge("paused_in_memory", in_mem)
        self.metrics.gauge("paused_on_disk", on_disk)
        return out

    def get_replica_group(self, name: str) -> Optional[List[int]]:
        row = self.names.get(name)
        if row is None:
            return None
        mask = int(self._np("member_mask")[row])
        return [r for r in range(32) if (mask >> r) & 1]

    def epoch_row(self, name: str, epoch: int) -> Optional[int]:
        """Row hosting (name, epoch) here — current or demoted — or None."""
        with self._state_lock:
            row = self.old_epochs.get((name, epoch))
            if row is not None:
                return row
            cur = self.names.get(name)
            if cur is not None and int(self._np("version")[cur]) == epoch:
                return cur
            return None

    def current_epoch(self, name: str) -> Optional[int]:
        with self._state_lock:
            row = self.names.get(name)
            if row is None:
                return None
            return int(self._np("version")[row])

    def is_stopped(self, name: str) -> bool:
        with self._state_lock:
            row = self.names.get(name)
            if row is None:
                return False
            return bool(int(self._np("stopped")[row]))

    def app_caught_up(self, name: str) -> bool:
        """Host app cursor == device frontier for the name's current row:
        the app state string reflects EVERY decision the device has
        executed.  The device can run ahead (host execution is
        payload-gated), so any caller about to serve ``app.checkpoint``
        as a consistent snapshot must check this — a device-level
        ``stopped`` flag alone does NOT mean the app has applied the
        epoch's tail (chaos-sweep find: a truncated 'final state' served
        from a stopped-on-device/lagging-on-host member diverged the
        next epoch's joiners)."""
        with self._state_lock:
            row = self.names.get(name)
            if row is None or row in self.hydrating_rows:
                # un-hydrated (recovery plane): the app state string does
                # not reflect ANY executed decision yet — serving it as a
                # consistent snapshot would hand out the pre-restore blank
                return False
            return int(self.app_exec_slot[row]) == int(
                self._np("exec_slot")[row]
            )

    # ------------------------------------------------------------------
    # cross-node trace plumbing (obs/reqtrace.py)
    # ------------------------------------------------------------------
    def _install_trace_locked(
        self, request_id: int, tc, gossip: bool = True
    ) -> None:
        """Remember a sampled request's trace context (state lock held).
        ``gossip=True`` queues it for the next payloads frame so every
        replica can stamp its decide/execute events; gossip-received
        contexts install with ``gossip=False`` (re-broadcasting them
        would ping-pong; the origin's broadcast already reached all
        peers)."""
        if tc is None or request_id is None:
            return
        d = self.trace_ctx
        if request_id not in d and len(d) >= self.TRACE_CTX_CAP:
            # bounded FIFO: dict preserves insertion order
            for k in list(
                itertools.islice(d, max(1, self.TRACE_CTX_CAP // 8))
            ):
                del d[k]
        d.setdefault(request_id, tc)
        if gossip:
            self._tc_gossip[request_id] = d[request_id]

    @staticmethod
    def _tc_detail(tc) -> Dict:
        """Event-detail fields for a trace context (empty when None)."""
        return {} if tc is None else {"tid": tc[0], "hop": tc[2]}

    # ------------------------------------------------------------------
    # propose (PaxosManager.propose/proposeStop, :1195-1390)
    # ------------------------------------------------------------------
    def propose(
        self,
        name: str,
        request_value: str,
        callback: Optional[Callable] = None,
        stop: bool = False,
        request_id: Optional[int] = None,
        entry_replica: Optional[int] = None,
        trace_ctx=None,
    ) -> Optional[int]:
        """Enqueue a request for consensus; returns the assigned vid (or
        None if the name is unknown here).  ``trace_ctx`` is the optional
        cross-node ``(trace_id, origin, hop)`` a sampled request arrived
        with — installed for the decide/execute/flush hops and recorded
        even when the local tracer is off (sampling is decided at the
        origin).

        Thread-safe: callable from transport threads concurrently with the
        tick loop (the lock covers the vid counter and the queue/arena
        handoff — vids key the cross-replica payload arena, so two threads
        must never mint the same vid for different requests).  User
        callbacks never run under the lock (a blocking callback must not
        stall the tick loop or other transport threads)."""
        cached_hit = False
        cached_response = None
        emulated = None
        with self._state_lock:
            row = self.names.get(name)
            if row is None:
                return None
            entry = self.my_id if entry_replica is None else entry_replica
            # exactly-once fast path: a retransmitted request id is answered
            # from the response cache, not re-proposed
            if request_id is not None and request_id in self.response_cache:
                cached_hit = True
                cached_response = self.response_cache[request_id][1]
            elif (
                request_id is not None
                and self.inflight.get(request_id) in self.vid_meta
            ):
                # original proposal still live here: refresh the callback
                # (the client re-registered) and wait for execution
                if callback is not None:
                    self.outstanding.put(request_id, callback)
                return None
            elif (
                self.emulate_unreplicated or self.lazy_propagation
            ) and not stop:
                # EMULATE_UNREPLICATED / LAZY_PROPAGATION test modes
                # (PaxosManager.java:1731-1778): execute at the entry
                # replica IMMEDIATELY, without waiting for agreement, so a
                # capacity run can attribute cost between app+wire and
                # consensus.  UNREPLICATED skips consensus entirely;
                # LAZY additionally still drives the proposal through the
                # group (peers execute it; the entry's early execution is
                # skipped at commit via the response cache) — both
                # deliberately weaken RSM ordering and exist only for
                # measurement.  The app call runs OUTSIDE the lock below
                # (a slow/failing execute must not wedge the whole node);
                # a concurrent retransmit while it runs is simply dropped
                # (the client retries into the cache).
                if request_id in self._emulating:
                    return None
                if self._next_counter > VID_COUNTER_MASK:
                    raise RuntimeError("vid counter space exhausted")
                counter = self._next_counter
                self._next_counter += 1
                if request_id is None:
                    request_id = (self._rid_nonce << 24) | counter
                self._emulating.add(request_id)
                emulated = (counter, request_id)
            else:
                if self._next_counter > VID_COUNTER_MASK:
                    raise RuntimeError("vid counter space exhausted")
                vid = (self.my_id << VID_NODE_SHIFT) | self._next_counter
                self._next_counter += 1
                if request_id is None:
                    # boot-unique: the bare vid counter RESETS across
                    # restarts when its vid was forwarded away before
                    # being journaled, and a reused id collides with the
                    # now-persistent dedup entries of pre-restart
                    # requests (misread as duplicates — chaos-soak find)
                    request_id = (self._rid_nonce << 24) | (
                        vid & VID_COUNTER_MASK
                    )
                if stop:
                    vid |= STOP_BIT
                self.arena[vid] = request_value
                self.vid_meta[vid] = (entry, request_id)
                # admission scope: queued vids can outlive the instance
                # they were proposed for (row re-homes carry held queues,
                # preemption re-queues by row number) — the drain refuses
                # to admit a vid into a different name's instance, or an
                # epoch-final stop into any later epoch (chaos-soak find:
                # a stale epoch-0 stop decided inside epoch 3 diverges any
                # member whose dedup entry for it aged out)
                self.vid_scope[vid] = (
                    name, int(self._np("version")[row])
                )
                self.inflight[request_id] = vid
                if callback is not None:
                    self.outstanding.put(request_id, callback)
                self.queues.setdefault(row, []).append(vid)
                self.row_activity[row] = time.time()
                self.demand_counts[name] = self.demand_counts.get(name, 0) + 1
                self.demand_backlog += 1
                self._install_trace_locked(request_id, trace_ctx)
                if self.tracer.enabled or trace_ctx is not None:
                    self.tracer.note(
                        request_id, "propose", name=name, node=self.my_id,
                        vid=vid, row=row, entry=entry, stop=bool(stop),
                        force=trace_ctx is not None,
                        **self._tc_detail(trace_ctx),
                    )
        if emulated is not None:
            counter, request_id = emulated
            req = SlimRequest(name, request_id, request_value)
            self._app_execute_retrying(
                req, do_not_reply=(entry != self.my_id)
            )
            response = getattr(req, "response_value", None)
            with self._state_lock:
                if self._cacheable(req):
                    self._cache_response(request_id, response, name)
                self.total_executed += 1
                self.row_activity[row] = time.time()
                self._emulating.discard(request_id)
                if self.lazy_propagation and name in self.names:
                    vid = (self.my_id << VID_NODE_SHIFT) | counter
                    self.arena[vid] = request_value
                    self.vid_meta[vid] = (entry, request_id)
                    self.vid_scope[vid] = (
                        name, int(self._np("version")[row])
                    )
                    self.inflight[request_id] = vid
                    self.queues.setdefault(row, []).append(vid)
            if callback:
                callback(request_id, response)
            return None
        if cached_hit:
            if self.tracer.enabled or trace_ctx is not None:
                self.tracer.note(request_id, "respond-cached", name=name,
                                 node=self.my_id,
                                 force=trace_ctx is not None,
                                 **self._tc_detail(trace_ctx))
            if callback:
                callback(request_id, cached_response)
            return None
        return vid

    def propose_stop(self, name: str, request_value: str = "", **kw) -> Optional[int]:
        return self.propose(name, request_value, stop=True, **kw)

    def propose_batch(
        self,
        items: List[Tuple],
        entry_replica: Optional[int] = None,
    ) -> List[Tuple[Optional[int], str, Optional[str]]]:
        """Batched ingress for a ``client_request_batch`` frame — the
        proposeBatched analog (``PaxosManager.java:1226``) on the entry
        side: ONE lock acquisition, one timestamp, and the per-item work
        stripped to the queue handoff, where the singleton `propose` pays
        lock+clock+cache-churn per request (at 20k req/s the per-request
        constant IS the system capacity).

        ``items``: [(name, value, request_id, callback)] — an optional
        5th element overrides the entry replica per item (forwarded
        proposals keep their original entry) and an optional 6th element
        is the item's cross-node trace context (tid, origin, hop).
        Returns
        [(request_id, outcome, response)]: "queued", "cached" (callback
        already fired with the response), "inflight" (original still
        live; callback re-registered), or "unknown" (name not here).
        Emulation modes take the singleton path (they execute inline)."""
        if self.emulate_unreplicated or self.lazy_propagation:
            # singleton path per item (it executes inline); propose()
            # returns None for BOTH "executed emulated" and "unknown
            # name", so unknown is detected up front — the batch caller
            # owes the client an error response for those
            out = []
            for item in items:
                name, value, rid, cb = item[:4]
                if self.names.get(name) is None:
                    out.append((rid, "unknown", None))
                    continue
                self.propose(
                    name, value, callback=cb, request_id=rid,
                    entry_replica=(
                        item[4] if len(item) > 4 else None
                    ),
                )
                out.append((rid, "emulated", None))
            return out
        results: List[Tuple[Optional[int], str, Optional[str]]] = []
        fired: List[Tuple[Callable, int, Optional[str]]] = []
        now = time.time()
        default_entry = self.my_id if entry_replica is None else entry_replica
        tr_on = self.tracer.enabled
        with self._state_lock:
            versions = self._np("version")
            names, cache = self.names, self.response_cache
            inflight, meta = self.inflight, self.vid_meta
            for item in items:
                name, value, rid, cb = item[:4]
                entry = (
                    item[4] if len(item) > 4 and item[4] is not None
                    else default_entry
                )
                tc = item[5] if len(item) > 5 else None
                row = names.get(name)
                if row is None:
                    results.append((rid, "unknown", None))
                    continue
                if rid is not None and rid in cache:
                    resp = cache[rid][1]
                    if cb is not None:
                        fired.append((cb, rid, resp))
                    results.append((rid, "cached", resp))
                    continue
                if rid is not None and inflight.get(rid) in meta:
                    if cb is not None:
                        self.outstanding.put(rid, cb)
                    results.append((rid, "inflight", None))
                    continue
                if self._next_counter > VID_COUNTER_MASK:
                    # per-item failure, NOT a raise: a mid-frame exception
                    # would discard the already-collected cached responses
                    # in `results` and never fire the callbacks queued in
                    # `fired` — and an up-front whole-frame reject would
                    # deny cached/inflight items that mint no vid at all
                    results.append((rid, "exhausted", None))
                    continue
                vid = (self.my_id << VID_NODE_SHIFT) | self._next_counter
                self._next_counter += 1
                if rid is None:
                    rid = (self._rid_nonce << 24) | (vid & VID_COUNTER_MASK)
                self.arena[vid] = value
                meta[vid] = (entry, rid)
                self.vid_scope[vid] = (name, int(versions[row]))
                inflight[rid] = vid
                if cb is not None:
                    self.outstanding.put_at(rid, cb, now)
                self.queues.setdefault(row, []).append(vid)
                self.row_activity[row] = now
                self.demand_counts[name] = self.demand_counts.get(name, 0) + 1
                self.demand_backlog += 1
                results.append((rid, "queued", None))
                self._install_trace_locked(rid, tc)
                if tr_on or tc is not None:
                    self.tracer.note(
                        rid, "propose", name=name, node=self.my_id,
                        vid=vid, row=row, entry=entry, batch=True,
                        force=tc is not None, **self._tc_detail(tc),
                    )
        for cb, rid, resp in fired:
            cb(rid, resp)
        return results

    def overloaded(self) -> bool:
        """Entry back-pressure: too many in-flight requests here."""
        return len(self.inflight) >= self.max_outstanding

    def has_backlog(self) -> bool:
        """Unadmitted or undecided work exists (drives the server loop's
        adaptive cadence).  Lock-free heuristic peek: a stale read only
        costs one tick of the wrong cadence.  Queues held on PENDING rows
        don't count — they cannot drain until the epoch commit lands, and
        counting them would spin the loop through thousands of no-op
        engine ticks for the whole pending window."""
        hydrating = self.hydrating_rows
        if self.pending_exec and any(
            g not in hydrating for g in self.pending_exec
        ):
            return True
        pending = self.pending_rows
        return any(
            vids and row not in pending and row not in hydrating
            for row, vids in self.queues.items()
        )

    def engine_work_in_flight(self) -> bool:
        """True while any member row holds consensus work that the next
        peer blob can advance: accepted-but-unexecuted lanes or
        outstanding coordinator proposals.  Drives the server's
        event-kicked tick (a blob arriving mid-round should be consumed
        NOW, not a full tick quantum later — per-hop quantum delays are
        what made the socket path's round trip ~10x the engine's)."""
        with self._state_lock:
            acc_slot = self._np("acc_slot")
            acc_vid = self._np("acc_vid")
            exec_slot = self._np("exec_slot")
            prop = self._np("c_prop_vid")
        live = (
            (acc_slot != NULL) & (acc_vid != NULL)
            & (acc_slot >= exec_slot[:, None])
        )
        return bool(live.any() or (prop != NULL).any())

    # ------------------------------------------------------------------
    # host channel ingress (payload replication + forwarded proposals)
    # ------------------------------------------------------------------
    def on_host_message(self, kind: str, body: Dict) -> None:
        with self._state_lock:
            self._on_host_message_locked(kind, body)

    def _on_host_message_locked(self, kind: str, body: Dict) -> None:
        if kind == "payloads":
            fresh: Dict[int, str] = {}
            for k, v in body["arena"].items():
                k = int(k)
                if k not in self.arena:
                    self.arena[k] = v
                    fresh[k] = v
            for k, meta in body.get("meta", {}).items():
                self.vid_meta.setdefault(int(k), (meta[0], meta[1]))
            if fresh and self.logger is not None:
                # peer-replicated payloads must be durable HERE too: if
                # only the admitting coordinator persisted them, a
                # coordinator-only crash could lose decided-but-unexecuted
                # values for everyone
                t_lp = time.monotonic()
                self.logger.log_payloads(fresh, meta={
                    k: self.vid_meta[k] for k in fresh if k in self.vid_meta
                })
                DelayProfiler.update_count(
                    "t_log_payloads", time.monotonic() - t_lp
                )
            tcs = body.get("tc")
            if tcs:
                # trace contexts ride the payload gossip so every replica
                # can stamp its decide/execute events with the trace id
                # (gossip=False: the origin already broadcast to all)
                for rid_s, tc in tcs.items():
                    try:
                        self._install_trace_locked(
                            int(rid_s),
                            (int(tc[0]), int(tc[1]), int(tc[2])),
                            gossip=False,
                        )
                    except (TypeError, ValueError, IndexError):
                        continue
            ae = body.get("app_exec")
            if ae is not None:
                rid, cursors = ae
                arr = self.peer_app_exec.get(rid)
                if arr is None:
                    arr = np.zeros(self.cfg.n_groups, np.int64)
                    self.peer_app_exec[rid] = arr
                if isinstance(cursors, dict):  # sparse delta (normal path)
                    # LAST-writer-wins for rows the sender lists: it is
                    # authoritative for its own cursor, frames are FIFO
                    # per peer, and a max-only merge could never LOWER a
                    # stale value left by a row's previous tenant (which
                    # would pin the retention watermark wrongly and
                    # false-arm the frontier-stall detector forever)
                    for row_s, cur in cursors.items():
                        arr[int(row_s)] = cur
                else:  # dense snapshot (legacy peers)
                    np.maximum(arr, np.asarray(cursors, np.int64), out=arr)
        elif kind == "forward":  # a peer forwards a proposal to me
            fwd_epoch = body.get("epoch")
            if fwd_epoch is not None and (
                self.current_epoch(body["name"]) != int(fwd_epoch)
            ):
                # a DELAYED forward from a superseded epoch must not be
                # injected into the current one — an old epoch's stop
                # executing in the new epoch diverges the RSM (chaos
                # soak); genuine client requests retransmit
                return
            tc = body.get("tc")
            tc = None if not tc else (int(tc[0]), int(tc[1]), int(tc[2]))
            if self.tracer.enabled or tc is not None:
                self.tracer.note(
                    body.get("request_id"), "forward-in",
                    name=body["name"], node=self.my_id,
                    entry=body.get("entry"),
                    force=tc is not None, **self._tc_detail(tc),
                )
            self.propose(
                body["name"], body["value"],
                stop=body.get("stop", False),
                request_id=body.get("request_id"),
                entry_replica=body.get("entry", None),
                trace_ctx=tc,
            )
        elif kind == "forward_batch":
            # a peer forwards a whole queue run (one frame, many
            # proposals).  Same staleness guard as singleton forwards.
            # FIFO within the run is preserved: requests accumulated
            # before a stop flush BEFORE the stop is proposed (proposing
            # the stop first would decide it ahead of requests that
            # preceded it, and the epoch bump would drop them as stale).
            if self.current_epoch(body["name"]) != int(body["epoch"]):
                return
            name = body["name"]
            tcs = body.get("tc") or {}

            def _tc_of(rid):
                tc = tcs.get(str(rid))
                return None if not tc else (
                    int(tc[0]), int(tc[1]), int(tc[2])
                )

            tr_on = self.tracer.enabled
            if tr_on or tcs:
                for rid, entry, _v, _s in body["reqs"]:
                    tc = _tc_of(rid)
                    if tr_on or tc is not None:
                        self.tracer.note(rid, "forward-in", name=name,
                                         node=self.my_id, entry=entry,
                                         force=tc is not None,
                                         **self._tc_detail(tc))
            items = []
            for rid, entry, value, stop in body["reqs"]:
                if stop:
                    if items:
                        self.propose_batch(items)
                        items = []
                    self.propose(
                        name, value, stop=True, request_id=rid,
                        entry_replica=entry, trace_ctx=_tc_of(rid),
                    )
                else:
                    items.append((name, value, rid, None, entry,
                                  _tc_of(rid)))
            if items:
                self.propose_batch(items)
        elif kind == "state_request":  # checkpoint-transfer pull
            self._serve_state_request(body)
        elif kind == "state_reply":
            self._apply_state_reply(
                body["states"], body.get("response_cache") or {}
            )
        elif kind == "need_payloads":  # straggler pull (SYNC_DECISIONS)
            sync = SyncDecisionsPacket.from_json(body)
            have = {v: self.arena[v] for v in sync.missing if v in self.arena}
            if have:
                meta = {
                    v: list(self.vid_meta[v])
                    for v in have if v in self.vid_meta
                }
                self.forward_out.append(
                    (sync.node_id, "payloads", {"arena": have, "meta": meta})
                )

    # ------------------------------------------------------------------
    # the tick
    # ------------------------------------------------------------------
    def coordinator_of_row(self, row: int) -> int:
        return int(ballot_coord(int(self._np("bal")[row])))

    def _filter_stale_vids(self, row: int, vids: List[int]) -> List[int]:
        """Admission guard: drop queued vids whose proposal scope no
        longer matches the instance now living at this row.  A vid may
        ride a re-home, a pause record, or a preemption re-queue into a
        row that has since been reused by another name, or into a later
        epoch of the same name.  Ordinary requests legitimately cross
        epochs (the app state carries over; exactly-once holds via the
        dedup cache) — but an epoch-final STOP is epoch-specific: decided
        in a later epoch it wrongly stops that epoch, and any member
        whose dedup entry for it expired executes it (RSM divergence,
        chaos-soak find).  Cross-NAME vids are always dropped.  Dropped
        vids release their inflight slot so a retransmitted proposal
        (e.g. the stop task's re-drive, which uses a deterministic
        request id) is not deduped against the dead one."""
        name = self.row_name.get(row)
        epoch_now = int(self._np("version")[row])
        keep: List[int] = []
        for vid in vids:
            if vid in self.retained:
                # a preemption re-queue raced the decision: the original
                # proposal got decided (and executed) after the re-queue,
                # so this copy is done — drop it from the queue WITHOUT
                # touching arena/meta (retention GC owns that lifecycle;
                # peers may still pull the payload)
                continue
            scope = self.vid_scope.get(vid)
            stale = scope is not None and (
                scope[0] != name
                or (bool(vid & STOP_BIT) and scope[1] != epoch_now)
            )
            if not stale and vid in self.arena:
                keep.append(vid)
                continue
            # out-of-scope, or the payload is gone (decided elsewhere and
            # retention-GC'd): nothing valid to propose — admitting it
            # would decide a lost payload, and forwarding it would ship
            # an EMPTY value that wedges the peer's RSM (chaos-soak find)
            self._release_vid(vid)
        # ALWAYS install and return the live queue list: callers mutate the
        # returned list in place (the forward branch clears it) and must be
        # operating on the real queue, not a filtered copy
        self.queues[row] = keep
        return keep

    def _coalesce_row_queue(self, row: int, name: str, epoch: int,
                            vids: List[int]) -> List[int]:
        """Pack runs of plain requests into BATCH vids (the RequestBatcher
        analog, ``RequestBatcher.java:40-158``): one consensus value then
        decides up to MAX_BATCH_SIZE client requests.  FIFO order is
        preserved; stops and already-minted batches pass through as their
        own lanes.  Mutates scheduling tables: member vids' arena/meta/
        scope move under the batch vid and their request ids repoint to it
        so the in-flight propose dedup keeps gating retransmits."""
        out: List[int] = []
        chunk: List[int] = []

        def flush() -> None:
            if len(chunk) == 1:
                out.append(chunk[0])
            elif chunk:
                subs = []
                for v in chunk:
                    entry, rid = self.vid_meta.get(v, (self.my_id, v))
                    subs.append((rid, entry, self.arena[v]))
                if self._next_counter > VID_COUNTER_MASK:
                    raise RuntimeError("vid counter space exhausted")
                bvid = (
                    BATCH_BIT
                    | (self.my_id << VID_NODE_SHIFT)
                    | self._next_counter
                )
                self._next_counter += 1
                self.arena[bvid] = encode_batch(subs)
                # batch vids carry no single request id: -1 is outside
                # every id namespace, so nothing ever dedups against it
                self.vid_meta[bvid] = (self.my_id, -1)
                self.vid_scope[bvid] = (name, epoch)
                for v in chunk:
                    self.arena.pop(v, None)
                    _e, rid = self.vid_meta.pop(v, (None, None))
                    self.vid_scope.pop(v, None)
                    if rid is not None and self.inflight.get(rid) == v:
                        self.inflight[rid] = bvid
                out.append(bvid)
            chunk.clear()

        for v in vids:
            if (v & (STOP_BIT | BATCH_BIT)) == 0:
                chunk.append(v)
                if len(chunk) >= self.max_batch_size:
                    flush()
            else:
                flush()
                out.append(v)
        flush()
        return out

    def build_requests(self) -> np.ndarray:
        """Single-step [G, K] lanes (the n_steps=1 face of the ring)."""
        return self.build_request_ring(1)[0]

    def build_request_ring(self, n_steps: int) -> np.ndarray:
        """Drain queues into the [n_steps, G, K] device request ring —
        slab i feeds dispatch substep i, so one host admission pass
        covers N engine steps; forward non-coordinated groups' requests
        to their believed coordinator.  Records the staged vid count for
        the ``device_queue_depth`` gauge."""
        G, K = self.cfg.n_groups, self.cfg.req_lanes
        depth = K * n_steps
        req = np.full((n_steps, G, K), NULL, np.int32)
        staged = 0
        bal = self._np("bal")
        for row, vids in list(self.queues.items()):
            if not vids:
                continue
            if row in self.pending_rows:
                # pre-COMPLETE epoch: hold (don't admit, don't forward) —
                # nothing may commit on a row the reconfigurator's probe
                # may still move; the queue drains once epoch_commit lands
                continue
            if row in self.hydrating_rows:
                # un-hydrated name with live traffic: hold admission and
                # promote it to the front of the hydration queue — the
                # held requests drain the moment its app state lands
                if self.hydrator is not None:
                    name = self.row_name.get(row)
                    if name is not None:
                        self.hydrator.request(name)
                continue
            vids = self._filter_stale_vids(row, vids)
            if not vids:
                continue
            coord = int(ballot_coord(int(bal[row])))
            if coord != self.my_id:
                name = self.row_name.get(row)
                if name is None:
                    vids.clear()
                    continue
                epoch_now = int(self._np("version")[row])
                # ONE forward_batch frame per row per tick (at capacity a
                # per-request forward frame was one json encode + syscall
                # + decode + singleton propose EACH — the non-coordinator
                # entry's whole budget); the coordinator re-proposes the
                # list under one lock acquisition
                reqs = []
                for vid in vids:
                    # _filter_stale_vids (just above, same lock) guarantees
                    # every kept vid has its payload in the arena
                    if vid & BATCH_BIT:
                        # a preemption re-queued this batch onto a row we
                        # no longer coordinate: unbundle and forward the
                        # members — the new coordinator re-coalesces them
                        # under its own vid space
                        for rid, entry, value in decode_batch(self.arena[vid]):
                            reqs.append([rid, entry, value, False])
                    else:
                        entry, rid = self.vid_meta.get(vid, (self.my_id, vid))
                        reqs.append(
                            [rid, entry, self.arena[vid],
                             bool(vid & STOP_BIT)]
                        )
                    # the coordinator re-mints its own vid; our local copy
                    # would only go stale (the callback stays in
                    # self.outstanding keyed by request_id)
                    self.arena.pop(vid, None)
                    self.vid_meta.pop(vid, None)
                    self.vid_scope.pop(vid, None)
                if reqs:
                    # traced requests carry their context to the
                    # coordinator, hop-incremented (one process boundary)
                    fwd_tc = {}
                    tcm = self.trace_ctx
                    for rid, _e, _v, _s in reqs:
                        tc = tcm.get(rid) if tcm else None
                        if tc is not None:
                            fwd_tc[str(rid)] = [tc[0], tc[1], tc[2] + 1]
                        if self.tracer.enabled or tc is not None:
                            self.tracer.note(
                                rid, "forward-out", name=name,
                                node=self.my_id, to=coord,
                                force=tc is not None,
                                **self._tc_detail(tc),
                            )
                    body = {
                        "name": name, "epoch": epoch_now, "reqs": reqs,
                    }
                    if fwd_tc:
                        body["tc"] = fwd_tc
                    self.forward_out.append(
                        (coord, "forward_batch", body)
                    )
                vids.clear()
                continue
            if self.batching_enabled and len(vids) > max(
                depth, self.min_batch_trigger - 1
            ):
                name = self.row_name.get(row)
                if name is not None:
                    vids = self.queues[row] = self._coalesce_row_queue(
                        row, name, int(self._np("version")[row]), vids
                    )
            take = vids[:depth]
            for off in range(0, len(take), K):
                slab = take[off:off + K]
                req[off // K, row, : len(slab)] = slab
            staged += len(take)
        self._last_ring_depth = staged
        return req

    def tick(
        self,
        gathered: Blob,
        heard: np.ndarray,
        want_coord: Optional[np.ndarray] = None,
    ) -> Tuple[Blob, Dict]:
        """One full cycle; returns (my fresh blob, host-channel delta).

        Holds the manager lock for the whole cycle: the transport-thread
        entry points (propose / on_host_message / create / kill) mutate
        the same queues, arena, and vid tables this reads and rewrites.
        User callbacks collected during execution fire AFTER the lock is
        released (a blocking callback must not wedge transport threads)."""
        with self._state_lock:
            result = self._tick_locked(gathered, heard, want_coord)
            fired, self._fired_callbacks = self._fired_callbacks, []
        for cb, rid, resp in fired:
            cb(rid, resp)
        return result

    def tick_host(
        self,
        gathered_vec: np.ndarray,
        heard: np.ndarray,
        want_coord: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, "EngineState", Dict]:
        """Packed-I/O tick for the deployed socket runtime: `gathered_vec`
        is the [R, N] stack of packed peer blob vectors (== the `D` wire
        frame bodies); returns (my fresh packed blob vector, the state it
        reflects — for identity-based staleness checks, captured under
        the lock so lifecycle ops can't mispair them — and the host
        delta).  One device upload + two downloads per tick instead of
        ~50 per-leaf dispatches — at loopback scale the per-leaf dispatch
        overhead was most of a node's tick cost."""
        with self._state_lock:
            result = self._tick_host_locked(gathered_vec, heard, want_coord)
            fired, self._fired_callbacks = self._fired_callbacks, []
        for cb, rid, resp in fired:
            cb(rid, resp)
        return result

    # ------------------------------------------------------------------
    # double-buffered dispatch (the serving pipeline's step entry):
    # step_dispatch admits batch N and fires the jitted step WITHOUT
    # waiting for the device; the caller then does host-side codec /
    # publish work while the ~1ms step runs, and step_complete syncs +
    # runs the post-step host cycle.  Transport threads frame, decode,
    # and admit batch N+1 throughout (the lock is free during the sync).
    # Step-for-step state-identical to tick_host (tests/test_pipeline.py).
    # ------------------------------------------------------------------
    def _await_step_locked(self) -> None:
        """Wait (lock held; CV releases it) until no step is in flight.
        Called at the TOP of every op that replaces engine state or
        depends on post-step bookkeeping — such ops must observe a fully
        completed tick, exactly as under the serial path.

        No-op for the thread that OWNS the in-flight step: by the time
        it runs post-step host work (checkpoint cadence, stop hooks) the
        device sync already happened, so it always sees complete state —
        and waiting would deadlock it on its own completion (the durable
        probe found exactly that: the first checkpoint-cadence fire
        inside step_complete wedged the node)."""
        while self._step_inflight and \
                self._step_thread != threading.get_ident():
            self._step_cv.wait()

    def step_dispatch(
        self,
        gathered_vec: np.ndarray,
        heard: np.ndarray,
        want_coord: Optional[np.ndarray] = None,
    ) -> Dict:
        """Admit + dispatch one engine step; returns the pending handle
        for :meth:`step_complete`.  The returned device values are NOT
        synced — self.state already points at the in-flight result (any
        reader that np.asarray's it simply blocks until the device is
        done, which is correct but serializing; the hot propose path
        avoids that via the carried lifecycle-leaf cache below)."""
        with self._state_lock:
            self._await_step_locked()  # single-depth pipeline
            cfg = self.cfg
            G = cfg.n_groups
            req = self.build_request_ring(self.steps_per_dispatch)
            wc = (
                np.zeros((G,), bool) if want_coord is None
                else np.asarray(want_coord, bool)
            )
            old_state = self.state
            # Carry the lifecycle-owned leaves' host cache across the
            # swap: the step passes version/member_mask/majority/tag
            # through UNCHANGED (ops/engine.py keeps them), and the
            # transport-thread propose/admission path reads them during
            # the overlap window — a cache miss there would block on the
            # device sync and re-serialize exactly what the pipeline
            # exists to overlap.  Copies are taken BEFORE the jit call:
            # the step donates old_state's buffers.
            carry: Dict[str, np.ndarray] = {}
            if self._np_cache_state is old_state:
                for leaf in ("version", "member_mask", "majority", "tag"):
                    arr = self._np_cache.get(leaf)
                    if arr is not None:
                        carry[leaf] = arr
            for leaf in ("version", "member_mask"):
                if leaf not in carry:
                    arr = np.asarray(getattr(old_state, leaf))
                    carry[leaf] = arr.copy() if arr.base is not None else arr
            t0 = time.monotonic()
            new_state, out_vec, blob_vec, new_heat = self._dispatch_step(
                old_state, jnp.asarray(gathered_vec), jnp.asarray(heard),
                jnp.asarray(req), jnp.asarray(wc), jnp.int32(self.my_id),
                self._heat_dev,
            )
            self.state = new_state
            self._heat_dev = new_heat
            self._np_cache = carry
            self._np_cache_state = new_state
            self._step_inflight = True
            self._step_thread = threading.get_ident()
            return {
                "out_vec": out_vec, "blob_vec": blob_vec,
                "state": new_state, "t0": t0,
            }

    def step_complete(
        self, pend: Dict
    ) -> Tuple[np.ndarray, "EngineState", Dict]:
        """Sync the in-flight step and run the post-step host cycle;
        returns (packed publish vector, the state it reflects, host
        delta) — the same triple as :meth:`tick_host`."""
        # device sync OUTSIDE the lock: np.asarray blocks with the GIL
        # released, so transport threads run the ingress/codec path
        # against the still-valid carried caches while the device works
        out_np_vec = np.asarray(pend["out_vec"])
        blob_vec = np.asarray(pend["blob_vec"])
        t0 = pend["t0"]
        with self._state_lock:
            try:
                DelayProfiler.update_delay("engine_step", t0)
                self.last_engine_step_s = time.monotonic() - t0
                DelayProfiler.update_count(
                    "t_engine_step", self.last_engine_step_s
                )
                outs = [split_out_vec(row, self.cfg) for row in out_np_vec]
                host_delta = self._post_step_locked(outs)
            finally:
                self._step_inflight = False
                self._step_thread = None
                self._step_cv.notify_all()
            fired, self._fired_callbacks = self._fired_callbacks, []
        for cb, rid, resp in fired:
            cb(rid, resp)
        return blob_vec, pend["state"], host_delta

    def _tick_host_locked(
        self,
        gathered_vec: np.ndarray,
        heard: np.ndarray,
        want_coord: Optional[np.ndarray],
    ) -> Tuple[np.ndarray, Dict]:
        self._await_step_locked()
        cfg = self.cfg
        G = cfg.n_groups
        req = self.build_request_ring(self.steps_per_dispatch)
        wc = (
            np.zeros((G,), bool) if want_coord is None
            else np.asarray(want_coord, bool)
        )
        t0 = time.monotonic()
        new_state, out_vec, blob_vec, new_heat = self._dispatch_step(
            self.state, jnp.asarray(gathered_vec), jnp.asarray(heard),
            jnp.asarray(req), jnp.asarray(wc), jnp.int32(self.my_id),
            self._heat_dev,
        )
        self.state = new_state
        self._heat_dev = new_heat
        out_np_vec = np.asarray(out_vec)  # one transfer; forces the sync
        DelayProfiler.update_delay("engine_step", t0)
        self.last_engine_step_s = time.monotonic() - t0
        DelayProfiler.update_count("t_engine_step", self.last_engine_step_s)
        outs = [split_out_vec(row, cfg) for row in out_np_vec]
        host_delta = self._post_step_locked(outs)
        return np.asarray(blob_vec), new_state, host_delta

    def _tick_locked(
        self,
        gathered: Blob,
        heard: np.ndarray,
        want_coord: Optional[np.ndarray] = None,
    ) -> Tuple[Blob, Dict]:
        self._await_step_locked()
        cfg = self.cfg
        G = cfg.n_groups
        req = self.build_request_ring(self.steps_per_dispatch)
        wc = (
            np.zeros((G,), bool) if want_coord is None
            else np.asarray(want_coord, bool)
        )
        # the Blob-of-leaves exchange reaches the unified packed step as
        # one [R, NB] matrix (each row == pack_blob of that replica);
        # donate=False — the test-cluster harness caches blob views that
        # alias the live state across ticks
        gvec = _pack_rows_jit(gathered)
        t0 = time.monotonic()
        new_state, out_vec, blob_vec, new_heat = self._tick_step(
            self.state, gvec, jnp.asarray(heard),
            jnp.asarray(req), jnp.asarray(wc), jnp.int32(self.my_id),
            self._heat_dev,
        )
        self.state = new_state
        self._heat_dev = new_heat
        out_np_vec = np.asarray(out_vec)  # one transfer; forces the sync
        # update_delay takes the START time (it computes monotonic()-t0)
        DelayProfiler.update_delay("engine_step", t0)
        self.last_engine_step_s = time.monotonic() - t0
        DelayProfiler.update_count("t_engine_step", self.last_engine_step_s)

        outs = [split_out_vec(row, cfg) for row in out_np_vec]
        host_delta = self._post_step_locked(outs)
        return split_blob_vec(np.asarray(blob_vec), cfg), host_delta

    def _post_step_locked(self, outs) -> Dict:
        """Shared post-engine host work (requeue, watermarks, journaling,
        execution, state pulls, gossip delta) for every tick flavor.

        ``outs`` is the dispatch's LIST of per-substep StepOutputs (a
        bare StepOutputs is accepted as a 1-list) — one host cycle per
        dispatch covers all N device-resident substeps: per-substep work
        (decision logging, execution, preempt requeue) runs in substep
        order; per-dispatch work (ballot pull, watermarks, checkpoint
        cadence, gossip delta) runs once against the final state."""
        if isinstance(outs, StepOutputs):
            outs = [outs]
        last = outs[-1]
        n_sub = len(outs)
        self._tick_no += 1
        if any(
            o.n_admitted.any() or o.n_committed.any()
            or o.acc_new.any() or o.bal_new.any()
            for o in outs
        ):
            self.last_progress_tick = self._tick_no
        # re-propose preempted requests at a fresh slot (PREEMPTED
        # analog), in substep order; appended AFTER the ring requeue
        # below so a vid preempted at substep i cannot collide with the
        # slab bookkeeping of substeps > i
        preempt_requeue = []
        for o in outs:
            pre_g, pre_l = np.nonzero(o.preempted_vid != NULL)
            for g_, l_ in zip(pre_g, pre_l):
                vid = int(o.preempted_vid[g_, l_])
                if vid in self.arena and vid not in self.retained:
                    preempt_requeue.append((int(g_), vid))
        # per-step engine metrics: aggregate counters reduced from the
        # vectorized step outputs — a few O(G) numpy sums per DISPATCH
        # (the engine step itself is ~1ms), never per-request host work
        mx = self.metrics
        n_dec = int(sum(int(o.n_committed.sum()) for o in outs))
        if n_dec:
            mx.count("decisions_executed", n_dec)
        n_admit = int(sum(int(o.n_admitted.sum()) for o in outs))
        if n_admit:
            mx.count("requests_admitted", n_admit)
        if preempt_requeue:
            mx.count("preempts", len(preempt_requeue))
        bal_rose = outs[0].bal_new
        for o in outs[1:]:
            bal_rose = bal_rose | o.bal_new
        flips = rises = 0
        if bal_rose.any():
            # coordinator flips: `bal` is only pulled host-side on the
            # rare dispatches where a promised ballot rose (elections),
            # and only the risen rows are compared against the cached
            # view; the pull reflects the dispatch-final state
            pg_m = np.nonzero(bal_rose)[0]
            bal_host = self._np("bal")
            self._bal_host = bal_host.copy()
            new_coord = ballot_coord(bal_host[pg_m]).astype(np.int32)
            flips = int((new_coord != self._coord_cache[pg_m]).sum())
            if flips:
                mx.count("coordinator_flips", flips)
            self._coord_cache[pg_m] = new_coord
            rises = len(pg_m)
            mx.count("ballot_rises", rises)
        mx.gauge("frontier_stall_groups", len(self._payload_blocked))
        mx.gauge("inflight_requests", len(self.inflight))
        mx.gauge("arena_payloads", len(self.arena))
        mx.observe("engine_step_s", self.last_engine_step_s)
        # residency plane: steps amortized per host dispatch, staged
        # device-ring depth, and the per-substep amortized host cost
        mx.count("host_dispatches")
        mx.gauge("dispatch_steps_per_host", n_sub)
        mx.gauge("device_queue_depth", self._last_ring_depth)
        mx.observe(
            "dispatch_amortized_s", self.last_engine_step_s / n_sub
        )
        # retrace sentinel: fold the shared sentinels' totals into this
        # node's counters as deltas (attribute reads only — no device
        # traffic), and mark them warm after the first completed
        # dispatch.  A retrace after warmup is the recompile analog of a
        # stray hot-path _np pull: it still WORKS, ~100x slower — so it
        # is shouted into the log, not just a metric
        n_c = self._dispatch_step.n_compiles + self._tick_step.n_compiles
        n_r = self._dispatch_step.n_retraces + self._tick_step.n_retraces
        if n_c != self._compile_seen:
            mx.count("engine_compiles", n_c - self._compile_seen)
            self._compile_seen = n_c
        if n_r != self._retrace_seen:
            mx.count("engine_retraces", n_r - self._retrace_seen)
            self._retrace_seen = n_r
            self.log.error(
                "engine step RETRACED after warmup (%d total): %s",
                n_r, self._dispatch_step.stats(),
            )
        if not self._dispatch_step.warm:
            self._dispatch_step.mark_warm()
            self._tick_step.mark_warm()
        # flight recorder: the per-step summary ring (always on; skips
        # pure-idle ticks internally so the ring spans real history)
        self.flight.record_step(
            tick=self._tick_no, admitted=n_admit, decided=n_dec,
            preempts=len(preempt_requeue), coordinator_flips=flips,
            ballot_rises=rises,
            frontier_stalls=len(self._payload_blocked),
            inflight=len(self.inflight),
        )
        # payload-retention watermark: min APP-execution cursor over all
        # group members (device frontiers can run ahead of payload-gated
        # app execution — GC'ing on them would strand a parked peer).
        # Peer cursors arrive by host-channel gossip; unheard-from peers
        # hold the watermark down until they gossip (a long-dead member
        # is eventually bypassed via checkpoint transfer, not GC).
        mask = self._np("member_mask")
        R = self.cfg.n_replicas
        rids = np.arange(R)
        in_group = ((mask[None, :] >> rids[:, None]) & 1) == 1
        cursors = np.stack([
            self.peer_app_exec.get(r, self._zero_cursors)
            if r != self.my_id else self.app_exec_slot
            for r in range(R)
        ])
        # A member more than JUMP_HORIZON behind the majority frontier no
        # longer holds the payload-retention watermark down: it can never
        # catch up through the rings and will recover via checkpoint
        # transfer instead (state_request/state_reply below) — without
        # this, one dead member pins every payload forever.
        horizon = last.maj_exec.astype(np.int64) - self.jump_horizon
        eligible = in_group & (cursors >= horizon[None, :])
        cur_masked = np.where(eligible, cursors, np.iinfo(np.int64).max)
        self._min_exec = np.where(
            eligible.any(axis=0), cur_masked.min(axis=0), self._min_exec
        )
        # requeue what wasn't admitted: the ring staged queue slab i into
        # substep i's lanes, and the engine admits a contiguous prefix
        # per slab — admitted = union of slab prefixes, leftovers keep
        # their order ahead of the unstaged tail
        K = self.cfg.req_lanes
        payload_delta: Dict[int, str] = {}
        meta_delta: Dict[int, Tuple[int, int]] = {}
        for row, vids in list(self.queues.items()):
            if not vids:
                continue
            admitted: List[int] = []
            rest: List[int] = []
            for i, o in enumerate(outs):
                slab = vids[i * K:(i + 1) * K]
                na = int(o.n_admitted[row])
                admitted += slab[:na]
                rest += slab[na:]
            rest += vids[n_sub * K:]
            self.queues[row] = rest
            for vid in admitted:
                payload_delta[vid] = self.arena.get(vid, "")
                if vid in self.vid_meta:
                    meta_delta[vid] = self.vid_meta[vid]
        for row, vid in preempt_requeue:
            self.queues.setdefault(row, []).append(vid)

        # log-before-send: persist the promise + accept delta before the
        # blob leaves (bare promises too — a ballot that rose with no
        # accept must survive a crash, ADVICE r1 high / handlePrepare's
        # LogMessagingTask rule).  The whole tick's blocks (including the
        # decision log inside _execute) leave as ONE group commit
        # (BatchedLogger analog) — flushed before this function returns,
        # so log-before-send still holds for the published blob.
        if self.logger is not None:
            with self.logger.batch():
                pg = np.nonzero(bal_rose)[0]
                if len(pg):
                    bal_np = self._np("bal")
                    self.logger.log_promises(pg.astype(np.int32), bal_np[pg])
                # accept lanes changed by ANY substep, valued from the
                # dispatch-final state: a lane overwritten by a LATER
                # substep's accept implies its earlier slot was decided
                # within this dispatch, and that decision is journaled
                # per substep by _execute below — so the final lane view
                # plus the per-substep decision log loses nothing
                acc_any = outs[0].acc_new
                for o in outs[1:]:
                    acc_any = acc_any | o.acc_new
                gs, lanes = np.nonzero(acc_any)
                if len(gs):
                    acc_slot = self._np("acc_slot")
                    acc_bal = self._np("acc_bal")
                    acc_vid = self._np("acc_vid")
                    self.logger.log_accepts(
                        gs.astype(np.int32),
                        acc_slot[gs, lanes],
                        acc_bal[gs, lanes],
                        acc_vid[gs, lanes],
                    )
                if payload_delta:
                    self.logger.log_payloads(payload_delta, meta=meta_delta)
                for o in outs:
                    self._execute(o)
        else:
            for o in outs:
                self._execute(o)
        self._maybe_request_state(last)
        self.outstanding.gc()
        if self._tick_no % 64 == 0 and self.inflight:
            # entries whose vid left vid_meta (forwarded to a coordinator /
            # GC'd) no longer gate re-proposal
            self.inflight = {
                r: v for r, v in self.inflight.items() if v in self.vid_meta
            }
        self._maybe_checkpoint(last)

        # periodic full-baseline refresh: a dropped gossip frame must not
        # strand peers' cursor views forever (the sparse delta has no
        # pull/heal path of its own) — O(live groups), not O(G)
        if self._tick_no % 256 == 0:
            self._app_exec_dirty.update(self.names.values())
            self._app_exec_dirty.update(self.old_epochs.values())
        dirty, self._app_exec_dirty = self._app_exec_dirty, set()
        host_delta = {
            "arena": payload_delta,
            "meta": {k: list(v) for k, v in meta_delta.items()},
            "app_exec": (self.my_id, {
                int(g): int(self.app_exec_slot[g]) for g in dirty
            }),
        }
        if self._tc_gossip:
            # sampled requests' trace contexts ride the payloads frame
            # once (drain): peers stamp their decide/execute events with
            # the shared trace id
            tc_out, self._tc_gossip = self._tc_gossip, {}
            host_delta["tc"] = {
                str(rid): list(tc) for rid, tc in tc_out.items()
            }
        return host_delta

    # ------------------------------------------------------------------
    # execution (EEC analog, PaxosInstanceStateMachine.java:1511-1734)
    # ------------------------------------------------------------------
    def _execute(self, out_np) -> None:
        committed = np.nonzero(out_np.n_committed)[0]
        if self.logger is not None and len(committed):
            t_j = time.monotonic()
            rows, slots, vids = [], [], []
            for g in committed:
                base = int(out_np.exec_base[g])
                for o in range(int(out_np.n_committed[g])):
                    rows.append(g)
                    slots.append(base + o)
                    vids.append(int(out_np.exec_vid[g, o]))
            self.logger.log_decisions(
                np.array(rows, np.int32), np.array(slots, np.int32),
                np.array(vids, np.int32),
            )
            DelayProfiler.update_count("t_journal", time.monotonic() - t_j)
        if len(committed):
            self.row_activity[committed] = time.time()
        tr = self.tracer
        tcm = self.trace_ctx
        # ballot attribution for decide events + the flight recorder's
        # decided ring comes from the rise-tick host view (_bal_host) —
        # pulling `bal` from the device per commit tick costs a sync
        # that measurably perturbs soak timing
        bal_np = self._bal_host
        for g in committed:
            base = int(out_np.exec_base[g])
            bal_g = int(bal_np[g])
            pend = self.pending_exec.setdefault(int(g), {})
            for o in range(int(out_np.n_committed[g])):
                vid = int(out_np.exec_vid[g, o])
                pend[base + o] = vid
                self.flight.record_decided(int(g), base + o, bal_g, vid)
                if vid == 0:
                    continue
                meta = self.vid_meta.get(vid)
                key = vid if meta is None or meta[1] == -1 else meta[1]
                tc = tcm.get(key) if tcm else None
                if tr.enabled or tc is not None:
                    tr.note(
                        key, "decide", name=self.row_name.get(int(g)),
                        node=self.my_id, row=int(g), slot=base + o,
                        vid=vid, ballot=bal_g,
                        force=tc is not None, **self._tc_detail(tc),
                    )
        t_exec = time.monotonic()
        missing = self._drain_pending_exec()
        DelayProfiler.update_delay("app_execute", t_exec)
        dt_exec = time.monotonic() - t_exec
        DelayProfiler.update_count("t_app_execute", dt_exec)
        if len(committed):
            # per-phase latency distribution (SLO surface): the decided-
            # slot execution leg of a tick, exported via /metrics + stats
            self.metrics.observe("phase_execute_s", dt_exec)
        if missing:
            self.forward_out.append(
                (-1, "need_payloads", SyncDecisionsPacket(
                    node_id=self.my_id, missing=missing,
                    is_missing_too_much=len(missing) > self.sync_threshold,
                ).to_json())
            )
        # retention GC: drop payloads every live member has executed past
        if self._tick_no % 32 == 0 and self.retained:
            for vid, (g, slot) in list(self.retained.items()):
                if slot < self._min_exec[g]:
                    del self.retained[vid]
                    self.arena.pop(vid, None)
                    self.vid_meta.pop(vid, None)
                    self.vid_scope.pop(vid, None)

    def _drain_pending_exec(self) -> List[int]:
        """Execute decided slots in order through the app, payload-gated;
        returns vids whose payloads are missing (to pull from peers)."""
        missing: List[int] = []
        for g in list(self.pending_exec.keys()):
            if g in self.hydrating_rows:
                # recovery plane: executing decided slots against the
                # not-yet-restored app state would diverge the RSM —
                # park until the hydrator restores this row, then the
                # next drain (the hydrator runs one itself) catches up
                if self.hydrator is not None:
                    name = self.row_name.get(g)
                    if name is not None:
                        self.hydrator.request(name)
                continue
            if g in self._needs_state:
                # blank join awaiting a donor's app state (commit-heal
                # resumed this member before its epoch-final-state fetch
                # landed): executing decided slots against the EMPTY
                # state would emit wrong responses/entry callbacks that
                # the later state adoption cannot retract — park until
                # the needs_state pull (fired every tick by
                # _maybe_request_state) delivers the state
                continue
            pend = self.pending_exec[g]
            name = self.row_name.get(g)
            cursor = int(self.app_exec_slot[g])
            blocked = False
            while cursor in pend:
                vid = pend[cursor]
                if not self._execute_one(name, g, cursor, vid):
                    missing.append(vid)
                    blocked = True
                    break  # payload not here yet; pull + retry next tick
                del pend[cursor]
                cursor += 1
            if cursor != int(self.app_exec_slot[g]):
                self.app_exec_slot[g] = cursor
                self._app_exec_dirty.add(g)
            if blocked:
                # (re)start the timer whenever the parked SLOT changes:
                # only a cursor truly stuck at one slot should trip the
                # pull — a straggler making net progress through payload
                # pulls is healing normally
                ent = self._payload_blocked.get(g)
                if ent is None or ent[1] != cursor:
                    self._payload_blocked[g] = (self._tick_no, cursor)
            else:
                self._payload_blocked.pop(g, None)
            if not pend:
                del self.pending_exec[g]
        return missing

    def _app_execute_retrying(self, req, do_not_reply: bool) -> None:
        """Retry-forever execute (``PaxosInstanceStateMachine.java:
        1647-1734``): a deterministic app must eventually execute a decided
        request — giving up would silently skip a slot and diverge the
        RSM, so the only alternatives are retry or wedge.  Backoff grows
        1ms -> 100ms; sustained failure surfaces loudly (DelayProfiler
        counter at /stats + a periodic WARNING log line) instead of
        raising into the tick loop."""
        delay = 0.001
        attempt = 0
        while True:
            try:
                if self.app.execute(req, do_not_reply_to_client=do_not_reply):
                    return
            except Exception:
                pass
            attempt += 1
            DelayProfiler.update_count("app_execute_retries")
            if attempt in (10, 100) or attempt % 1000 == 0:
                self.log.warning(
                    "app refusing to execute %s#%s (%d attempts); "
                    "retrying forever (node is wedged until it succeeds)",
                    req.paxos_id, req.request_id, attempt,
                )
            time.sleep(delay)
            delay = min(delay * 2, 0.1)

    def _cache_response(self, request_id: int, response: Optional[str],
                        name: str) -> None:
        self.response_cache[request_id] = (time.time(), response, name)
        if len(self.response_cache) > self.response_cache_cap:
            self._evict_response_cache()

    @staticmethod
    def _cacheable(req) -> bool:
        """False for RETRYABLE refusals (``req.txn_retry``, set by the
        transaction plane when a request bounces off a locked group):
        caching one would freeze the refusal under exactly-once dedup
        and the same request id could never succeed after the lock
        clears.  Deterministic across replicas — the refusal is computed
        from replicated lock state and mutates nothing, so every member
        skips the cache for the same decided entry."""
        return not getattr(req, "txn_retry", False)

    def _evict_response_cache(self) -> None:
        """Size bound (RESPONSE_CACHE_SIZE analog): evict the oldest
        tenth so the cache (and its state-transfer ride-along) stays
        bounded under sustained load between checkpoint GCs.  Eviction
        is per-node (like the reference's time+size-GC'd
        GCConcurrentHashMap): exactly-once is guaranteed within the
        TTL/size window, not beyond it.

        Evicts the INSERTION-ORDER head: entries land with a fresh
        timestamp, so dict order ≈ age order (a restored/installed
        older entry can be slightly mis-ranked — the window is a
        heuristic either way).  The previous full timestamp sort was
        O(cap·log cap) per eviction — sampling-profiled at ~25% of a
        loaded core at 20k req/s across three replicas."""
        n = max(1, len(self.response_cache) // 10)
        for rid in list(itertools.islice(self.response_cache, n)):
            del self.response_cache[rid]

    def _execute_one(self, name: Optional[str], g: int, slot: int, vid: int) -> bool:
        if vid == 0:  # NOOP hole-filler: nothing to execute
            return True
        payload = self.arena.get(vid)
        if payload is None:
            return False
        if vid & BATCH_BIT:
            # one decided slot carrying an ordered batch of client
            # requests: unpack and run each through the app.  Every
            # replica decodes the same payload in the same order, and the
            # per-sub-request dedup decision is deterministic across the
            # group (same decided sequence, same earlier executions), so
            # the RSM stays convergent.  Hot loop: the clock and the
            # cache size-bound check amortize once per BATCH (at 2000
            # sub-requests/slot the per-request constants here are the
            # replica's whole execution budget).
            now = time.time()
            rc = self.response_cache
            nm = name or ""
            my = self.my_id
            tr_on = self.tracer.enabled
            for request_id, entry, value in decode_batch(payload):
                if request_id in rc:
                    if entry == my:
                        cb = self.outstanding.pop(request_id)
                        if cb is not None:
                            self._fired_callbacks.append(
                                (cb, request_id, rc[request_id][1])
                            )
                    continue
                req = SlimRequest(nm, request_id, value)
                self._app_execute_retrying(req, do_not_reply=(entry != my))
                self.total_executed += 1
                tc = self.trace_ctx.get(request_id) \
                    if self.trace_ctx else None
                if tr_on or tc is not None:
                    self.tracer.note(request_id, "execute", name=nm,
                                     node=my, row=g, slot=slot, batch=True,
                                     force=tc is not None,
                                     **self._tc_detail(tc))
                self.inflight.pop(request_id, None)
                response = req.response_value
                if self._cacheable(req):
                    rc[request_id] = (now, response, nm)
                if entry == my:
                    cb = self.outstanding.pop(request_id)
                    if cb is not None:
                        self._fired_callbacks.append(
                            (cb, request_id, response)
                        )
            if len(rc) > self.response_cache_cap:
                self._evict_response_cache()
            self._slots_since_ckpt += 1
            self.retained[vid] = (g, slot)
            return True
        entry, request_id = self.vid_meta.get(vid, (-1, vid))
        if request_id in self.response_cache:
            # duplicate of an already-executed request (client retransmit
            # through a different entry replica): skip re-execution on
            # EVERY replica — deterministic, since all see the same
            # decided sequence and the same earlier execution.
            if entry == self.my_id:
                cb = self.outstanding.pop(request_id)
                if cb is not None:
                    self._fired_callbacks.append(
                        (cb, request_id, self.response_cache[request_id][1])
                    )
            self.retained[vid] = (g, slot)
            return True
        req = SlimRequest(
            name or "", request_id, payload, stop=bool(vid & STOP_BIT)
        )
        self._app_execute_retrying(req, do_not_reply=(entry != self.my_id))
        self.total_executed += 1
        tc = self.trace_ctx.get(request_id) if self.trace_ctx else None
        if self.tracer.enabled or tc is not None:
            self.tracer.note(request_id, "execute", name=name or "",
                             node=self.my_id, row=g, slot=slot,
                             stop=bool(vid & STOP_BIT),
                             force=tc is not None, **self._tc_detail(tc))
        self._slots_since_ckpt += 1
        self.inflight.pop(request_id, None)
        response = getattr(req, "response_value", None)
        # cache BEFORE the stop hook: the hook snapshots (app state,
        # dedup set) as the epoch-final handoff pair, and the app state
        # it captures INCLUDES this stop execution — a snapshot whose
        # dedup set lacks the stop's own entry is an inconsistent pair
        # (chaos-sweep forensics: every breach diff was missing exactly
        # one epoch-final stop id)
        if self._cacheable(req):
            self._cache_response(request_id, response, name or "")
        if (vid & STOP_BIT) and self.on_stop_executed is not None and name:
            epoch = int(self._np("version")[g])
            try:
                self.on_stop_executed(name, g, epoch)
            except Exception:
                pass  # reconfiguration-layer hook must not wedge execution
        if entry == self.my_id:
            cb = self.outstanding.pop(request_id)
            if cb is not None:
                self._fired_callbacks.append((cb, request_id, response))
        self.retained[vid] = (g, slot)  # keep for straggler pulls
        return True

    # ------------------------------------------------------------------
    # THE data-plane straggler sync protocol — the one heal path for
    # every way a member falls behind, mirroring the reference's single
    # sync state machine (detect stall -> request missing decisions ->
    # checkpoint transfer if too far behind,
    # PaxosInstanceStateMachine.java:2161-2340; StatePacket /
    # handleCheckpoint:1744; jumpSlot, PaxosAcceptor.java:538).  Missing
    # DECISIONS within the window heal through the blob rings + payload
    # pulls (need_payloads); everything beyond heals here: detection
    # (_maybe_request_state) -> state_request to a rotated donor ->
    # _apply_state_reply (full checkpoint jump, small-gap jump once
    # provably stalled, or app-cursor adoption).  The control-plane
    # sibling for stranded EPOCH forms (pause records, pending rows) is
    # the reconfigurator's epoch_probe.
    # ------------------------------------------------------------------
    STATE_REQ_INTERVAL = 16  # ticks between pulls for the same row
    PAYLOAD_BLOCKED_TICKS = 64  # parked-on-missing-payload pull trigger
    FRONTIER_STALLED_TICKS = 64  # behind-majority-without-progress trigger

    def _maybe_request_state(self, out_np) -> None:
        """Detect rows needing a state pull: (a) device frontier stranded
        beyond the ring window — the decisions it needs left every peer's
        [G, W] ring (the SyncDecisionsPacket 'isMissingTooMuch' case), or
        (b) the APP cursor stranded behind the local device frontier past
        the retention horizon — the payloads it needs were GC'd everywhere
        (only the app state + cursor need transfer, not an engine jump),
        or (c) the cursor parked on a missing payload for many ticks at
        ANY gap size — a short-history group whose payloads were GC'd
        before this member joined fits under both horizons yet can never
        execute its way forward, or (d) the device frontier strictly
        behind the majority with NO progress for many ticks at ANY gap —
        the needed decisions can leave every peer's window entirely (a
        majority that paused+resumed keeps only >= frontier remnants),
        and a row in this state must heal by a (small-gap) jump."""
        W = self.cfg.window
        # post-step frontier derived from the step outputs (exec_base +
        # newly executed) — the profiler caught the per-tick
        # _np("exec_slot") device pull at ~4% of a loaded core, paid on
        # EVERY tick for a detector that almost never fires
        exec_np = (
            out_np.exec_base.astype(np.int64)
            + out_np.n_committed.astype(np.int64)
        )
        behind_dev = (out_np.maj_exec - exec_np) > W
        behind_app = (exec_np - self.app_exec_slot) > self.jump_horizon
        need = behind_dev | behind_app
        for g, (t0, _slot) in self._payload_blocked.items():
            if self._tick_no - t0 > self.PAYLOAD_BLOCKED_TICKS:
                need[g] = True
        # (d) frontier-stalled tracking, vectorized: (re)arm whenever the
        # stalled SLOT changes; rows making progress or caught up disarm.
        # Behind is measured against the MAX known frontier (own device
        # frontier vs every peer's gossiped app cursor), not the majority
        # frontier: the chaos soak found the inverted shape too — a
        # MAJORITY stranded behind one resumed member, where maj_exec
        # equals the stragglers' own frontier and a majority-based
        # detector never fires (yet only that one member can donate the
        # decisions, which left every window).
        mask_np = self._np("member_mask")
        peak = np.maximum(
            exec_np.astype(np.int64), out_np.maj_exec.astype(np.int64)
        )
        for r, arr in self.peer_app_exec.items():
            in_grp = ((mask_np >> r) & 1) == 1
            peak = np.maximum(peak, np.where(in_grp, arr, 0))
        behind = peak > exec_np
        rearm = behind & (self._stall_slot != exec_np)
        self._stall_since = np.where(
            rearm, self._tick_no, np.where(behind, self._stall_since, -1)
        )
        self._stall_slot = np.where(behind, exec_np, -1)
        need |= (
            behind & (self._stall_since >= 0)
            & (self._tick_no - self._stall_since > self.FRONTIER_STALLED_TICKS)
        )
        for g in self._needs_state:
            need[g] = True
        if self.hydrating_rows:
            # un-hydrated rows LOOK app-lagged (cursor parked at the
            # checkpoint frontier by design) but need hydration, not a
            # donor pull — pulling would adopt peer state that the
            # hydrator later overwrites with the stale checkpoint copy.
            # Rows still behind after hydration pull on the next tick
            need[np.fromiter(self.hydrating_rows, np.int64)] = False
        if not need.any():
            return
        versions = self._np("version")
        masks = self._np("member_mask")
        by_dst: Dict[int, List[Dict]] = {}
        for g in np.nonzero(need)[0]:
            g = int(g)
            name = self.row_name.get(g)
            if name is None or self.names.get(name) != g:
                continue  # only current-epoch mappings pull state
            if self._tick_no - self._last_state_req.get(g, -(10 ** 9)) \
                    < self.STATE_REQ_INTERVAL:
                continue
            self._last_state_req[g] = self._tick_no
            # one donor per request, rotated across the membership so a
            # dead/lagging donor doesn't wedge the pull (and the broadcast
            # doesn't N-plicate O(cache) replies)
            members = [r for r in range(32)
                       if (int(masks[g]) >> r) & 1 and r != self.my_id]
            if not members:
                continue
            dst = members[(self._tick_no // self.STATE_REQ_INTERVAL) % len(members)]
            by_dst.setdefault(dst, []).append(
                {"row": g, "name": name, "version": int(versions[g])}
            )
        for dst, rows in by_dst.items():
            self.forward_out.append(
                (dst, "state_request", {"rows": rows, "from": self.my_id})
            )

    def _serve_state_request(self, body: Dict) -> None:
        """Serve a consistent (device frontier == app cursor) snapshot of
        each requested row; skip rows where the two disagree — the
        requester retries and another peer may be quiescent."""
        # donor snapshots pair device frontier with the app cursor: an
        # in-flight step would advance one but not (yet) the other
        self._await_step_locked()
        exec_np = self._np("exec_slot")
        states = []
        for ent in body["rows"]:
            g, name = int(ent["row"]), ent["name"]
            if self.names.get(name) != g:
                continue
            if g in self._needs_state:
                continue  # blank-joined myself: serving my empty state
                # would "heal" another blank member into blankness
            if g in self.hydrating_rows:
                continue  # un-hydrated (recovery plane): my app state is
                # still the pre-restore blank — donating it would
                # "heal" the requester into blankness too
            if int(self._np("version")[g]) != int(ent["version"]):
                continue
            frontier = int(exec_np[g])
            if int(self.app_exec_slot[g]) != frontier:
                continue  # app cursor lags the device: snapshot inconsistent
            bal = int(self._np("bal")[g])
            states.append(StatePacket(
                paxos_id=name, version=int(ent["version"]),
                ballot_num=int(ballot_num(bal)),
                ballot_coord=int(ballot_coord(bal)),
                slot=frontier, row=g,
                app_hash=int(self._np("app_hash")[g]),
                n_execd=int(self._np("n_execd")[g]),
                stopped=int(self._np("stopped")[g]),
                state=self.app.checkpoint(name),
            ).to_json())
        if states:
            # The FULL (TTL+size-bounded) response cache rides along:
            # without these entries the receiver cannot dedup a duplicate
            # decision (same request id, different vid) landing after its
            # jumped frontier — replicas that executed the first copy skip
            # it, a jumped replica would execute it and DIVERGE the RSM.
            # Filtering by the retained-payload index proved unsound: a
            # re-proposed duplicate's first execution can predate payload
            # GC, leaving the one dedup entry that matters out of the
            # filter (caught by the chaos soak).
            # entries for the SERVED names only, over their in-TTL
            # history (no dependence on payload retention), BOUNDED: a
            # hot name's cache can hold tens of thousands of entries and
            # shipping all of them makes every straggler pull O(cache)
            # (VERDICT r3 weak #5).  The newest `cap` entries per name
            # ship; older ones fall outside the same probabilistic
            # exactly-once window the per-node TTL+size eviction already
            # defines (a duplicate older than the window can re-execute
            # on ANY replica, transferred state or not).
            served = {s_["paxos_id"] for s_ in states}
            by_name: Dict[str, list] = {}
            for rid, (t, resp, nm) in self.response_cache.items():
                if nm in served:
                    by_name.setdefault(nm, []).append((t, rid, resp))
            cap = max(1024, self.response_cache_cap // 8)
            cache = {}
            for nm, ents in by_name.items():
                if len(ents) > cap:
                    ents.sort()  # oldest first; keep the newest cap
                    ents = ents[-cap:]
                for t, rid, resp in ents:
                    cache[str(rid)] = [t, resp, nm]
            self.forward_out.append(
                (body["from"], "state_reply",
                 {"states": states, "response_cache": cache})
            )

    def _apply_state_reply(
        self, states: List[Dict], response_cache: Optional[Dict] = None
    ) -> None:
        """Adopt donor frontiers for rows still stranded (jumpSlot).
        Entries are StatePacket JSON (the CHECKPOINT_STATE wire schema)."""
        from .ops.lifecycle import jump_rows

        # a state jump replaces engine rows: it must observe a COMPLETED
        # tick (an in-flight step's post-step would otherwise process
        # out_np against rows this jump just rewrote)
        self._await_step_locked()
        W = self.cfg.window
        exec_np = self._np("exec_slot")
        jumps: List[Dict] = []      # engine jump + app restore
        app_only: List[Dict] = []   # app restore only (device was current)
        states = [
            {
                "row": int(p_.row), "name": p_.paxos_id,
                "version": int(p_.version), "exec": int(p_.slot),
                "bal": int(encode_ballot(p_.ballot_num, p_.ballot_coord)),
                "app_hash": int(p_.app_hash),
                "n_execd": int(p_.n_execd),
                "stopped": int(p_.stopped),
                "app_state": p_.state,
            }
            for p_ in (StatePacket.from_json(e) for e in states)
        ]
        for ent in states:
            g, name = int(ent["row"]), ent["name"]
            if self.names.get(name) != g:
                continue
            if int(self._np("version")[g]) != int(ent["version"]):
                continue
            donor_exec = int(ent["exec"])
            my_exec = int(exec_np[g])
            stalled = (
                int(self._stall_since[g]) >= 0
                and self._tick_no - int(self._stall_since[g])
                > self.FRONTIER_STALLED_TICKS
                and int(self._stall_slot[g]) == my_exec
            )
            if donor_exec >= my_exec + W or (
                stalled and donor_exec > my_exec
            ):
                # jump clear past my ring, OR any positive gap once the
                # frontier has provably stalled (the needed decisions
                # left every peer's window — rings can't heal it).  Safe
                # at any gap: jump_rows keeps window lanes at/above the
                # adopted frontier, so no live vote is forgotten
                jumps.append(ent)
            elif donor_exec <= my_exec and (
                donor_exec > int(self.app_exec_slot[g])
                or (g in self._needs_state
                    and donor_exec >= int(self.app_exec_slot[g]))
            ):
                # device is current but the APP cursor stranded behind the
                # payload-retention horizon: adopt the donor's app state at
                # its (<= mine) frontier and resume host execution from
                # there — no engine surgery needed or safe
                app_only.append(ent)
        if not jumps and not app_only:
            return
        if jumps:
            self.state = jump_rows(
                self.state,
                np.array([e["row"] for e in jumps]),
                np.array([e["exec"] for e in jumps]),
                np.array([e["bal"] for e in jumps]),
                np.array([e["app_hash"] for e in jumps]),
                np.array([e["n_execd"] for e in jumps]),
                np.array([e["stopped"] for e in jumps]),
            )
        # install the donor's dedup entries ONLY for names whose state
        # was actually ADOPTED here: an entry is sound exactly when it is
        # paired with a state that contains its execution.  Installing a
        # served-but-not-adopted name's entries would DEDUP-SKIP this
        # member's own parked executions of those requests once their
        # payloads arrive — a truncated history with a full dedup set
        # (the chaos sweeps' remaining breach shape: identical dedup
        # sets, app_n_executed 5 vs 3 at equal frontiers).
        adopted = {e["name"] for e in jumps} | {e["name"] for e in app_only}
        self.install_dedup({
            rid: ent for rid, ent in (response_cache or {}).items()
            if str(ent[2]) in adopted
        })
        for ent in jumps:
            g = int(ent["row"])
            self.app.restore(ent["name"], ent["app_state"])
            self.app_exec_slot[g] = int(ent["exec"])
            self._app_exec_dirty.add(g)
            self.pending_exec.pop(g, None)
            self._payload_blocked.pop(g, None)
            self._stall_since[g] = -1
            self._stall_slot[g] = -1
            self._needs_state.discard(g)
            # donor state supersedes the checkpoint copy: the hydrator
            # must NOT later restore the older shard state over it
            self.hydrating_rows.discard(g)
            if int(ent["stopped"]) and self.on_stop_executed is not None:
                # the STOP decision will never execute locally (the jump
                # landed past it) — fire the hook now so the epoch layer
                # captures the final state and acks pending stops
                try:
                    self.on_stop_executed(
                        ent["name"], g, int(ent["version"])
                    )
                except Exception:
                    pass
        for ent in app_only:
            g = int(ent["row"])
            self.app.restore(ent["name"], ent["app_state"])
            self.app_exec_slot[g] = int(ent["exec"])
            self._app_exec_dirty.add(g)
            self._payload_blocked.pop(g, None)
            self._stall_since[g] = -1
            self._stall_slot[g] = -1
            self._needs_state.discard(g)
            self.hydrating_rows.discard(g)  # donor state supersedes shard
            pend = self.pending_exec.get(g)
            if pend:  # decisions at/past the adopted cursor still execute
                for slot in [s for s in pend if s < int(ent["exec"])]:
                    del pend[slot]
        # make the adoption durable at the next cadence point (debounced:
        # several replies in one burst must not each snapshot the engine);
        # until then a crash merely rewinds to a state the pull re-heals
        self._slots_since_ckpt = max(self._slots_since_ckpt, self.checkpoint_every)

    # ------------------------------------------------------------------
    # checkpointing (consistentCheckpoint analog, :1553-1615)
    # ------------------------------------------------------------------
    def _maybe_checkpoint(self, out_np) -> None:
        if self.logger is None or self._slots_since_ckpt < self.checkpoint_every:
            return
        self.checkpoint_now()

    def checkpoint_now(self) -> None:
        if self.logger is None:
            return
        if self.hydrating_rows:
            # a snapshot taken mid-hydration would persist the
            # pre-restore blank app states of every cold name as a NEWER
            # generation — catastrophic.  Defer to the next cadence
            # point; background hydration bounds the wait
            self.metrics.count("recovery_checkpoint_deferred")
            return
        with self._state_lock:
            # snapshots must capture a COMPLETED tick (engine arrays and
            # host cursors from the same cycle)
            self._await_step_locked()
        t_ck = time.monotonic()
        self._checkpoint_now_inner()
        DelayProfiler.update_delay("checkpoint", t_ck)
        DelayProfiler.update_count("t_checkpoint", time.monotonic() - t_ck)

    def _checkpoint_now_inner(self) -> None:
        # _np returns donation-safe PRIVATE host arrays (never zero-copy
        # views of the device buffers — see its docstring), so the async
        # writer can serialize them while later donated ticks overwrite
        # the device state in place; going through it also shares the
        # per-state-version cache with the hot accessors
        arrays = {k: self._np(k) for k in self.state._fields}
        app_states = {
            name: self.app.checkpoint(name) for name in self.names
        }
        # the live arena is exactly the payload set still needed by some
        # replica (pending execution locally or retained for stragglers);
        # pre-checkpoint PAYLOADS journal blocks are unreachable after this
        # snapshot's GC, so they must travel in the snapshot itself
        # app_states correspond to the APP cursor (app_exec_slot), which
        # can trail the device frontier when payloads are in flight; the
        # in-between (slot -> vid) map rides along so recovery resumes
        # execution exactly where the app state string left off.
        # checkpoint_async: every container below is a FRESH object (dict
        # comps / copies) captured under the manager lock — the writer
        # thread serializes them while the tick keeps running (a loaded
        # snapshot costs ~0.5s of json+npz+fsync; paying it in the tick
        # was the measured latency spike that failed the capacity gate)
        # recency hints for the recovery plane's hot set: rows ordered by
        # last activity, newest first (one argsort per checkpoint).  The
        # next restart hydrates these names before serving
        act = self.row_activity
        # hint enough rows to cover the configured hot budget (operators
        # can raise RECOVERY_HOT_NAMES past the floor).  argpartition,
        # not a full argsort: this runs in the tick-blocking snapshot
        # section, and O(G log G) at 1M+ rows is the same in-tick
        # latency shape the async writer exists to avoid
        cap = max(16384, Config.get_int(PC.RECOVERY_HOT_NAMES))
        cap = min(cap, len(act))
        top = np.argpartition(-act, cap - 1)[:cap] if cap else np.array([], np.int64)
        top = top[np.argsort(-act[top], kind="stable")]
        hot_rows = [
            int(r) for r in top
            if act[r] > 0 and int(r) in self.row_name
        ]
        self.logger.checkpoint_async(arrays, app_states, {
            "hot_rows": hot_rows,
            "names": dict(self.names),
            "pending_rows": sorted(self.pending_rows),
            "needs_state": sorted(self._needs_state),
            "response_cache": {
                str(rid): [t, resp, nm]
                for rid, (t, resp, nm) in self.response_cache.items()
                if t >= time.time() - self.response_cache_ttl
            },
            "paused": {
                f"{n}@{e}": rec for (n, e), rec in (
                    self.paused.peek_items()
                    if hasattr(self.paused, "peek_items")
                    else self.paused.items()
                )
            },
            "old_epochs": [[n, e, r] for (n, e), r in self.old_epochs.items()],
            "next_counter": self._next_counter,
            "arena": dict(self.arena),
            "vid_meta": {k: list(v) for k, v in self.vid_meta.items()},
            "app_exec_slot": self.app_exec_slot.tolist(),
            "pending_exec": {
                str(g): {str(s_): v for s_, v in pend.items()}
                for g, pend in self.pending_exec.items()
            },
        })
        self._slots_since_ckpt = 0
        # response-cache GC piggybacks on checkpoint cadence
        cut = time.time() - self.response_cache_ttl
        for key in [k for k in self.response_cache
                    if self.response_cache[k][0] < cut]:
            del self.response_cache[key]

    def drain_forward_out(self) -> List[Tuple[int, str, Dict]]:
        """Atomically take the pending outbound host-channel messages.
        An unlocked swap could lose a message appended by a transport
        thread between the load and the store."""
        with self._state_lock:
            out, self.forward_out = self.forward_out, []
        return out

    def blob(self) -> Blob:
        """Current publishable snapshot (what peers gather)."""
        return make_blob(self.state)

    def blob_vec(self) -> np.ndarray:
        """Packed publish vector for the current state (the wire body of
        a `D` frame); used by the socket runtime at boot and after
        lifecycle ops, before the first packed tick returns one."""
        return self.publish_snapshot()[0]

    def publish_snapshot(self) -> Tuple[np.ndarray, EngineState]:
        """(packed publish vector, the exact state it was computed from),
        captured atomically — callers caching the pair can then detect
        staleness by state identity without racing lifecycle ops."""
        with self._state_lock:
            state = self.state
            return np.asarray(_pack_blob_jit(make_blob(state))), state

    def close(self) -> None:
        if self.hydrator is not None:
            self.hydrator.stop()
        if self.logger:
            self.logger.close()
