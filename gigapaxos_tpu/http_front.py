"""HTTP front-ends for the reconfigurator and active-replica roles.

API-parity targets: ``HttpReconfigurator`` (``http/HttpReconfigurator.
java:51,79`` — netty REST for create/delete/request-actives; commands as
``{"type": "CREATE", "name": ..., "initialState": ...}``) and the fork's
``HttpActiveReplica`` (``HttpActiveReplica.java:29`` — POST app requests).

Python re-design: a stdlib ``ThreadingHTTPServer`` per role, mounted next
to the socket transport at ``port + PC.HTTP_PORT_OFFSET``.  Handlers
bridge into the same demux paths the binary protocol uses (an HTTP create
is exactly an ``rc_client`` op with the reply parked on the HTTP worker
thread), so the front-end adds no new semantics — just a wire format.

Endpoints (reconfigurator):
  GET  /?name=N                 -> request actives (also /?type=REQ_ACTIVES)
  POST / {"type": "CREATE",  "name": N, "initialState": S}
  POST / {"type": "DELETE",  "name": N}
  POST / {"type": "RECONFIGURE", "name": N, "actives": [..]}
  GET  /stats                   -> DelayProfiler + placement snapshot
  GET  /metrics                 -> RC engine registry (placement gauges)
Endpoints (active replica):
  POST / {"name": N, "request": value}   -> execute through consensus
  GET  /stats                            -> DelayProfiler snapshot
  GET  /metrics                          -> engine registry (Prometheus)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .utils.profiler import DelayProfiler

# HTTP op type -> (rc_client kind, ack kind) — HttpRequestType analog
_RC_OPS = {
    "CREATE": ("create_service", "create_ack"),
    "DELETE": ("delete_service", "delete_ack"),
    "RECONFIGURE": ("reconfigure", "reconfigure_ack"),
    "REQ_ACTIVES": ("request_actives", "actives_response"),
}


def _body_of(op_type: str, payload: Dict) -> Dict:
    name = payload["name"]
    if op_type == "CREATE":
        body = {"name": name, "initial_state": payload.get("initialState")}
        if payload.get("actives") is not None:
            body["actives"] = list(payload["actives"])
        return body
    if op_type == "RECONFIGURE":
        return {"name": name, "new_actives": list(payload["actives"])}
    return {"name": name}


class _Waiter:
    """Parks an HTTP worker thread until the layer's async reply lands."""

    def __init__(self):
        self.ev = threading.Event()
        self.reply: Optional[Dict] = None

    def __call__(self, kind: str, body: Dict) -> None:
        self.reply = {"kind": kind, "body": body}
        self.ev.set()


# shared response plumbing for BOTH role handlers (AR and RC serve the
# same /stats-/metrics exposition shapes; one copy, no drift)
def _send_json(handler: BaseHTTPRequestHandler, code: int, obj: Dict) -> None:
    _send_bytes(handler, code, json.dumps(obj).encode("utf-8"),
                "application/json")


def _send_text(handler: BaseHTTPRequestHandler, code: int, text: str) -> None:
    _send_bytes(handler, code, text.encode("utf-8"),
                "text/plain; charset=utf-8")


def _send_bytes(handler, code: int, data: bytes, ctype: str) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)


def _metrics_body(metrics: Optional[Callable[[], str]]) -> str:
    """The /metrics exposition: the node's registry render with the
    DelayProfiler line riding along so one scrape sees both planes."""
    body = metrics() if metrics is not None else ""
    return body + "# delayprofiler " + DelayProfiler.get_stats() + "\n"


def _http_server(host: str, port: int, handler_cls) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), handler_cls)
    srv.daemon_threads = True
    # the HTTP fronts are CLIENT-plane listeners: under a TLS deployment
    # they serve HTTPS with the same contexts/policy as the client
    # socket plane (SERVER_AUTH presents the node cert; MUTUAL_AUTH
    # additionally requires a client cert — a cert-less scraper is
    # rejected at the handshake, same as a cert-less binary client)
    from .net.ssl_util import build_client_plane_contexts

    ctx, _dialer = build_client_plane_contexts()
    if ctx is not None:
        srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name=f"http-{port}")
    t.start()
    return srv


def start_rc_http(
    host: str,
    port: int,
    submit: Callable[[str, Dict, Callable[[str, Dict], None]], None],
    timeout_s: float = 20.0,
    metrics: Optional[Callable[[], str]] = None,
    stats: Optional[Callable[[], Dict]] = None,
) -> ThreadingHTTPServer:
    """Mount the reconfigurator REST API.  ``submit(kind, body, reply)``
    injects the op into the RC demux with `reply` as the client sink.
    ``metrics()`` renders the RC engine's registry (``GET /metrics``,
    Prometheus-style — carries the placement gauges/counters);
    ``stats()`` returns the layer's structured stats (``GET /stats`` —
    the placement snapshot: per-active loads, probe RTTs)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _run(self, op_type: str, payload: Dict) -> None:
            if op_type not in _RC_OPS:
                _send_json(self, 400, {"error": f"unknown type {op_type!r}"})
                return
            if not payload.get("name"):
                _send_json(self, 400, {"error": "missing name"})
                return
            kind, _ack = _RC_OPS[op_type]
            w = _Waiter()
            submit(kind, _body_of(op_type, payload), w)
            if not w.ev.wait(timeout_s):
                _send_json(self, 504, {"error": "timeout"})
                return
            body = w.reply["body"]
            code = 200 if body.get("ok") else 409
            _send_json(self, code, body)

        def do_GET(self):
            path = urlparse(self.path).path
            if path == "/stats":
                body = {"stats": DelayProfiler.get_stats()}
                if stats is not None:
                    body.update(stats() or {})
                _send_json(self, 200, body)
                return
            if path == "/metrics":
                _send_text(self, 200, _metrics_body(metrics))
                return
            q = parse_qs(urlparse(self.path).query)
            name = (q.get("name") or [None])[0]
            op = (q.get("type") or ["REQ_ACTIVES"])[0].upper()
            self._run(op, {"name": name})

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                _send_json(self, 400, {"error": "bad json"})
                return
            self._run(str(payload.get("type", "")).upper(), payload)

    return _http_server(host, port, Handler)


def start_ar_http(
    host: str,
    port: int,
    propose: Callable[[str, str, Callable], Optional[int]],
    timeout_s: float = 20.0,
    overloaded: Optional[Callable[[], bool]] = None,
    metrics: Optional[Callable[[], str]] = None,
) -> ThreadingHTTPServer:
    """Mount the active-replica app-request API (HttpActiveReplica analog).
    ``propose(name, value, callback)`` is the manager's propose;
    ``overloaded()`` gates admission (503) so the MAX_OUTSTANDING back
    -pressure covers every entry path, not just the binary protocol;
    ``metrics()`` renders the node's engine-metrics registry as text
    (``GET /metrics``, Prometheus-style — the obs-plane dump endpoint)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            path = urlparse(self.path).path
            if path == "/stats":
                _send_json(self, 200, {"stats": DelayProfiler.get_stats()})
            elif path == "/metrics":
                _send_text(self, 200, _metrics_body(metrics))
            else:
                _send_json(self, 404, {"error": "POST app requests to /"})

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                _send_json(self, 400, {"error": "bad json"})
                return
            name = payload.get("name")
            value = payload.get("request", payload.get("value"))
            if not name or value is None:
                _send_json(self, 400, {"error": "need name and request"})
                return
            if overloaded is not None and overloaded():
                _send_json(self, 503, {"error": "overload", "name": name})
                return
            ev = threading.Event()
            box: Dict = {}

            def cb(rid, resp):
                box["response"] = resp
                ev.set()

            vid = propose(name, str(value), cb)
            if vid is None:
                _send_json(self, 404, {"error": "unknown_name", "name": name})
                return
            if not ev.wait(timeout_s):
                _send_json(self, 504, {"error": "timeout"})
                return
            _send_json(self, 200,
                       {"name": name, "response": box.get("response")})

    return _http_server(host, port, Handler)
