"""HTTP front-ends for the reconfigurator and active-replica roles.

API-parity targets: ``HttpReconfigurator`` (``http/HttpReconfigurator.
java:51,79`` — netty REST for create/delete/request-actives; commands as
``{"type": "CREATE", "name": ..., "initialState": ...}``) and the fork's
``HttpActiveReplica`` (``HttpActiveReplica.java:29`` — POST app requests).

Python re-design: a stdlib ``ThreadingHTTPServer`` per role, mounted next
to the socket transport at ``port + PC.HTTP_PORT_OFFSET``.  Handlers
bridge into the same demux paths the binary protocol uses (an HTTP create
is exactly an ``rc_client`` op with the reply parked on the HTTP worker
thread), so the front-end adds no new semantics — just a wire format.

Endpoints (reconfigurator):
  GET  /?name=N                 -> request actives (also /?type=REQ_ACTIVES)
  POST / {"type": "CREATE",  "name": N, "initialState": S}
  POST / {"type": "DELETE",  "name": N}
  POST / {"type": "RECONFIGURE", "name": N, "actives": [..]}
Endpoints (active replica):
  POST / {"name": N, "request": value}   -> execute through consensus
  GET  /stats                            -> DelayProfiler snapshot
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .utils.profiler import DelayProfiler

# HTTP op type -> (rc_client kind, ack kind) — HttpRequestType analog
_RC_OPS = {
    "CREATE": ("create_service", "create_ack"),
    "DELETE": ("delete_service", "delete_ack"),
    "RECONFIGURE": ("reconfigure", "reconfigure_ack"),
    "REQ_ACTIVES": ("request_actives", "actives_response"),
}


def _body_of(op_type: str, payload: Dict) -> Dict:
    name = payload["name"]
    if op_type == "CREATE":
        body = {"name": name, "initial_state": payload.get("initialState")}
        if payload.get("actives") is not None:
            body["actives"] = list(payload["actives"])
        return body
    if op_type == "RECONFIGURE":
        return {"name": name, "new_actives": list(payload["actives"])}
    return {"name": name}


class _Waiter:
    """Parks an HTTP worker thread until the layer's async reply lands."""

    def __init__(self):
        self.ev = threading.Event()
        self.reply: Optional[Dict] = None

    def __call__(self, kind: str, body: Dict) -> None:
        self.reply = {"kind": kind, "body": body}
        self.ev.set()


def _http_server(host: str, port: int, handler_cls) -> ThreadingHTTPServer:
    srv = ThreadingHTTPServer((host, port), handler_cls)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name=f"http-{port}")
    t.start()
    return srv


def start_rc_http(
    host: str,
    port: int,
    submit: Callable[[str, Dict, Callable[[str, Dict], None]], None],
    timeout_s: float = 20.0,
) -> ThreadingHTTPServer:
    """Mount the reconfigurator REST API.  ``submit(kind, body, reply)``
    injects the op into the RC demux with `reply` as the client sink."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _respond(self, code: int, obj: Dict) -> None:
            data = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _run(self, op_type: str, payload: Dict) -> None:
            if op_type not in _RC_OPS:
                self._respond(400, {"error": f"unknown type {op_type!r}"})
                return
            if not payload.get("name"):
                self._respond(400, {"error": "missing name"})
                return
            kind, _ack = _RC_OPS[op_type]
            w = _Waiter()
            submit(kind, _body_of(op_type, payload), w)
            if not w.ev.wait(timeout_s):
                self._respond(504, {"error": "timeout"})
                return
            body = w.reply["body"]
            code = 200 if body.get("ok") else 409
            self._respond(code, body)

        def do_GET(self):
            q = parse_qs(urlparse(self.path).query)
            name = (q.get("name") or [None])[0]
            op = (q.get("type") or ["REQ_ACTIVES"])[0].upper()
            self._run(op, {"name": name})

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                self._respond(400, {"error": "bad json"})
                return
            self._run(str(payload.get("type", "")).upper(), payload)

    return _http_server(host, port, Handler)


def start_ar_http(
    host: str,
    port: int,
    propose: Callable[[str, str, Callable], Optional[int]],
    timeout_s: float = 20.0,
    overloaded: Optional[Callable[[], bool]] = None,
    metrics: Optional[Callable[[], str]] = None,
) -> ThreadingHTTPServer:
    """Mount the active-replica app-request API (HttpActiveReplica analog).
    ``propose(name, value, callback)`` is the manager's propose;
    ``overloaded()`` gates admission (503) so the MAX_OUTSTANDING back
    -pressure covers every entry path, not just the binary protocol;
    ``metrics()`` renders the node's engine-metrics registry as text
    (``GET /metrics``, Prometheus-style — the obs-plane dump endpoint)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _respond(self, code: int, obj: Dict) -> None:
            data = json.dumps(obj).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _respond_text(self, code: int, text: str) -> None:
            data = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = urlparse(self.path).path
            if path == "/stats":
                self._respond(200, {"stats": DelayProfiler.get_stats()})
            elif path == "/metrics":
                body = metrics() if metrics is not None else ""
                # DelayProfiler rides along so one scrape sees both planes
                body += "# delayprofiler " + DelayProfiler.get_stats() + "\n"
                self._respond_text(200, body)
            else:
                self._respond(404, {"error": "POST app requests to /"})

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError:
                self._respond(400, {"error": "bad json"})
                return
            name = payload.get("name")
            value = payload.get("request", payload.get("value"))
            if not name or value is None:
                self._respond(400, {"error": "need name and request"})
                return
            if overloaded is not None and overloaded():
                self._respond(503, {"error": "overload", "name": name})
                return
            ev = threading.Event()
            box: Dict = {}

            def cb(rid, resp):
                box["response"] = resp
                ev.set()

            vid = propose(name, str(value), cb)
            if vid is None:
                self._respond(404, {"error": "unknown_name", "name": name})
                return
            if not ev.wait(timeout_s):
                self._respond(504, {"error": "timeout"})
                return
            self._respond(200, {"name": name, "response": box.get("response")})

    return _http_server(host, port, Handler)
