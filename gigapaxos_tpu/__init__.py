"""gigapaxos_tpu — a TPU-native group-scalable replicated state machine framework.

A brand-new implementation of the capabilities of GigaPaxos (reference:
``/root/reference``, ``src/edu/umass/cs/gigapaxos/PaxosManager.java:104-119``):
millions of independent Paxos consensus groups per node with on-demand
creation, pausing, persistent logging/checkpointing, failure detection,
coordinator election, and a reconfiguration layer that migrates replica sets
at runtime — all behind a ``Replicable{execute, checkpoint, restore}`` app SPI.

Unlike the reference's object-per-group Java event machines over custom TCP
NIO, the core here is a **batched JAX/XLA engine**: the acceptor and
coordinator state of *all* groups lives as HBM-resident ``[G]`` / ``[G, W]``
int32 arrays, and prepare/accept/decide for every group advance together as
vectorized ops inside a single jitted step.  Inter-replica Paxos traffic is
one ``all_gather`` of a packed int32 state blob over a 'replica' mesh axis
(ICI), not per-group point-to-point messages.

Layout (mirrors SURVEY.md §7):
  utils/       config flags, delay profiler                   (ref: utils/)
  obs/         structured logging, per-request tracing,
               engine metrics registry                        (ref: j.u.logging + RequestInstrumenter + DelayProfiler)
  interfaces/  Replicable app SPI, Request types              (ref: gigapaxos/interfaces/)
  packets/     wire packets + tensor packing                  (ref: paxospackets/)
  ops/         the batched consensus kernels                  (ref: PaxosAcceptor/Coordinator)
  parallel/    mesh construction, shard_map SPMD step         (ref: nio/ multicast)
  storage/     journal + checkpoint durability                (ref: SQLPaxosLogger)
  net/         host transport (client/control plane over DCN) (ref: nio/)
  models/      example Replicable apps                        (ref: examples/)
  reconfiguration/  control plane: create/delete/migrate RSMs (ref: reconfiguration/)
  clients/     async clients                                  (ref: PaxosClientAsync)
"""

__version__ = "0.1.0"
