"""Poll-driven 2PC transaction driver.

One :class:`TxnDriver` drives one transaction through the replicated
state machines in :mod:`.app` without ever blocking: every call to
:meth:`poll` inspects the responses that have arrived, retransmits what
timed out (logical clock — the chaos-compressed convention, never a
wall-clock gate), and submits the next protocol step.  The chaos soak
polls many drivers between cluster steps; the synchronous
:class:`~gigapaxos_tpu.txn.transactor.Transactor` wraps one driver in a
step loop.

Protocol order (the invariants the resolver relies on):

1. ``begin`` to the coordinator group — ACKED before any prepare is
   sent, so every lock in the system is traceable to a begin record
   (no orphan prepares: presumed abort can always find the record).
2. ``prepare`` per participant IN SORTED NAME ORDER, strictly
   sequentially — the classic deadlock-freedom argument: all
   transactions acquire locks along one global order.
3. ``prepared`` marker, then ``decide committed`` — the coordinator
   answers with the FINAL outcome (first decide wins), which may be
   ``aborted`` if a resolver presumed-abort beat us; the driver obeys
   whatever came back.
4. Drive the decided outcome (``commit``/``abort``) to EVERY
   participant named by the transaction — including ones never
   prepared, so a straggling prepare retransmit hits the participant's
   resolved-ring fence instead of re-locking.
5. ``end`` the coordinator record.

Retransmits reuse the SAME request id: an executed-and-cached step is
answered from the response cache (exactly-once), while retryable
refusals are deliberately left uncached by the manager
(``request.txn_retry``) so the same id retries the op after the lock
clears.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional

from ..paxos_config import PC
from ..utils.config import Config
from .app import ABORTED, COMMITTED, tx_op, txc_op

# driver states
_BEGIN, _PREPARE, _MARK, _DECIDE, _DRIVE, _END, _DONE = range(7)


class _Op:
    """One in-flight replicated op: value + rid + response box."""

    __slots__ = ("name", "value", "rid", "box", "sent_at", "attempts")

    def __init__(self, name: str, value: str, rid: int):
        self.name = name
        self.value = value
        self.rid = rid
        self.box: List = []
        self.sent_at = float("-inf")
        self.attempts = 0

    def latest(self) -> Optional[Dict]:
        if not self.box:
            return None
        import json

        try:
            return json.loads(self.box[-1]) if self.box[-1] else None
        except (ValueError, TypeError):
            return None


class TxnDriver:
    """Drive one transaction to a single global outcome.

    ``submit(name, value, request_id, callback)`` proposes one
    replicated request through any entry replica (async; the callback
    receives ``(request_id, response)``).  ``clock()`` returns logical
    seconds — the soak advances it per cluster step.
    """

    def __init__(
        self,
        txn,
        submit: Callable[[str, str, int, Callable], None],
        coord: str,
        clock: Callable[[], float],
        *,
        prepare_timeout_s: Optional[float] = None,
        retransmit_s: float = 0.25,
        metrics=None,
        rng: Optional[random.Random] = None,
    ):
        self.txn = txn
        self.submit = submit
        self.coord = coord
        self.clock = clock
        self.prepare_timeout_s = (
            Config.get_float(PC.TXN_PREPARE_TIMEOUT_S)
            if prepare_timeout_s is None else float(prepare_timeout_s)
        )
        self.retransmit_s = float(retransmit_s)
        self.metrics = metrics
        self._rng = rng or random
        self._state = _BEGIN
        self._t0 = None  # logical time of first poll
        self._wall0 = None  # wall time, for the latency histogram only
        self._op: Optional[_Op] = None
        self._drive: List[_Op] = []
        self._prep_idx = 0
        self.outcome: Optional[str] = None
        self._abort_why: Optional[str] = None
        self._responses: Dict[str, List] = {}
        self.result: Optional[Dict] = None
        # ops per name, in sorted-lock-order
        self._vals: Dict[str, List[str]] = {}
        for n, v in txn.ops:
            self._vals.setdefault(n, []).append(v)
        self.names = sorted(self._vals)

    # ---- submission helpers -------------------------------------------
    def _rid(self) -> int:
        return self._rng.randrange(1 << 48, 1 << 62)

    def _send(self, op: _Op) -> None:
        op.sent_at = self.clock()
        op.attempts += 1
        self.submit(op.name, op.value, op.rid,
                    lambda rid, resp, b=op.box: b.append(resp))

    def _start(self, name: str, value: str) -> _Op:
        op = _Op(name, value, self._rid())
        self._op = op
        self._send(op)
        return op

    def _retransmit(self, op: _Op, now: float) -> None:
        if now - op.sent_at >= self.retransmit_s:
            self._send(op)

    # ---- the state machine --------------------------------------------
    def poll(self) -> Optional[Dict]:
        """Advance as far as arrived responses allow; returns the result
        dict once the transaction reached END, else None."""
        if self._state == _DONE:
            return self.result
        now = self.clock()
        if self._t0 is None:
            self._t0 = now
            self._wall0 = time.time()
            self._start(self.coord, txc_op(
                "begin", self.txn.txid, names=self.names,
                ops=list(map(list, self.txn.ops)), t=now,
            ))
            if self.metrics is not None:
                self.metrics.count("txn_begun")
            return None

        if self._state == _BEGIN:
            r = self._op.latest()
            if r is None:
                self._retransmit(self._op, now)
                return None
            if r.get("outcome"):  # retransmit of an already-decided txn
                self.outcome = r["outcome"]
                self._enter_drive()
                return None
            self._state = _PREPARE
            self._prep_idx = 0
            self._start_prepare()
            return None

        if self._state == _PREPARE:
            r = self._op.latest()
            if r is None:
                self._retransmit(self._op, now)
            elif r.get("ok"):
                self._prep_idx += 1
                if self._prep_idx >= len(self.names):
                    self._state = _MARK
                    self._start(self.coord,
                                txc_op("prepared", self.txn.txid))
                else:
                    self._start_prepare()
                return None
            elif r.get("resolved"):
                # already decided here (a resolver raced us): learn the
                # global outcome through decide and obey it
                self._abort_why = f"resolved:{r['resolved']}"
                self._state = _DECIDE
                self._start(self.coord, txc_op(
                    "decide", self.txn.txid, outcome=ABORTED))
                return None
            elif r.get("retry"):
                # lock held by a rival: same-rid retransmit IS the retry
                # (the refusal was not cached), paced by the logical clock
                self._retransmit(self._op, now)
            else:
                self._begin_abort(f"prepare-refused:{r}")
                return None
            # sorted sequential lock waits bound total wait; past the
            # prepare budget, presume abort ourselves
            if now - self._t0 > self.prepare_timeout_s:
                self._begin_abort("prepare-timeout")
            return None

        if self._state == _MARK:
            r = self._op.latest()
            if r is None:
                self._retransmit(self._op, now)
                return None
            self._state = _DECIDE
            self._start(self.coord, txc_op(
                "decide", self.txn.txid, outcome=COMMITTED))
            return None

        if self._state == _DECIDE:
            r = self._op.latest()
            if r is None:
                self._retransmit(self._op, now)
                return None
            self.outcome = r.get("outcome") or ABORTED
            if self.metrics is not None:
                if self.outcome == COMMITTED:
                    self.metrics.count("txn_committed")
                    self.metrics.observe(
                        "txn_commit_latency_s", time.time() - self._wall0
                    )
                else:
                    self.metrics.count("txn_aborted")
            self._enter_drive()
            return None

        if self._state == _DRIVE:
            done = True
            for op in self._drive:
                r = op.latest()
                if r is None:
                    done = False
                    self._retransmit(op, now)
                elif not r.get("ok") and r.get("retry"):
                    done = False
                    self._retransmit(op, now)
                elif r.get("ok") and r.get("responses") is not None:
                    self._responses[op.name] = r["responses"]
            if done:
                self._state = _END
                self._start(self.coord, txc_op("end", self.txn.txid))
            return None

        if self._state == _END:
            r = self._op.latest()
            if r is None:
                self._retransmit(self._op, now)
                return None
            self._state = _DONE
            self.result = {
                "txid": self.txn.txid,
                "committed": self.outcome == COMMITTED,
                "outcome": self.outcome,
                "responses": self._responses,
                "latency_s": time.time() - self._wall0,
            }
            if self._abort_why and self.outcome != COMMITTED:
                self.result["aborted"] = self._abort_why
            return self.result
        return None

    # ---- transitions ---------------------------------------------------
    def _start_prepare(self) -> None:
        name = self.names[self._prep_idx]
        self._start(name, tx_op(
            "prepare", self.txn.txid, vals=self._vals[name],
        ))

    def _begin_abort(self, why: str) -> None:
        self._abort_why = why
        self._state = _DECIDE
        self._start(self.coord, txc_op(
            "decide", self.txn.txid, outcome=ABORTED))

    def _enter_drive(self) -> None:
        """Drive the decided outcome to EVERY named participant (even
        never-prepared ones — the abort writes the resolved-ring fence a
        straggling prepare retransmit will hit)."""
        self._state = _DRIVE
        kind = "commit" if self.outcome == COMMITTED else "abort"
        self._drive = []
        for name in self.names:
            op = _Op(name, tx_op(kind, self.txn.txid), self._rid())
            self._drive.append(op)
            self._send(op)
