"""Presumed-abort transaction resolution: the crash-recovery path.

A driver can die at any protocol step — before any prepare, mid-lock,
after the decide, mid-outcome-drive.  Because every transition is a
replicated log entry, the coordinator group's live records are a
complete inventory of every transaction that might still hold locks
anywhere, and re-driving them is idempotent (participants answer
re-drives from their resolved rings).  The :class:`TxnResolver` closes
the loop:

* records already DECIDED (``committed``/``aborted``) but never ended —
  the driver died mid-drive — are re-driven to every participant and
  then ended;
* records still ``begun``/``prepared`` past the presumed-abort horizon
  (logical clock vs. the record's begin time) are decided ABORTED —
  first-decide-wins makes the race against a slow-but-alive driver
  safe: whoever decides first fixes the global outcome and the other
  obeys it.

Run one resolver per deployment (or several — every step is
idempotent), poll it on the soak/serving cadence, and restart recovery
needs nothing special: journal replay rebuilds the records and the next
resolver pass re-drives them.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional

from ..paxos_config import PC
from ..utils.config import Config
from .app import ABORTED, COMMITTED, tx_op, txc_op
from .driver import _Op


class _Job:
    """Re-drive one decided record: outcome to every participant, then
    end the record."""

    def __init__(self, txid: str, outcome: str, names: List[str]):
        self.txid = txid
        self.outcome = outcome
        self.names = list(names)
        self.drive: List[_Op] = []
        self.end_op: Optional[_Op] = None


class TxnResolver:
    """Poll-driven in-doubt transaction resolver (presumed abort).

    ``submit``/``clock`` follow the :class:`~.driver.TxnDriver`
    conventions; ``resolve_period_s`` (logical) paces the coordinator
    ``list`` scans and ``presume_abort_s`` is the begin-to-abort horizon
    for undecided records.
    """

    def __init__(
        self,
        submit: Callable,
        coord: str,
        clock: Callable[[], float],
        *,
        resolve_period_s: Optional[float] = None,
        presume_abort_s: Optional[float] = None,
        retransmit_s: float = 0.25,
        metrics=None,
        rng: Optional[random.Random] = None,
    ):
        self.submit = submit
        self.coord = coord
        self.clock = clock
        self.resolve_period_s = (
            Config.get_float(PC.TXN_RESOLVE_PERIOD_S)
            if resolve_period_s is None else float(resolve_period_s)
        )
        self.presume_abort_s = (
            Config.get_float(PC.TXN_PREPARE_TIMEOUT_S)
            if presume_abort_s is None else float(presume_abort_s)
        )
        self.retransmit_s = float(retransmit_s)
        self.metrics = metrics
        self._rng = rng or random
        self._list_op: Optional[_Op] = None
        self._last_list = float("-inf")
        self._jobs: Dict[str, _Job] = {}
        self._deciding: Dict[str, _Op] = {}
        self._record_names: Dict[str, List[str]] = {}
        self.live_records = 0  # from the last completed list scan
        self.resolved_count = 0
        self.scans = 0  # completed list scans (settle loops gate on it)

    def _rid(self) -> int:
        return self._rng.randrange(1 << 48, 1 << 62)

    def _send(self, op: _Op) -> None:
        op.sent_at = self.clock()
        op.attempts += 1
        self.submit(op.name, op.value, op.rid,
                    lambda rid, resp, b=op.box: b.append(resp))

    def _retx(self, op: _Op, now: float) -> None:
        if now - op.sent_at >= self.retransmit_s:
            self._send(op)

    def idle(self) -> bool:
        """True when the last scan saw no live records and no re-drive
        is in flight — the settle loop's convergence signal."""
        return not self._jobs and not self._deciding \
            and self.live_records == 0

    # ---- the poll ------------------------------------------------------
    def poll(self) -> None:
        now = self.clock()
        # 1. periodic coordinator scan
        if self._list_op is not None:
            r = self._list_op.latest()
            if r is None:
                self._retx(self._list_op, now)
            else:
                self._list_op = None
                self._on_records(r.get("records") or {}, now)
        elif now - self._last_list >= self.resolve_period_s:
            self._last_list = now
            self._list_op = _Op(self.coord, txc_op("list"), self._rid())
            self._send(self._list_op)

        # 2. pending presume-abort decides
        for txid, op in list(self._deciding.items()):
            r = op.latest()
            if r is None:
                self._retx(op, now)
                continue
            del self._deciding[txid]
            # whatever outcome won (ours or a racing driver's commit),
            # re-drive it now rather than waiting for the next scan
            outcome = r.get("outcome") or ABORTED
            if txid not in self._jobs:
                names = self._record_names.get(txid, [])
                self._start_job(txid, outcome, names)

        # 3. advance re-drive jobs
        for txid, job in list(self._jobs.items()):
            if job.end_op is not None:
                r = job.end_op.latest()
                if r is None:
                    self._retx(job.end_op, now)
                else:
                    del self._jobs[txid]
                    self.resolved_count += 1
                    if self.metrics is not None:
                        self.metrics.count("txn_in_doubt_resolved")
                continue
            done = True
            for op in job.drive:
                r = op.latest()
                if r is None or (not r.get("ok") and r.get("retry")):
                    done = False
                    self._retx(op, now)
            if done:
                job.end_op = _Op(
                    self.coord, txc_op("end", job.txid), self._rid()
                )
                self._send(job.end_op)

    # ---- record handling ----------------------------------------------
    def _on_records(self, records: Dict[str, Dict], now: float) -> None:
        self.scans += 1
        self.live_records = len(records)
        self._record_names = {
            txid: list(rec.get("names") or [])
            for txid, rec in records.items()
        }
        for txid, rec in records.items():
            if txid in self._jobs or txid in self._deciding:
                continue
            state = rec.get("state")
            if state in (COMMITTED, ABORTED):
                # decided but never ended: the driver died mid-drive
                self._start_job(txid, state, rec.get("names") or [])
            elif now - float(rec.get("t") or 0.0) >= self.presume_abort_s:
                op = _Op(self.coord, txc_op(
                    "decide", txid, outcome=ABORTED), self._rid())
                self._deciding[txid] = op
                self._send(op)

    def _start_job(self, txid: str, outcome: str, names: List[str]) -> None:
        job = _Job(txid, outcome, names)
        kind = "commit" if outcome == COMMITTED else "abort"
        for name in job.names:
            op = _Op(name, tx_op(kind, txid), self._rid())
            job.drive.append(op)
            self._send(op)
        if not job.names:
            job.end_op = _Op(self.coord, txc_op("end", txid), self._rid())
            self._send(job.end_op)
        self._jobs[txid] = job
