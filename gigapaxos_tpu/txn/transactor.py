"""Distributed transactions across RSM groups (experimental, matching the
reference's scope).

API-parity target: ``txn/DistTransactor.java`` (333 LoC wrapping an
``AbstractReplicaCoordinator``) with the 2PC-style ops of
``txn/txpackets/`` (LockRequest / UnlockRequest / TxOpRequest /
CommitRequest / AbortRequest) — present and functional but explicitly
*experimental*, exactly as in the reference (``SURVEY.md`` §2.6: "treat
as capability stub: present, compiles, not load-bearing").

Design: locks are themselves CONSENSUS operations.  :class:`TxnApp`
wraps the user's Replicable; reserved ``__tx__``-prefixed request values
are interpreted as lock-table ops (acquire/release/apply), everything
else passes through — but is refused while the group is locked by a
transaction, making each group's lock linearizable with its log.  The
transactor acquires locks in sorted-name order (deadlock freedom),
applies the ops, then releases — each step an ordinary replicated
request, so crash recovery replays to a consistent lock state and an
abort path releases whatever was acquired.

Guarantee honesty (same envelope as the reference's experimental txn):
this provides ISOLATION (no other request or transaction interleaves
with a locked group) and lock-phase all-or-nothing, but an abort during
the APPLY phase does not roll back ops already applied to earlier
groups — there is no undo log.  An aborted result reports how many ops
had applied (``applied_ops``) so callers can compensate.
"""

from __future__ import annotations

import json
import random
import time
from typing import Dict, List, Optional, Tuple

from ..interfaces.app import Replicable, Request

TX_PREFIX = "__tx__:"


class TxnApp(Replicable):
    """Replicable wrapper adding a per-name transaction lock table
    (``TXLockerMap`` analog); the lock state is part of the RSM (it rides
    checkpoints), so all replicas agree on it."""

    def __init__(self, app: Replicable):
        self.app = app
        self.locks: Dict[str, str] = {}  # name -> holding txid

    # ---- Replicable ----------------------------------------------------
    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        name = request.paxos_id
        value = request.request_value or ""
        if value.startswith(TX_PREFIX):
            op = json.loads(value[len(TX_PREFIX):])
            request.response_value = json.dumps(self._tx_op(name, op))
            return True
        holder = self.locks.get(name)
        if holder is not None:
            # group locked by an in-flight transaction: refuse (the client
            # retries; LockRequest semantics)
            request.response_value = json.dumps(
                {"ok": False, "locked_by": holder}
            )
            return True
        return self.app.execute(request, do_not_reply_to_client)

    def _tx_op(self, name: str, op: Dict) -> Dict:
        kind, txid = op["kind"], op["txid"]
        holder = self.locks.get(name)
        if kind == "lock":
            if holder is None:
                self.locks[name] = txid
                return {"ok": True}
            return {"ok": holder == txid, "locked_by": holder}
        if kind == "unlock":
            if holder == txid:
                del self.locks[name]
            return {"ok": True}  # idempotent
        if kind == "apply":
            if holder != txid:
                return {"ok": False, "locked_by": holder}
            from ..packets.paxos_packets import RequestPacket

            inner = RequestPacket(
                paxos_id=name, request_id=int(op["rid"]),
                request_value=op["value"],
            )
            self.app.execute(inner, True)
            return {"ok": True,
                    "response": getattr(inner, "response_value", None)}
        return {"ok": False, "error": f"unknown tx op {kind!r}"}

    def checkpoint(self, name: str) -> Optional[str]:
        return json.dumps({
            "app": self.app.checkpoint(name),
            "lock": self.locks.get(name),
        })

    def restore(self, name: str, state: Optional[str]) -> bool:
        if state:
            try:
                d = json.loads(state)
            except (json.JSONDecodeError, TypeError):
                d = {"app": state, "lock": None}
            if isinstance(d, dict) and "app" in d:
                if d.get("lock") is not None:
                    self.locks[name] = d["lock"]
                else:
                    self.locks.pop(name, None)
                return self.app.restore(name, d["app"])
        else:
            self.locks.pop(name, None)
        return self.app.restore(name, state)

    def get_request(self, stringified: str):
        return self.app.get_request(stringified)

    # convenience passthroughs for fixtures
    def __getattr__(self, item):
        return getattr(self.app, item)


class Transaction:
    """An ordered set of (name, request_value) ops applied atomically
    w.r.t. other transactions and single-group requests."""

    def __init__(self, ops: List[Tuple[str, str]]):
        self.ops = list(ops)
        self.txid = f"tx{random.randrange(1 << 48):012x}"

    @property
    def names(self) -> List[str]:
        return sorted({n for n, _ in self.ops})


class DistTransactor:
    """Drives transactions through any request submitter
    (``DistTransactor.java`` analog).  ``submit(name, value, timeout)``
    must deliver a consensus-executed response string or None."""

    def __init__(self, submit, lock_timeout_s: float = 10.0):
        self.submit = submit
        self.lock_timeout_s = lock_timeout_s

    def _tx(self, name: str, op: Dict, timeout: float) -> Optional[Dict]:
        resp = self.submit(
            name, TX_PREFIX + json.dumps(op, separators=(",", ":")), timeout
        )
        if resp is None:
            return None
        return json.loads(resp)

    def execute(self, txn: Transaction, timeout: float = 30.0) -> Dict:
        """Lock all groups (sorted order — deadlock-free), apply all ops,
        unlock.  On failure: release acquired locks and report abort with
        `applied_ops` (ops already applied are NOT rolled back — see the
        module docstring's guarantee note)."""
        deadline = time.time() + timeout
        acquired: List[str] = []
        applied = 0
        try:
            for name in txn.names:  # phase 1: lock
                while True:
                    r = self._tx(name, {"kind": "lock", "txid": txn.txid},
                                 self.lock_timeout_s)
                    if r and r.get("ok"):
                        acquired.append(name)
                        break
                    if time.time() > deadline:
                        return self._abort(txn, acquired, "lock-timeout", 0)
                    time.sleep(0.05)  # holder backoff (TXLockerMap wait)
            results = []
            for i, (name, value) in enumerate(txn.ops):  # phase 2: apply
                r = self._tx(name, {
                    "kind": "apply", "txid": txn.txid,
                    "rid": random.randrange(1 << 53, 1 << 62),
                    "value": value,
                }, max(1.0, deadline - time.time()))
                if not (r and r.get("ok")):
                    return self._abort(
                        txn, acquired, f"apply-failed@{i}", applied
                    )
                applied += 1
                results.append(r.get("response"))
            self._release(txn, acquired)
            return {"committed": True, "responses": results}
        except Exception as e:  # release on any client-side failure
            self._abort(txn, acquired, repr(e), applied)
            raise

    def _release(self, txn: Transaction, names: List[str]) -> None:
        for name in names:
            self._tx(name, {"kind": "unlock", "txid": txn.txid},
                     self.lock_timeout_s)

    def _abort(self, txn: Transaction, acquired: List[str], why: str,
               applied: int) -> Dict:
        self._release(txn, acquired)
        return {"committed": False, "aborted": why, "applied_ops": applied}
