"""Synchronous transaction front-end over the poll-driven
:class:`~gigapaxos_tpu.txn.driver.TxnDriver`.

:class:`Transaction` names the ops; :class:`Transactor` runs one
transaction to its single global outcome by alternating driver polls
with cluster steps.  Time inside :meth:`Transactor.run` is LOGICAL —
each ``step()`` advances an internal clock by ``step_dt`` — so lock
waits, retransmits, and the prepare timeout all follow the
chaos-compressed clock convention (no ``time.time()`` gate anywhere in
the protocol path; ROADMAP item 1's no-hard-wall-clock-gates rule).
A caller with real time to spend can inject its own ``clock``.

``DistTransactor`` remains as the reference-named alias
(``txn/DistTransactor.java``), now implemented — not a capability
stub: aborts discard STAGED ops, so no participant is ever mutated by
a transaction that did not commit.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from .app import TXN_COORD
from .driver import TxnDriver


class Transaction:
    """An ordered set of (name, request_value) ops applied atomically:
    either every op executes (exactly once) or none does."""

    def __init__(self, ops: List[Tuple[str, str]],
                 txid: Optional[str] = None,
                 rng: Optional[random.Random] = None):
        self.ops = list(ops)
        r = rng or random
        self.txid = txid or f"tx{r.randrange(1 << 48):012x}"

    @property
    def names(self) -> List[str]:
        return sorted({n for n, _ in self.ops})


class Transactor:
    """Run transactions synchronously against a stepped cluster.

    ``submit(name, value, request_id, callback)`` proposes one
    replicated request (async); ``step()`` advances the cluster one
    tick.  Each step advances the logical clock by ``step_dt`` seconds.
    """

    def __init__(
        self,
        submit: Callable[[str, str, int, Callable], None],
        step: Callable[[], None],
        coord: str = TXN_COORD,
        *,
        step_dt: float = 0.05,
        prepare_timeout_s: Optional[float] = None,
        retransmit_s: float = 0.25,
        metrics=None,
        rng: Optional[random.Random] = None,
    ):
        self.submit = submit
        self.step = step
        self.coord = coord
        self.step_dt = float(step_dt)
        self.prepare_timeout_s = prepare_timeout_s
        self.retransmit_s = retransmit_s
        self.metrics = metrics
        self.rng = rng
        self._steps = 0

    def clock(self) -> float:
        """Logical seconds: steps taken x step_dt (chaos-compressed)."""
        return self._steps * self.step_dt

    def driver(self, txn: Transaction) -> TxnDriver:
        return TxnDriver(
            txn, self.submit, self.coord, self.clock,
            prepare_timeout_s=self.prepare_timeout_s,
            retransmit_s=self.retransmit_s,
            metrics=self.metrics, rng=self.rng,
        )

    def run(self, txn: Transaction, max_steps: int = 20000) -> Dict:
        """Drive ``txn`` to its decided outcome; returns the driver's
        result dict (``committed``/``outcome``/``responses``).  Raises
        ``TimeoutError`` only if the cluster makes no progress within
        ``max_steps`` ticks — a liveness budget, not a wall clock."""
        d = self.driver(txn)
        for _ in range(max_steps):
            out = d.poll()
            if out is not None:
                return out
            self.step()
            self._steps += 1
        raise TimeoutError(
            f"transaction {txn.txid} undecided after {max_steps} steps "
            f"(state={d._state})"
        )


#: reference-named alias (``txn/DistTransactor.java``)
DistTransactor = Transactor
