"""The transaction RSM: participant and coordinator state machines in one
Replicable wrapper.

Every 2PC state transition is itself a REPLICATED REQUEST (Gray &
Lampson, *Consensus on Transaction Commit*; Spanner's 2PC layered over
Paxos groups): reserved ``__tx__:``-prefixed values are PARTICIPANT ops
executed inside the data group's own consensus log, and
``__txc__:``-prefixed values are COORDINATOR-RECORD ops executed inside
a dedicated coordinator group's log.  Because each transition is a
decided log entry, crash recovery is just journal replay — a restarted
replica re-derives its lock table, staged ops, and coordinator records
from the same decisions everyone else executed, and the resolver
(:mod:`.recovery`) re-drives any transaction that was in doubt.

Participant protocol (per data group):

* ``prepare``   — stage the transaction's ops for this name AND acquire
  the name's lock, in ONE replicated step.  Refused retryably while a
  rival holds the lock; refused terminally once the transaction is
  already resolved here (the late-prepare fence: a straggling prepare
  decided after the transaction's abort must not re-acquire the lock).
* ``commit``    — apply the staged ops through the inner app, release
  the lock, remember the outcome.  Idempotent (re-drives answer from
  the resolved ring).
* ``abort``     — discard the staged ops (nothing was ever applied —
  the staged-until-decision rule is what closes the old stub's no-undo
  hole), release the lock, remember ``aborted`` even when nothing was
  staged (presumed abort + the late-prepare fence).

Coordinator protocol (per coordinator group, any name works — the
convention is :data:`TXN_COORD` / ``__txc__0``):

* ``begin``     — durably create the transaction record (names + ops +
  the client's logical begin time) in state ``begun``.
* ``prepared``  — bookkeeping transition once every participant staged.
* ``decide``    — the COMMIT POINT.  First decide wins; every later
  decide (a racing resolver, a retransmit) is answered with the
  already-decided outcome, so all drivers converge on one global
  outcome.
* ``end``       — retire the record once the outcome reached every
  participant; the outcome parks in a bounded resolved ring so late
  ``outcome`` queries (and killed-driver audits) still get an answer.
* ``outcome`` / ``list`` — reads used by the resolver and the audits.

All of it — locks, staged ops, per-name resolved rings, coordinator
records — rides :meth:`TxnApp.checkpoint` / :meth:`TxnApp.restore`, so
pause/hibernate, state transfer, and restart-from-journal carry the
transaction plane exactly like app state.

Refusals that the client should simply retry (lock held by a rival, or
a plain request against a locked group) set ``request.txn_retry`` — the
manager skips the response cache for those, so the SAME request id can
be retried after the lock clears without tripping exactly-once dedup.
The skip is deterministic (every replica computes the same refusal from
the same replicated state), so the RSM stays convergent.
"""

from __future__ import annotations

import json
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..interfaces.app import Replicable, Request

TX_PREFIX = "__tx__:"
TXC_PREFIX = "__txc__:"
#: default coordinator-group name (create it like any other group)
TXN_COORD = "__txc__0"

COMMITTED = "committed"
ABORTED = "aborted"

#: per-name resolved-transaction ring bound: the late-prepare fence only
#: needs to outlive the retransmit horizon of one transaction, not all
#: history (a prepare delayed past 512 later transactions on the same
#: name is beyond any retransmit schedule this repo runs)
RESOLVED_RING = 512


def tx_op(kind: str, txid: str, **kw) -> str:
    """Encode one participant op as a request value."""
    kw.update(kind=kind, txid=txid)
    return TX_PREFIX + json.dumps(kw, sort_keys=True, separators=(",", ":"))


def txc_op(kind: str, txid: str = "", **kw) -> str:
    """Encode one coordinator-record op as a request value."""
    kw.update(kind=kind, txid=txid)
    return TXC_PREFIX + json.dumps(kw, sort_keys=True, separators=(",", ":"))


def _ring_put(ring: "OrderedDict[str, str]", txid: str, outcome: str) -> None:
    ring[txid] = outcome
    ring.move_to_end(txid)
    while len(ring) > RESOLVED_RING:
        ring.popitem(last=False)


class TxnApp(Replicable):
    """Replicable wrapper holding the transaction plane's replicated
    state next to the inner app's: per-name locks, staged-until-decision
    ops, resolved rings, and coordinator records.  Everything mutates
    only inside :meth:`execute` (a decided log entry), so all replicas
    agree on it by construction."""

    def __init__(self, app: Replicable):
        self.app = app
        self.locks: Dict[str, str] = {}              # name -> holding txid
        # name -> (txid, [op values]) staged until the global decision
        self.staged: Dict[str, Tuple[str, List[str]]] = {}
        # name -> bounded ring txid -> outcome (idempotent re-drives +
        # the late-prepare fence)
        self.resolved: Dict[str, "OrderedDict[str, str]"] = {}
        # coordinator-group name -> txid -> live record
        self.records: Dict[str, Dict[str, Dict]] = {}
        # coordinator-group name -> bounded ring txid -> final outcome
        self.ended: Dict[str, "OrderedDict[str, str]"] = {}

    # ---- Replicable ----------------------------------------------------
    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        name = request.paxos_id
        value = request.request_value or ""
        if value.startswith(TX_PREFIX):
            op = json.loads(value[len(TX_PREFIX):])
            out = self._participant_op(name, op)
        elif value.startswith(TXC_PREFIX):
            op = json.loads(value[len(TXC_PREFIX):])
            out = self._coordinator_op(name, op)
        else:
            holder = self.locks.get(name)
            if holder is None:
                return self.app.execute(request, do_not_reply_to_client)
            # group locked by an in-flight transaction: refuse retryably
            # and keep the refusal OUT of the response cache so the same
            # request id flows once the lock clears
            request.txn_retry = True
            out = {"ok": False, "locked_by": holder, "retry": True}
        if out.pop("_retry", False):
            request.txn_retry = True
        request.response_value = json.dumps(out, sort_keys=True)
        return True

    # ---- participant RSM ----------------------------------------------
    def _resolved_outcome(self, name: str, txid: str) -> Optional[str]:
        ring = self.resolved.get(name)
        return ring.get(txid) if ring else None

    def _participant_op(self, name: str, op: Dict) -> Dict:
        kind, txid = op["kind"], op["txid"]
        holder = self.locks.get(name)
        if kind == "prepare":
            res = self._resolved_outcome(name, txid)
            if res is not None:
                # the late-prepare fence: this transaction was already
                # decided here — a straggler prepare must not re-lock
                return {"ok": False, "resolved": res}
            if holder is not None and holder != txid:
                return {"ok": False, "locked_by": holder, "retry": True,
                        "_retry": True}
            vals = [str(v) for v in (op.get("vals") or [])]
            self.locks[name] = txid
            self.staged[name] = (txid, vals)
            return {"ok": True, "staged": len(vals)}
        if kind == "commit":
            res = self._resolved_outcome(name, txid)
            if res == COMMITTED:
                return {"ok": True, "already": True}
            if res == ABORTED:
                # cannot happen under first-decide-wins; visible if it does
                return {"ok": False, "conflict": res}
            if holder != txid:
                return {"ok": False, "unprepared": True}
            _, vals = self.staged.pop(name, (txid, []))
            responses = []
            # deterministic inner request ids (this runs inside a
            # replicated execute — every replica must mint the same)
            base_rid = zlib.crc32(txid.encode("utf-8")) << 8
            from ..packets.paxos_packets import RequestPacket

            for i, v in enumerate(vals):
                inner = RequestPacket(
                    paxos_id=name, request_id=base_rid + i,
                    request_value=v,
                )
                self.app.execute(inner, True)
                responses.append(getattr(inner, "response_value", None))
            del self.locks[name]
            _ring_put(self.resolved.setdefault(name, OrderedDict()),
                      txid, COMMITTED)
            return {"ok": True, "responses": responses}
        if kind == "abort":
            if holder == txid:
                del self.locks[name]
            st = self.staged.get(name)
            if st is not None and st[0] == txid:
                del self.staged[name]
            # record the abort even when nothing was staged: presumed
            # abort + the fence against a prepare decided after this
            _ring_put(self.resolved.setdefault(name, OrderedDict()),
                      txid, ABORTED)
            return {"ok": True}
        if kind == "status":
            st = self.staged.get(name)
            return {
                "ok": True, "locked_by": holder,
                "staged": (list(st[1]) if st and st[0] == txid else None),
                "resolved": self._resolved_outcome(name, txid),
            }
        return {"ok": False, "error": f"unknown tx op {kind!r}"}

    # ---- coordinator RSM ----------------------------------------------
    def _coordinator_op(self, name: str, op: Dict) -> Dict:
        kind, txid = op["kind"], op.get("txid", "")
        recs = self.records.setdefault(name, {})
        ended = self.ended.setdefault(name, OrderedDict())
        rec = recs.get(txid)
        if kind == "begin":
            if txid in ended:
                return {"ok": True, "outcome": ended[txid], "ended": True}
            if rec is None:
                rec = recs[txid] = {
                    "txid": txid,
                    "names": sorted(str(n) for n in (op.get("names") or [])),
                    "ops": list(op.get("ops") or []),
                    "state": "begun",
                    "t": float(op.get("t") or 0.0),
                }
            out = {"ok": True, "state": rec["state"]}
            if rec["state"] in (COMMITTED, ABORTED):
                out["outcome"] = rec["state"]
            return out
        if kind == "prepared":
            if txid in ended:
                return {"ok": True, "outcome": ended[txid], "ended": True}
            if rec is None:
                return {"ok": False, "unknown": True}
            if rec["state"] == "begun":
                rec["state"] = "prepared"
            out = {"ok": True, "state": rec["state"]}
            if rec["state"] in (COMMITTED, ABORTED):
                out["outcome"] = rec["state"]
            return out
        if kind == "decide":
            if txid in ended:
                return {"ok": True, "outcome": ended[txid], "ended": True}
            want = op.get("outcome")
            if want not in (COMMITTED, ABORTED):
                return {"ok": False, "error": f"bad outcome {want!r}"}
            if rec is None:
                # decide for a record never begun: only reachable by a
                # retransmit straddling an end+ring-eviction; presume
                # abort so nothing can commit without a begin record
                _ring_put(ended, txid, ABORTED)
                return {"ok": True, "outcome": ABORTED, "presumed": True}
            if rec["state"] in (COMMITTED, ABORTED):
                return {"ok": True, "outcome": rec["state"]}
            rec["state"] = want  # the commit point — first decide wins
            return {"ok": True, "outcome": want, "decided": True}
        if kind == "end":
            if rec is None:
                return {"ok": True, "already": True,
                        "outcome": ended.get(txid)}
            if rec["state"] not in (COMMITTED, ABORTED):
                return {"ok": False, "undecided": rec["state"]}
            del recs[txid]
            _ring_put(ended, txid, rec["state"])
            return {"ok": True, "outcome": rec["state"]}
        if kind == "outcome":
            if rec is not None:
                live = rec["state"] if rec["state"] in (COMMITTED, ABORTED) \
                    else None
                return {"ok": True, "outcome": live, "state": rec["state"]}
            return {"ok": True, "outcome": ended.get(txid)}
        if kind == "list":
            return {
                "ok": True,
                "records": {t: dict(r) for t, r in sorted(recs.items())},
            }
        return {"ok": False, "error": f"unknown txc op {kind!r}"}

    # ---- admission / local-read interaction ----------------------------
    def is_coordinated(self, value: str) -> bool:
        """Transaction ops always coordinate; everything else follows
        the inner app's routing (local reads keep working — they see
        committed state only, since staged ops are never applied)."""
        if value.startswith(TX_PREFIX) or value.startswith(TXC_PREFIX):
            return True
        inner = getattr(self.app, "is_coordinated", None)
        return True if inner is None else inner(value)

    def txn_local_read_blocked(self, name: str) -> bool:
        """Consulted by ``PaxosManager.local_read_ok``: a locked/staged
        name's reads must serialize through consensus (where they are
        refused retryably until the decision lands) — a local read racing
        the commit apply could otherwise be un-serializable against the
        transaction."""
        return name in self.locks or name in self.staged

    def txn_stats(self) -> Dict:
        """Admin-op surface (``server._on_admin`` "stats")."""
        return {
            "locks": len(self.locks),
            "staged": len(self.staged),
            "live_records": sum(len(r) for r in self.records.values()),
        }

    # ---- checkpoint / restore ------------------------------------------
    def checkpoint(self, name: str) -> Optional[str]:
        doc: Dict = {"app": self.app.checkpoint(name)}
        if name in self.locks:
            doc["lock"] = self.locks[name]
        st = self.staged.get(name)
        if st is not None:
            doc["staged"] = [st[0], list(st[1])]
        ring = self.resolved.get(name)
        if ring:
            doc["resolved"] = list(ring.items())
        recs = self.records.get(name)
        if recs:
            doc["records"] = {t: dict(r) for t, r in sorted(recs.items())}
        ended = self.ended.get(name)
        if ended:
            doc["ended"] = list(ended.items())
        return json.dumps(doc, sort_keys=True)

    def _clear_name(self, name: str) -> None:
        self.locks.pop(name, None)
        self.staged.pop(name, None)
        self.resolved.pop(name, None)
        self.records.pop(name, None)
        self.ended.pop(name, None)

    def restore(self, name: str, state: Optional[str]) -> bool:
        if not state:
            self._clear_name(name)
            return self.app.restore(name, state)
        try:
            d = json.loads(state)
        except (json.JSONDecodeError, TypeError):
            d = None
        if not (isinstance(d, dict) and "app" in d):
            # a plain inner-app state (e.g. an initial_state at create)
            self._clear_name(name)
            return self.app.restore(name, state)
        self._clear_name(name)
        if d.get("lock") is not None:
            self.locks[name] = d["lock"]
        if d.get("staged"):
            txid, vals = d["staged"][0], d["staged"][1]
            self.staged[name] = (txid, [str(v) for v in vals])
        if d.get("resolved"):
            self.resolved[name] = OrderedDict(
                (t, o) for t, o in d["resolved"]
            )
        if d.get("records"):
            self.records[name] = {t: dict(r) for t, r in d["records"].items()}
        if d.get("ended"):
            self.ended[name] = OrderedDict((t, o) for t, o in d["ended"])
        return self.app.restore(name, d["app"])

    def get_request(self, stringified: str):
        return self.app.get_request(stringified)

    # convenience passthroughs for fixtures
    def __getattr__(self, item):
        return getattr(self.app, item)
