from .transactor import DistTransactor, Transaction, TxnApp

__all__ = ["DistTransactor", "Transaction", "TxnApp"]
