from .app import (
    ABORTED,
    COMMITTED,
    TX_PREFIX,
    TXC_PREFIX,
    TXN_COORD,
    TxnApp,
    tx_op,
    txc_op,
)
from .driver import TxnDriver
from .recovery import TxnResolver
from .transactor import DistTransactor, Transaction, Transactor

__all__ = [
    "ABORTED",
    "COMMITTED",
    "TX_PREFIX",
    "TXC_PREFIX",
    "TXN_COORD",
    "DistTransactor",
    "Transaction",
    "Transactor",
    "TxnApp",
    "TxnDriver",
    "TxnResolver",
    "tx_op",
    "txc_op",
]
