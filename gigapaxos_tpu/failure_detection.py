"""FailureDetection — keep-alive pings + vectorized election triggers.

Ref: ``FailureDetection.java:62-79`` — ping period = timeout/2 (default
node timeout 6s, ``PaxosConfig.java:668``), ``lastHeardFrom`` map, and the
optimization that *any* traffic counts as heard-from
(``PaxosInstanceStateMachine.java:884,1002,1167``).  The reference then
consults ``isNodeUp``/``lastCoordinatorLongDead`` per instance inside
``checkRunForCoordinator`` (:1962-2072); here that per-group decision is
one vectorized pass producing the engine's ``want_coord`` mask:

  run for coordinator of group g iff the believed coordinator (ballot
  coord) is dead AND I am the next-in-line member (round-robin successor,
  the ``roundRobinCoordinator`` spread rule :2123), OR the coordinator
  has been dead ~3x the timeout (anyone may run — liveness backstop).
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

import numpy as np

from .ops.ballot import ballot_coord
from .paxos_config import PC
from .utils.config import Config


class FailureDetector:
    def __init__(
        self,
        my_id: int,
        node_ids: Iterable[int],
        timeout_s: Optional[float] = None,
    ):
        self.my_id = int(my_id)
        if timeout_s is None:
            timeout_s = Config.get_float(PC.FAILURE_DETECTION_TIMEOUT_S)
        self.timeout_s = timeout_s
        self.long_dead_factor = Config.get_float(PC.COORDINATOR_LONG_DEAD_FACTOR)
        # explicit ping period if configured; defaults to timeout/2
        # (FailureDetection.java:62-79)
        self._ping_period_s = (
            Config.get_float(PC.PING_PERIOD_S)
            if Config.is_set(PC.PING_PERIOD_S) else timeout_s / 2.0
        )
        now = time.time()
        self.last_heard: Dict[int, float] = {int(n): now for n in node_ids}

    @property
    def ping_period_s(self) -> float:
        return self._ping_period_s

    def heard_from(self, node_id: int) -> None:
        self.last_heard[int(node_id)] = time.time()

    def is_node_up(self, node_id: int) -> bool:
        if node_id == self.my_id:
            return True
        t = self.last_heard.get(int(node_id))
        return t is not None and (time.time() - t) < self.timeout_s

    def dead_for(self, node_id: int) -> float:
        if node_id == self.my_id:
            return 0.0
        t = self.last_heard.get(int(node_id))
        return float("inf") if t is None else time.time() - t

    # ---- vectorized election trigger ----------------------------------
    def want_coord(
        self,
        bal: np.ndarray,          # [G] promised ballots (packed)
        member_mask: np.ndarray,  # [G]
        n_replicas: int,
    ) -> np.ndarray:
        """[G] bool: should THIS node start an election for each group."""
        R = n_replicas
        up = np.array([self.is_node_up(r) for r in range(R)], bool)
        long_dead = np.array(
            [self.dead_for(r) > self.timeout_s * self.long_dead_factor
             for r in range(R)], bool,
        )
        coord = np.asarray(ballot_coord(np.asarray(bal))) % R
        mask = np.asarray(member_mask)
        # a coordinator that is alive but NOT a member of the group (left
        # behind by elastic membership churn / a heal that shrank the
        # set) will never serve it — treat exactly like a dead one, long-
        # dead included (any member may run; preemption sorts the race).
        # Without this the group wedges forever: entries forward every
        # proposal to a node that no longer hosts the row, and no
        # election ever fires because the node still answers pings
        # (chaos-soak find, seed 20260730).
        coord_member = ((mask >> coord) & 1) == 1
        coord_down = ~up[coord] | ~coord_member
        coord_long_dead = long_dead[coord] | ~coord_member
        # next-in-line: the cyclically-next member id after the dead coord
        im_member = ((mask >> self.my_id) & 1) == 1
        next_rr = np.copy(coord)
        for step in range(1, R + 1):
            cand = (coord + step) % R
            is_member = ((mask >> cand) & 1) == 1
            cand_up = up[cand]
            pick = (next_rr == coord) & is_member & cand_up
            next_rr = np.where(pick, cand, next_rr)
        im_next = next_rr == self.my_id
        return im_member & coord_down & (im_next | coord_long_dead)
