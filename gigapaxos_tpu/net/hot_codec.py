"""Binary client-plane hot-path codec ('R'/'S' frames).

The serving hot path carries two frame shapes at rate: client request
batches in and response batches out.  As JSON ('J' frames) each costs a
``json.dumps``/``json.loads`` plus a per-item dict — at capacity that
per-request constant IS the system throughput (the reference sidesteps
it with hand-rolled byte layouts, ``RequestPacket.toBytes`` /
``PaxosPacketDemultiplexerFast.java``).  These fixed-layout frames
replace it:

* ``R`` — request batch: ``sender:i32 count:u32`` then per item
  ``rid:u64 flags:u8 name_len:u16 value_len:u32 name value [trace]``
  (flags bit0 = stop, bit1 = trace context present);
* ``S`` — response batch: ``sender:i32 count:u32`` then per item
  ``rid:u64 err:u8 has:u8 name_len:u16 resp_len:u32 name resp [trace]``
  (has bit0 = response present, bit1 = trace context present).

``[trace]`` is the OPTIONAL cross-node trace context
(``obs/reqtrace.py``): ``tid:u64 origin:i32 hop:u8`` appended after the
item's payload only when the bit is set.  Untraced items carry no extra
bytes, so frames without trace contexts are byte-identical to the
pre-trace wire format (pinned by the golden-bytes tests).  A traced
request item is the 5-tuple ``(rid, name, value, stop, (tid, origin,
hop))``; a traced response dict carries ``"tc": [tid, origin, hop]``.

Both directions have TWO implementations producing byte-identical wire
frames: the native library (``native/gp_codec.cc`` via ctypes — the
scan/pack runs with the GIL released, so transport threads progress
while the tick thread holds the state lock) and pure Python ``struct``
(``GP_NO_NATIVE=1`` or no toolchain).  Parity is pinned by golden-bytes
and round-trip tests (``tests/test_hot_codec.py``); :func:`status`
reports which implementation is live so a silently missing toolchain
can never masquerade as the fast path (it shows up in the ``stats``
admin op).

Error strings travel as codes (the table below); a response carrying an
error outside the table cannot ride an ``S`` frame — the caller falls
back to the JSON path for that batch (correctness first).
"""

from __future__ import annotations

import ctypes
import struct
from typing import Dict, List, Optional, Tuple

_ENV = struct.Struct("<iI")   # sender:i32, count:u32 (after the kind byte)
_R_ITEM = struct.Struct("<QBHI")   # rid, flags, name_len, value_len
_S_ITEM = struct.Struct("<QBBHI")  # rid, err, has, name_len, resp_len
_TC = struct.Struct("<QiB")        # trace tail: tid, origin, hop

STOP_FLAG = 0x01
TRACE_FLAG = 0x02  # in R `flags` and S `has`: 13-byte trace tail follows

# error-string table (the only errors the serving path emits); 0 = none
ERR_CODES: Dict[str, int] = {"overload": 1, "unknown_name": 2,
                             "exhausted": 3}
ERR_STRINGS: Dict[int, str] = {v: k for k, v in ERR_CODES.items()}

# request item: (request_id, name, value, stop) — or the traced 5-tuple
# (request_id, name, value, stop, (tid, origin, hop))
ReqItem = Tuple


def _lib() -> Optional[ctypes.CDLL]:
    from ..native import codec_lib

    return codec_lib()


def native_active() -> bool:
    return _lib() is not None


def status() -> Dict:
    """Which codec implementation is live (the ``stats`` admin-op row)."""
    return {
        "binary_frames": True,
        "native": native_active(),
        "impl": "gp_codec.so" if native_active() else "python-struct",
    }


# ---------------------------------------------------------------------------
# request batches ('R')
# ---------------------------------------------------------------------------
def encode_request_batch(sender: int, items: List[ReqItem]) -> bytes:
    lib = _lib()
    if lib is not None:
        return _encode_req_native(lib, sender, items)
    parts = [b"R", _ENV.pack(int(sender), len(items))]
    for item in items:
        rid, name, value, stop = item[:4]
        tc = item[4] if len(item) > 4 else None
        nb = name.encode("utf-8")
        vb = value.encode("utf-8")
        flags = (STOP_FLAG if stop else 0) | (TRACE_FLAG if tc else 0)
        parts.append(_R_ITEM.pack(int(rid), flags, len(nb), len(vb)))
        parts.append(nb)
        parts.append(vb)
        if tc:
            parts.append(_TC.pack(int(tc[0]), int(tc[1]), int(tc[2])))
    return b"".join(parts)


def _encode_req_native(lib, sender: int, items: List[ReqItem]) -> bytes:
    n = len(items)
    rids = (ctypes.c_uint64 * n)()
    flags = (ctypes.c_uint8 * n)()
    name_ptrs = (ctypes.c_char_p * n)()
    name_lens = (ctypes.c_uint16 * n)()
    val_ptrs = (ctypes.c_char_p * n)()
    val_lens = (ctypes.c_uint32 * n)()
    tids = (ctypes.c_uint64 * n)()
    origins = (ctypes.c_int32 * n)()
    hops = (ctypes.c_uint8 * n)()
    cap = 9 + 15 * n
    # the encoded bytes objects must outlive the call (c_char_p holds a
    # borrowed pointer) — keep them pinned in a list until pack returns
    pin = []
    for i, item in enumerate(items):
        rid, name, value, stop = item[:4]
        tc = item[4] if len(item) > 4 else None
        nb = name.encode("utf-8")
        vb = value.encode("utf-8")
        pin.append(nb)
        pin.append(vb)
        rids[i] = int(rid)
        flags[i] = (STOP_FLAG if stop else 0) | (TRACE_FLAG if tc else 0)
        name_ptrs[i] = nb
        name_lens[i] = len(nb)
        val_ptrs[i] = vb
        val_lens[i] = len(vb)
        cap += len(nb) + len(vb)
        if tc:
            tids[i] = int(tc[0])
            origins[i] = int(tc[1])
            hops[i] = int(tc[2]) & 0xFF
            cap += _TC.size
    out = (ctypes.c_uint8 * cap)()
    wrote = lib.gpc_pack_req(
        out, cap, int(sender), n, rids, flags,
        name_ptrs, name_lens, val_ptrs, val_lens,
        tids, origins, hops,
    )
    if wrote < 0:  # cannot happen with the exact cap; belt and braces
        raise ValueError("gpc_pack_req: buffer overflow")
    return bytes(bytearray(out)[:wrote])


def decode_request_batch(payload: bytes) -> Tuple[int, List[ReqItem]]:
    """-> (sender, [(rid, name, value, stop[, tc]), ...]); raises
    ValueError on a malformed frame (the caller drops it loudly, like
    blob skew).  Traced items come back as 5-tuples with
    ``tc = (tid, origin, hop)``; untraced items stay 4-tuples."""
    lib = _lib()
    if lib is not None:
        return _decode_req_native(lib, payload)
    if len(payload) < 9 or payload[:1] != b"R":
        raise ValueError("malformed R frame")
    sender, count = _ENV.unpack_from(payload, 1)
    off = 9
    items: List[ReqItem] = []
    try:
        for _ in range(count):
            rid, flags, nl, vl = _R_ITEM.unpack_from(payload, off)
            off += _R_ITEM.size
            name = payload[off:off + nl].decode("utf-8")
            off += nl
            value = payload[off:off + vl].decode("utf-8")
            off += vl
            if off > len(payload):
                raise ValueError("truncated R frame")
            if flags & TRACE_FLAG:
                tid, origin, hop = _TC.unpack_from(payload, off)
                off += _TC.size
                items.append((rid, name, value, bool(flags & STOP_FLAG),
                              (tid, origin, hop)))
            else:
                items.append((rid, name, value, bool(flags & STOP_FLAG)))
    except struct.error as e:
        raise ValueError(f"malformed R frame: {e}") from e
    if off != len(payload):
        raise ValueError("R frame has trailing bytes")
    return sender, items


def _decode_req_native(lib, payload: bytes) -> Tuple[int, List[ReqItem]]:
    if len(payload) < 9:
        raise ValueError("malformed R frame")
    (count,) = struct.unpack_from("<I", payload, 5)
    if count > (len(payload) - 9) // _R_ITEM.size + 1:
        # declared count can't fit in the frame: reject BEFORE sizing the
        # index buffer off an attacker-controlled u32
        raise ValueError("malformed R frame (count)")
    idx = (ctypes.c_int64 * (9 * max(1, count)))()
    n = lib.gpc_req_index(payload, len(payload), idx, count)
    if n < 0:
        raise ValueError("malformed R frame (native index)")
    (sender,) = struct.unpack_from("<i", payload, 1)
    items: List[ReqItem] = []
    for i in range(n):
        o = i * 9
        no, nl, vo, vl = idx[o + 2], idx[o + 3], idx[o + 4], idx[o + 5]
        base = (
            idx[o], payload[no:no + nl].decode("utf-8"),
            payload[vo:vo + vl].decode("utf-8"),
            bool(idx[o + 1] & STOP_FLAG),
        )
        if idx[o + 1] & TRACE_FLAG:
            base += ((idx[o + 6], int(idx[o + 7]), int(idx[o + 8])),)
        items.append(base)
    return sender, items


# ---------------------------------------------------------------------------
# response batches ('S')
# ---------------------------------------------------------------------------
def encodable_response(item: Dict) -> bool:
    """True when this response item fits the fixed layout (known error
    code, string-or-None response)."""
    err = item.get("error")
    if err is not None and err not in ERR_CODES:
        return False
    resp = item.get("response")
    return resp is None or isinstance(resp, str)


def encode_response_batch(sender: int, items: List[Dict]) -> bytes:
    """``items`` are the server's buffered response dicts
    (request_id/response/name[/error][/tc]).  Caller must pre-screen with
    :func:`encodable_response` and take the JSON path otherwise."""
    lib = _lib()
    if lib is not None:
        return _encode_resp_native(lib, sender, items)
    parts = [b"S", _ENV.pack(int(sender), len(items))]
    for item in items:
        nb = str(item.get("name") or "").encode("utf-8")
        resp = item.get("response")
        tc = item.get("tc")
        rb = b"" if resp is None else resp.encode("utf-8")
        parts.append(_S_ITEM.pack(
            int(item["request_id"]),
            ERR_CODES.get(item.get("error") or "", 0),
            (0 if resp is None else 1) | (TRACE_FLAG if tc else 0),
            len(nb), len(rb),
        ))
        parts.append(nb)
        parts.append(rb)
        if tc:
            parts.append(_TC.pack(int(tc[0]), int(tc[1]), int(tc[2])))
    return b"".join(parts)


def _encode_resp_native(lib, sender: int, items: List[Dict]) -> bytes:
    n = len(items)
    rids = (ctypes.c_uint64 * n)()
    errs = (ctypes.c_uint8 * n)()
    has = (ctypes.c_uint8 * n)()
    name_ptrs = (ctypes.c_char_p * n)()
    name_lens = (ctypes.c_uint16 * n)()
    resp_ptrs = (ctypes.c_char_p * n)()
    resp_lens = (ctypes.c_uint32 * n)()
    tids = (ctypes.c_uint64 * n)()
    origins = (ctypes.c_int32 * n)()
    hops = (ctypes.c_uint8 * n)()
    cap = 9 + 16 * n
    pin = []
    for i, item in enumerate(items):
        nb = str(item.get("name") or "").encode("utf-8")
        resp = item.get("response")
        tc = item.get("tc")
        rb = b"" if resp is None else resp.encode("utf-8")
        pin.append(nb)
        pin.append(rb)
        rids[i] = int(item["request_id"])
        errs[i] = ERR_CODES.get(item.get("error") or "", 0)
        has[i] = (0 if resp is None else 1) | (TRACE_FLAG if tc else 0)
        name_ptrs[i] = nb
        name_lens[i] = len(nb)
        resp_ptrs[i] = rb
        resp_lens[i] = len(rb)
        cap += len(nb) + len(rb)
        if tc:
            tids[i] = int(tc[0])
            origins[i] = int(tc[1])
            hops[i] = int(tc[2]) & 0xFF
            cap += _TC.size
    out = (ctypes.c_uint8 * cap)()
    wrote = lib.gpc_pack_resp(
        out, cap, int(sender), n, rids, errs, has,
        name_ptrs, name_lens, resp_ptrs, resp_lens,
        tids, origins, hops,
    )
    if wrote < 0:
        raise ValueError("gpc_pack_resp: buffer overflow")
    return bytes(bytearray(out)[:wrote])


def decode_response_batch(payload: bytes) -> Tuple[int, List[Dict]]:
    """-> (sender, [response dicts shaped like the JSON path's]), so the
    client's ``_on_response`` consumes either wire format unchanged.
    Traced responses carry ``"tc": [tid, origin, hop]``."""
    lib = _lib()
    if lib is not None:
        return _decode_resp_native(lib, payload)
    if len(payload) < 9 or payload[:1] != b"S":
        raise ValueError("malformed S frame")
    sender, count = _ENV.unpack_from(payload, 1)
    off = 9
    items: List[Dict] = []
    try:
        for _ in range(count):
            rid, err, has, nl, rl = _S_ITEM.unpack_from(payload, off)
            off += _S_ITEM.size
            name = payload[off:off + nl].decode("utf-8")
            off += nl
            resp = payload[off:off + rl].decode("utf-8") if has & 1 else None
            off += rl
            if off > len(payload):
                raise ValueError("truncated S frame")
            item: Dict = {"request_id": rid, "response": resp, "name": name}
            if err:
                item["error"] = ERR_STRINGS[err]
            if has & TRACE_FLAG:
                tid, origin, hop = _TC.unpack_from(payload, off)
                off += _TC.size
                item["tc"] = [tid, origin, hop]
            items.append(item)
    except struct.error as e:
        raise ValueError(f"malformed S frame: {e}") from e
    if off != len(payload):
        raise ValueError("S frame has trailing bytes")
    return sender, items


def _decode_resp_native(lib, payload: bytes) -> Tuple[int, List[Dict]]:
    if len(payload) < 9:
        raise ValueError("malformed S frame")
    (count,) = struct.unpack_from("<I", payload, 5)
    if count > (len(payload) - 9) // _S_ITEM.size + 1:
        raise ValueError("malformed S frame (count)")
    idx = (ctypes.c_int64 * (10 * max(1, count)))()
    n = lib.gpc_resp_index(payload, len(payload), idx, count)
    if n < 0:
        raise ValueError("malformed S frame (native index)")
    (sender,) = struct.unpack_from("<i", payload, 1)
    items: List[Dict] = []
    for i in range(n):
        o = i * 10
        no, nl, ro, rl = idx[o + 3], idx[o + 4], idx[o + 5], idx[o + 6]
        item: Dict = {
            "request_id": idx[o],
            "response": (
                payload[ro:ro + rl].decode("utf-8")
                if idx[o + 2] & 1 else None
            ),
            "name": payload[no:no + nl].decode("utf-8"),
        }
        if idx[o + 1]:
            item["error"] = ERR_STRINGS[int(idx[o + 1])]
        if idx[o + 2] & TRACE_FLAG:
            item["tc"] = [idx[o + 7], int(idx[o + 8]), int(idx[o + 9])]
        items.append(item)
    return sender, items
