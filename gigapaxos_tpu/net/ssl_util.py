"""TLS context construction from the flag system.

Re-creation of the reference's SSL mode selection
(``SSLDataProcessingWorker.java:59`` modes CLEAR / SERVER_AUTH /
MUTUAL_AUTH, configured at ``PaxosConfig.java:548-553``) on Python's
``ssl`` module with PEM files instead of JKS keystores:

* ``SERVER_AUTH`` — listeners present ``SSL_CERT_FILE``; dialers verify
  against ``SSL_CA_FILE``.
* ``MUTUAL_AUTH`` — additionally, listeners REQUIRE a peer certificate
  chained to ``SSL_CA_FILE``, and dialers present their own cert (so
  every mesh/client connection is mutually authenticated).

The mesh needs both a server and a client context per node (each peer
both listens and dials — one context cannot play both TLS roles).
"""

from __future__ import annotations

import ssl
from typing import Optional, Tuple

from ..paxos_config import PC
from ..utils.config import Config

MODES = ("CLEAR", "SERVER_AUTH", "MUTUAL_AUTH")


def _paths() -> Tuple[str, str, str]:
    return (
        Config.get_str(PC.SSL_KEY_FILE),
        Config.get_str(PC.SSL_CERT_FILE),
        Config.get_str(PC.SSL_CA_FILE),
    )


def _make_contexts(mode: str) -> Tuple[
    Optional[ssl.SSLContext], Optional[ssl.SSLContext]
]:
    """Single source of truth for (listener, dialer) context wiring."""
    if mode not in MODES:
        raise ValueError(f"unknown SSL mode {mode!r} (want one of {MODES})")
    if mode == "CLEAR":
        return None, None
    key, cert, ca = _paths()
    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(cert, key)
    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client.load_verify_locations(ca)
    client.check_hostname = False  # node identity = address book, not CN
    if mode == "MUTUAL_AUTH":
        server.load_verify_locations(ca)
        server.verify_mode = ssl.CERT_REQUIRED
        client.load_cert_chain(cert, key)
    return server, client


def build_ssl_contexts() -> Tuple[
    Optional[ssl.SSLContext], Optional[ssl.SSLContext]
]:
    """(server_ctx, client_ctx) for the configured SSL_MODE, or
    (None, None) under CLEAR."""
    return _make_contexts(Config.get_str(PC.SSL_MODE).upper() or "CLEAR")


def client_plane_split() -> bool:
    """True when CLIENT_SSL_MODE is set: nodes open a SEPARATE
    client-facing listener at port + CLIENT_PORT_OFFSET running that
    mode (the reference's per-plane port split,
    ``PaxosConfig.java:219-224``)."""
    return bool(Config.get_str(PC.CLIENT_SSL_MODE).strip())


def client_plane_mode() -> str:
    mode = Config.get_str(PC.CLIENT_SSL_MODE).strip().upper()
    return mode or (Config.get_str(PC.SSL_MODE).upper() or "CLEAR")


def build_client_plane_contexts() -> Tuple[
    Optional[ssl.SSLContext], Optional[ssl.SSLContext]
]:
    """(server_ctx, client_ctx) for the client-facing listener's mode."""
    return _make_contexts(client_plane_mode())


def client_ssl_context() -> Optional[ssl.SSLContext]:
    """Dialer-side context for CLIENTS (PaxosClientAsync /
    ReconfigurableAppClient): the client-plane mode when the port split
    is configured, else the mesh mode; None under CLEAR.  Under
    MUTUAL_AUTH the client must hold its own cert."""
    return _make_contexts(client_plane_mode())[1]
