"""Wire codecs for the host transport.

Two frame families share the TCP substrate (ref: the reference mixes JSON
and hand-rolled byte layouts on one NIO channel,
``paxosutil/PaxosPacketDemultiplexerFast.java:1``):

* ``J`` frames — JSON control messages: host-channel deltas, client
  requests/responses, failure-detection pings, admin ops.
* ``D`` frames — packed engine blobs: sender id + tick + raw int32 leaf
  bytes in ``Blob._fields`` order (shapes are static per EngineConfig, so
  no per-leaf headers are needed — the reference's fixed-layout
  ``RequestPacket.toBytes`` idea applied to whole state arrays).  The
  kind byte doubles as the blob SCHEMA version (``B`` was the pre-tag
  layout; ``C`` the pre-compact all-int32 layout; ``D`` is the compact
  exec-anchored layout, ``ops/engine.py`` module docstring): a
  fixed-layout frame from a different schema must be dropped by kind,
  never parsed misaligned — a mixed-version node fails loudly instead
  of feeding misparsed ballots into consensus.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.engine import Blob, EngineConfig, _leaf_shapes, blob_vec_len

_BHDR = struct.Struct(">cIQ")  # kind, sender, tick

# cross-node trace context (Dapper-style, obs/reqtrace.py): an OPTIONAL
# ``"tc": [trace_id, origin_node, hop]`` field on J-frame request bodies
# (client_request[_batch] items, forward/forward_batch, payload gossip).
# Absent = untraced; bodies without it are byte-identical to the
# pre-trace wire format.  The binary R/S frames carry the same triple in
# a fixed 13-byte layout (net/hot_codec.py).
TRACE_KEY = "tc"


def attach_trace(body: Dict, tc) -> Dict:
    """Stamp a trace context onto a request body (no-op when None)."""
    if tc is not None:
        body[TRACE_KEY] = [int(tc[0]), int(tc[1]), int(tc[2])]
    return body


def extract_trace(body: Dict):
    """-> (trace_id, origin, hop) or None; malformed contexts drop (a
    trace field must never break request handling)."""
    tc = body.get(TRACE_KEY)
    if not tc:
        return None
    try:
        return (int(tc[0]), int(tc[1]), int(tc[2]))
    except (TypeError, ValueError, IndexError, KeyError):
        return None


def bump_hop(tc):
    """The per-process-boundary hop increment (forwards re-stamp with
    this so the merged timeline orders hops causally even under clock
    skew)."""
    return None if tc is None else (tc[0], tc[1], tc[2] + 1)


def encode_json(kind: str, sender: int, body: Dict) -> bytes:
    env = {"k": kind, "s": sender, "b": body}
    return b"J" + json.dumps(env, separators=(",", ":")).encode("utf-8")


def decode_kind(payload: bytes) -> str:
    return payload[:1].decode("ascii", "replace")


def decode_json(payload: bytes) -> Tuple[str, int, Dict]:
    env = json.loads(payload[1:].decode("utf-8"))
    return env["k"], int(env["s"]), env["b"]


def blob_shapes(cfg: EngineConfig):
    # derived from the engine's leaf table so the per-leaf codec and the
    # packed-vector codec can never disagree on the wire layout
    return dict(_leaf_shapes(Blob._fields, cfg))


def encode_blob(sender: int, tick: int, blob: Blob) -> bytes:
    parts = [_BHDR.pack(b"D", sender, tick)]
    for leaf in blob:
        parts.append(np.asarray(leaf, np.int32).tobytes())
    return b"".join(parts)


def encode_blob_vec(sender: int, tick: int, vec: np.ndarray) -> bytes:
    """Packed-vector fast path: `vec` is already the frame body (leaf
    C-order ravels in ``Blob._fields`` order — identical bytes to
    :func:`encode_blob`)."""
    return _BHDR.pack(b"D", sender, tick) + np.ascontiguousarray(
        vec, np.int32
    ).tobytes()


def decode_blob_vec(
    payload: bytes, cfg: EngineConfig
) -> Tuple[int, int, np.ndarray]:
    """Zero-split decode for the packed tick path: the frame body IS the
    [N] gathered-row vector.  Same size check as :func:`decode_blob`."""
    kind, sender, tick = _BHDR.unpack_from(payload, 0)
    if kind != b"D":
        raise ValueError(
            f"blob frame schema {kind!r} != expected b'D' "
            "(mixed-version peer; refusing to parse)"
        )
    n = blob_vec_len(cfg)
    if len(payload) != _BHDR.size + 4 * n:
        raise ValueError(
            f"blob frame size {len(payload)} != expected "
            f"{_BHDR.size + 4 * n} (peer blob-schema/config mismatch)"
        )
    return sender, tick, np.frombuffer(payload, np.int32, offset=_BHDR.size)


def decode_blob(payload: bytes, cfg: EngineConfig) -> Tuple[int, int, Blob]:
    kind, sender, tick = _BHDR.unpack_from(payload, 0)
    if kind != b"D":
        raise ValueError(
            f"blob frame schema {kind!r} != expected b'D' "
            "(mixed-version peer; refusing to parse)"
        )
    shapes = blob_shapes(cfg)
    expect = _BHDR.size + 4 * sum(int(np.prod(s)) for s in shapes.values())
    if len(payload) != expect:
        # fixed-layout frame: a size mismatch means the peer runs a
        # different blob schema (version skew) or a different
        # EngineConfig — misaligned leaves would feed garbage ballots
        # into consensus, so reject the frame outright
        raise ValueError(
            f"blob frame size {len(payload)} != expected {expect} "
            "(peer blob-schema/config mismatch)"
        )
    off = _BHDR.size
    leaves = []
    for name in Blob._fields:
        shape = shapes[name]
        n = int(np.prod(shape))
        arr = np.frombuffer(payload, np.int32, count=n, offset=off).reshape(shape)
        off += n * 4
        leaves.append(arr)
    return sender, tick, Blob(*leaves)
