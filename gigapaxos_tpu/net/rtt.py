"""RTT estimation + latency-aware server selection.

API-parity targets: ``nioutils/RTTEstimator`` (EWMA RTT per address) and
``paxosutil/E2ELatencyAwareRedirector.java:18`` (the client-side policy:
send to the lowest-learned-latency server, with a small probe ratio of
random picks so alternatives keep being measured)."""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional


class RTTEstimator:
    """EWMA round-trip estimate per key (server id / address)."""

    ALPHA = 1.0 / 8

    def __init__(self):
        self._rtt: Dict[Any, float] = {}
        self._lock = threading.Lock()

    def record(self, key: Any, rtt_s: float) -> None:
        with self._lock:
            old = self._rtt.get(key)
            self._rtt[key] = (
                rtt_s if old is None else (1 - self.ALPHA) * old
                + self.ALPHA * rtt_s
            )

    def get(self, key: Any) -> Optional[float]:
        with self._lock:
            return self._rtt.get(key)


class LatencyAwareRedirector:
    """Pick the fastest-known candidate, probing randomly at PROBE_RATIO
    so a currently-slow server can redeem itself (E2ELatencyAwareRedirector
    semantics: learned EWMA + probe rate)."""

    PROBE_RATIO = 0.1

    def __init__(self, estimator: Optional[RTTEstimator] = None):
        self.rtt = estimator or RTTEstimator()

    def pick(self, candidates: List[Any]) -> Any:
        if not candidates:
            raise ValueError("no candidates")
        if random.random() < self.PROBE_RATIO:
            return random.choice(candidates)
        unknown = [c for c in candidates if self.rtt.get(c) is None]
        if unknown:
            return random.choice(unknown)  # measure everyone once
        return min(candidates, key=lambda c: self.rtt.get(c))

    def record(self, key: Any, rtt_s: float) -> None:
        self.rtt.record(key, rtt_s)
