"""RTT estimation + latency-aware server selection.

API-parity targets: ``nioutils/RTTEstimator`` (EWMA RTT per address) and
``paxosutil/E2ELatencyAwareRedirector.java:18`` (the client-side policy:
send to the lowest-learned-latency server, with a small probe ratio of
random picks so alternatives keep being measured), plus the echo-probe
orientation of ``Reconfigurator.java:2420`` — estimates can be SEEDED
from active probes so the first pick is already latency-aware instead of
arbitrary (cold start was previously blind until real traffic taught
the EWMA)."""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple


class RTTEstimator:
    """EWMA round-trip estimate per key (server id / address)."""

    ALPHA = 1.0 / 8

    def __init__(self):
        self._rtt: Dict[Any, float] = {}
        self._lock = threading.Lock()

    def record(self, key: Any, rtt_s: float) -> None:
        with self._lock:
            old = self._rtt.get(key)
            self._rtt[key] = (
                rtt_s if old is None else (1 - self.ALPHA) * old
                + self.ALPHA * rtt_s
            )

    def seed(self, key: Any, rtt_s: float) -> bool:
        """Install a probe-derived estimate ONLY when the key is still
        unmeasured (an echo RTT is pure network time; once real traffic
        has taught the EWMA its end-to-end number — queueing included —
        a probe must not drag it back down).  Returns True if seeded."""
        with self._lock:
            if key in self._rtt:
                return False
            self._rtt[key] = float(rtt_s)
            return True

    def get(self, key: Any) -> Optional[float]:
        with self._lock:
            return self._rtt.get(key)

    def pop(self, key: Any) -> None:
        """Drop a key's estimate (e.g. a server removed from the
        cluster — its stale RTT must not keep ranking it)."""
        with self._lock:
            self._rtt.pop(key, None)

    def items(self) -> Iterable[Tuple[Any, float]]:
        with self._lock:
            return list(self._rtt.items())


def _stable_key(c: Any):
    """Deterministic secondary sort key for candidate ids of any type."""
    return (str(type(c).__name__), str(c))


class LatencyAwareRedirector:
    """Pick the fastest-known candidate, probing randomly at PROBE_RATIO
    so a currently-slow server can redeem itself (E2ELatencyAwareRedirector
    semantics: learned EWMA + probe rate).  Exact-RTT ties break
    DETERMINISTICALLY (lowest stable key) — two clients with the same
    measurements pick the same server, and a test can assert the pick."""

    PROBE_RATIO = 0.1

    def __init__(self, estimator: Optional[RTTEstimator] = None):
        self.rtt = estimator or RTTEstimator()

    def pick(self, candidates: List[Any]) -> Any:
        if not candidates:
            raise ValueError("no candidates")
        if random.random() < self.PROBE_RATIO:
            return random.choice(candidates)
        unknown = [c for c in candidates if self.rtt.get(c) is None]
        if unknown:
            return random.choice(unknown)  # measure everyone once
        return min(
            candidates, key=lambda c: (self.rtt.get(c), _stable_key(c))
        )

    def record(self, key: Any, rtt_s: float) -> None:
        self.rtt.record(key, rtt_s)

    def seed(self, key: Any, rtt_s: float) -> bool:
        """Cold-start orientation: adopt an echo-probe RTT unless real
        traffic already measured this key (see RTTEstimator.seed)."""
        return self.rtt.seed(key, rtt_s)
