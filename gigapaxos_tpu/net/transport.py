"""MessageTransport — async TCP message substrate (ref: ``NIOTransport``).

Re-creation of the reference's from-scratch NIO layer
(``nio/NIOTransport.java:115``: single selector thread, non-blocking
connect/accept/read/write, per-destination pending-write queues with
congestion back-pressure, auto-reconnect; wire format = 4-byte magic
preamble + 4-byte length + payload, ``NIOTransport.java:483-524``) on top
of one asyncio event loop running in a dedicated thread, so synchronous
callers (the manager tick loop) can ``send_to_id`` without owning a loop.

Differences by design, not omission: SSL is delegated to asyncio's native
TLS support (``ssl_context`` arg vs the reference's hand-rolled SSLEngine
wrapper, ``SSLDataProcessingWorker.java:59``); byte-order and magic match
no one — this framework's peers only speak to each other.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

MAGIC = 0x47503270  # "GP2p"
_HDR = struct.Struct(">II")  # magic, payload length
MAX_PAYLOAD = 256 * 1024 * 1024
CONGESTION_LIMIT = 4096  # per-peer queued messages before drops (isCongested)

# handler(payload: bytes, sender: (host, port), reply) -> None
# ``reply(bytes)`` queues a frame back on the SAME connection (needed for
# client request/response: clients don't listen on a port).
Handler = Callable[[bytes, Tuple[str, int], Callable[[bytes], None]], None]


class MessageTransport:
    def __init__(
        self,
        my_id: int,
        node_config,
        handler: Handler,
        listen_host: Optional[str] = None,
        listen_port: Optional[int] = None,
        ssl_context=None,
        ssl_server_context=None,
        ssl_client_context=None,
    ):
        self.my_id = int(my_id)
        self.node_config = node_config
        self.handler = handler
        if listen_host is None or listen_port is None:
            listen_host, listen_port = node_config.get_node_address(my_id)
        self.listen_host, self.listen_port = listen_host, int(listen_port)
        # TLS: a mesh peer both LISTENS and DIALS, and asyncio requires a
        # TLS_SERVER context on the listener and a TLS_CLIENT context on
        # outbound connects — one context cannot serve both directions.
        # `ssl_context` remains as a single-role convenience.
        self._ssl_server = ssl_server_context or ssl_context
        self._ssl_client = ssl_client_context or ssl_context
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=f"transport-{my_id}", daemon=True
        )
        self._writers: Dict[Tuple[str, int], asyncio.StreamWriter] = {}
        self._queues: Dict[Tuple[str, int], asyncio.Queue] = {}
        self._senders: Dict[Tuple[str, int], asyncio.Task] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._stopped = False
        self.n_sent = 0
        self.n_rcvd = 0
        self.n_dropped = 0  # congestion drops (NIOInstrumenter analog)
        # WAN emulation hook (JSONDelayEmulator analog, nio/
        # JSONDelayEmulator.java:36-56): delay_fn(addr) -> seconds of
        # artificial link delay before a frame is queued for delivery
        self.delay_fn: Optional[Callable[[Tuple[str, int]], float]] = None

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_server(), self._loop)
        fut.result(timeout=10)
        self._started.set()

    async def _start_server(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.listen_host, self.listen_port,
            ssl=self._ssl_server,
        )
        if self.listen_port == 0 and self._server.sockets:
            # ephemeral bind: report the kernel-chosen port (race-free
            # alternative to probe-and-rebind in tests/tools)
            self.listen_port = self._server.sockets[0].getsockname()[1]

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True

        async def _shutdown():
            if self._server is not None:
                self._server.close()
            # cancel every task on this loop (senders AND the per-connection
            # read handlers — leaving them pending spews "Task was
            # destroyed" / "Event loop is closed" at interpreter exit)
            me = asyncio.current_task()
            for task in asyncio.all_tasks():
                if task is not me:
                    task.cancel()
            for w in self._writers.values():
                try:
                    w.close()
                except Exception:
                    pass

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop).result(5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)

    # ---- receive path --------------------------------------------------
    # reply-path write-buffer cap: a slow client must not buffer replies
    # unboundedly in its connection's writer (congestion -> drop, like the
    # forward path; clients retransmit)
    REPLY_BUFFER_LIMIT = 8 * 1024 * 1024

    async def _on_connection(self, reader: asyncio.StreamReader, writer):
        peer = writer.get_extra_info("peername") or ("?", 0)

        def reply(payload: bytes) -> None:
            def _w():
                try:
                    if writer.transport.get_write_buffer_size() \
                            > self.REPLY_BUFFER_LIMIT:
                        self.n_dropped += 1
                        return
                    writer.write(_HDR.pack(MAGIC, len(payload)) + payload)
                except Exception:
                    self.n_dropped += 1
            self._loop.call_soon_threadsafe(_w)

        try:
            while True:
                hdr = await reader.readexactly(_HDR.size)
                magic, length = _HDR.unpack(hdr)
                if magic != MAGIC or length > MAX_PAYLOAD:
                    break  # protocol violation: drop the connection
                payload = await reader.readexactly(length)
                self.n_rcvd += 1
                try:
                    self.handler(payload, peer, reply)
                except Exception:
                    pass  # handler errors must not kill the read loop
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            try:
                writer.close()
            except Exception:
                pass  # loop may already be closing (shutdown teardown)

    # ---- send path -----------------------------------------------------
    def send_to_id(self, node_id: int, payload: bytes) -> bool:
        """Queue for delivery to a node id; False when congested/unknown."""
        if node_id not in self.node_config:
            return False
        return self.send_to_address(
            self.node_config.get_node_address(node_id), payload
        )

    def send_to_address(self, addr: Tuple[str, int], payload: bytes,
                        delay: float = 0.0) -> bool:
        """Queue a frame; `delay` postpones the enqueue (chunk pacing /
        emulation) on top of any configured delay_fn link delay."""
        if self._stopped:
            return False
        addr = (addr[0], int(addr[1]))
        if self.delay_fn is not None:
            delay += self.delay_fn(addr)
        if delay > 0:
            self._loop.call_soon_threadsafe(
                self._loop.call_later, delay, self._enqueue, addr, payload
            )
        else:
            self._loop.call_soon_threadsafe(self._enqueue, addr, payload)
        return True

    def _enqueue(self, addr: Tuple[str, int], payload: bytes) -> None:
        q = self._queues.get(addr)
        if q is None:
            q = asyncio.Queue()
            self._queues[addr] = q
            self._senders[addr] = self._loop.create_task(self._sender(addr, q))
        if q.qsize() >= CONGESTION_LIMIT:
            self.n_dropped += 1  # congestion: drop, like the reference
            return
        q.put_nowait(payload)

    def is_congested(self, node_id: int) -> bool:
        try:
            addr = self.node_config.get_node_address(node_id)
        except KeyError:
            return True
        q = self._queues.get((addr[0], int(addr[1])))
        return q is not None and q.qsize() >= CONGESTION_LIMIT

    async def _sender(self, addr: Tuple[str, int], q: asyncio.Queue) -> None:
        """Per-peer writer with auto-reconnect (pending-writes analog)."""
        writer: Optional[asyncio.StreamWriter] = None
        while not self._stopped:
            payload = await q.get()
            for _attempt in (0, 1):
                if writer is None:
                    try:
                        _r, writer = await asyncio.open_connection(
                            addr[0], addr[1], ssl=self._ssl_client
                        )
                        self._writers[addr] = writer
                    except OSError:
                        writer = None
                        await asyncio.sleep(0.05)
                        continue
                try:
                    writer.write(_HDR.pack(MAGIC, len(payload)) + payload)
                    await writer.drain()
                    self.n_sent += 1
                    break
                except (ConnectionError, OSError):
                    try:
                        writer.close()
                    except Exception:
                        pass
                    writer = None  # retry once with a fresh connection
