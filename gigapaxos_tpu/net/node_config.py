"""NodeConfig — the id -> (host, port) address book.

Ref: ``nio/interfaces/NodeConfig.java:29`` and the properties scheme
``active.NAME=host:port`` / ``reconfigurator.NAME=host:port``
(SURVEY.md §5, ``utils/Config``).  Node ids here are small ints (they
double as mesh/ballot coordinates); names map to ids in registration
order, mirroring the reference's string-node-id to int compression
(``paxosutil/IntegerMap.java:40``).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..utils.config import Config


class NodeConfig:
    def __init__(self, addresses: Optional[Dict[int, Tuple[str, int]]] = None):
        self._addr: Dict[int, Tuple[str, int]] = dict(addresses or {})
        self._names: Dict[int, str] = {}

    @classmethod
    def from_properties(cls, prefix: str = "active") -> "NodeConfig":
        """Build from ``{prefix}.NAME=host:port`` config entries; ids are
        assigned by sorted name order (deterministic across nodes)."""
        nc = cls()
        entries = Config.node_addresses(prefix)
        for i, name in enumerate(sorted(entries)):
            nc._addr[i] = entries[name]
            nc._names[i] = name
        return nc

    def add(self, node_id: int, host: str, port: int, name: str = "") -> None:
        self._addr[int(node_id)] = (host, int(port))
        if name:
            self._names[int(node_id)] = name

    def remove(self, node_id: int) -> None:
        self._addr.pop(int(node_id), None)
        self._names.pop(int(node_id), None)

    def get_node_address(self, node_id: int) -> Tuple[str, int]:
        return self._addr[int(node_id)]

    def get_node_ids(self) -> List[int]:
        return sorted(self._addr)

    def get_node_name(self, node_id: int) -> str:
        return self._names.get(int(node_id), str(node_id))

    def id_of_name(self, name: str) -> Optional[int]:
        for i, n in self._names.items():
            if n == name:
                return i
        return None

    def __contains__(self, node_id: int) -> bool:
        return int(node_id) in self._addr

    def __len__(self) -> int:
        return len(self._addr)
