"""Host networking — the DCN/loopback transport (ref: ``nio/``, SURVEY §2.3).

On real TPU pods the *consensus* traffic (engine blobs) rides ICI via the
SPMD all_gather path (``parallel/spmd.py``); this package carries what the
mesh can't: client I/O, request payloads, control-plane messages, and the
blob exchange itself in loopback / multi-process deployments (the analog
of the reference's N-servers-on-127.0.0.1 mode).
"""

from .node_config import NodeConfig
from .transport import MessageTransport

__all__ = ["MessageTransport", "NodeConfig"]
