"""Device mesh construction for the consensus engine.

The replica axis ('r') is the TPU-native replacement for the reference's
NIO multicast between group members (``nio/NIOTransport.java:115`` et al.,
SURVEY.md §2.3): PREPARE/ACCEPT/ACCEPT_REPLY/COMMIT traffic rides one
``all_gather`` per step over ICI.  The group axis ('g') shards the
million-group state arrays — groups are fully independent, so 'g' needs no
collectives at all (the "group-parallelism" axis of SURVEY.md §2.8).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

REPLICA_AXIS = "r"
GROUP_AXIS = "g"


def pick_mesh_shape(n_devices: int, n_replicas: Optional[int] = None) -> Tuple[int, int]:
    """Choose (group_shards, replicas): replica axis 3 when it divides the
    device count (the BASELINE v5e 3-acceptor layout), else 2, else 1."""
    if n_replicas is None:
        for r in (3, 2, 1):
            if n_devices % r == 0:
                n_replicas = r
                break
    if n_devices % n_replicas:
        raise ValueError(f"{n_replicas} replicas don't divide {n_devices} devices")
    return n_devices // n_replicas, n_replicas


def make_mesh(
    n_replicas: int,
    n_group_shards: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = jax.devices() if devices is None else list(devices)
    need = n_replicas * n_group_shards
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(n_group_shards, n_replicas)
    return Mesh(arr, (GROUP_AXIS, REPLICA_AXIS))
