"""Device mesh construction for the consensus engine.

The replica axis ('r') is the TPU-native replacement for the reference's
NIO multicast between group members (``nio/NIOTransport.java:115`` et al.,
SURVEY.md §2.3): PREPARE/ACCEPT/ACCEPT_REPLY/COMMIT traffic rides one
``all_gather`` per step over ICI.  The group axis ('g') shards the
million-group state arrays — groups are fully independent, so 'g' needs no
collectives at all (the "group-parallelism" axis of SURVEY.md §2.8).

Two deployment shapes use these axes:

* ``make_mesh(n_replicas, n_group_shards)`` — the 2-D acceptor-per-chip
  mesh: each chip holds ONE replica row of a group shard and the blob
  exchange is an ``all_gather`` over 'r' (``spmd.spmd_step``).
* ``make_group_mesh(n_devices)`` — the 1-D group-sharded mesh: every chip
  holds ALL R replica rows for its G/n slice, so the exchange is the
  device-local stacked blobs and the step has ZERO cross-device
  collectives (``spmd.group_sharded_step``).  This is the weak-scaling
  shape: capacity and throughput both scale with the device count.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

REPLICA_AXIS = "r"
GROUP_AXIS = "g"


def pick_mesh_shape(n_devices: int, n_replicas: Optional[int] = None) -> Tuple[int, int]:
    """Choose (group_shards, replicas): replica axis 3 when it divides the
    device count (the BASELINE v5e 3-acceptor layout), else 2, else 1."""
    if n_replicas is None:
        for r in (3, 2, 1):
            if n_devices % r == 0:
                n_replicas = r
                break
    if n_devices % n_replicas:
        raise ValueError(f"{n_replicas} replicas don't divide {n_devices} devices")
    return n_devices // n_replicas, n_replicas


def make_mesh(
    n_replicas: int,
    n_group_shards: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = jax.devices() if devices is None else list(devices)
    need = n_replicas * n_group_shards
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(n_group_shards, n_replicas)
    return Mesh(arr, (GROUP_AXIS, REPLICA_AXIS))


def make_group_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the group axis only: every device hosts all R replica
    rows for its slice of the G axis (the zero-collective SPMD shape)."""
    devices = jax.devices() if devices is None else list(devices)
    n_devices = len(devices) if n_devices is None else n_devices
    if len(devices) < n_devices:
        raise ValueError(f"need {n_devices} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_devices]), (GROUP_AXIS,))


def describe_state_mesh(leaf) -> Dict:
    """Runtime mesh descriptor of the devices backing one state array —
    {n_devices, shape, platform} for the ``stats`` admin op, so an
    accidentally-unsharded deployment (one device hosting a G meant to be
    spread over a mesh) is visible at runtime, not discovered in an OOM.

    Works on any jax.Array: a NamedSharding reports its mesh axes; a
    single-device array reports {n_devices: 1, shape: {}}."""
    try:
        sharding = leaf.sharding
        dev = sorted(sharding.device_set, key=lambda d: d.id)
        platform = dev[0].platform if dev else "unknown"
        shape: Dict[str, int] = {}
        mesh = getattr(sharding, "mesh", None)
        if mesh is not None:
            shape = {str(k): int(v) for k, v in mesh.shape.items()}
        return {
            "n_devices": len(dev),
            "shape": shape,
            "platform": platform,
        }
    except (AttributeError, TypeError):
        # host numpy array or an abstract leaf: no device residency
        return {"n_devices": 0, "shape": {}, "platform": "host"}
