from .mesh import make_mesh, pick_mesh_shape
from .spmd import (
    group_sharded_step,
    make_step,
    single_chip_step,
    spmd_step,
    stack_states,
)

__all__ = [
    "make_mesh",
    "pick_mesh_shape",
    "make_step",
    "spmd_step",
    "single_chip_step",
    "group_sharded_step",
    "stack_states",
]
