from .mesh import make_mesh, pick_mesh_shape
from .spmd import spmd_step, single_chip_step, stack_states

__all__ = [
    "make_mesh",
    "pick_mesh_shape",
    "spmd_step",
    "single_chip_step",
    "stack_states",
]
