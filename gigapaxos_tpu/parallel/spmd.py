"""SPMD wrappers for the consensus step.

Three execution modes over the same pure :func:`gigapaxos_tpu.ops.engine.step`:

* :func:`spmd_step` — shard_map over a ``(g, r)`` mesh: each replica chip
  holds its own engine state shard; the blob exchange is a single
  ``lax.all_gather`` over the replica axis (ICI).  This is the
  acceptor-per-chip deployment shape (BASELINE.json: 3 chips as acceptors)
  and what the driver's ``dryrun_multichip`` exercises.

* :func:`group_sharded_step` — shard_map over a 1-D ``('g',)`` mesh
  covering ALL devices: each device hosts G/n_shards groups × all R
  replica rows, so the blob "exchange" is the device-local stacked blobs
  and the step has **zero cross-device collectives** (groups are fully
  independent).  This is the weak-scaling headline shape: aggregate
  dec/s and hosted-group capacity both scale ~linearly with the mesh,
  and per-device HBM is ``bytes_per_group x G / n_shards``.  A G that
  does not divide the mesh pads with inert rows (``pad_group_states``)
  which the step keeps frozen (member_mask 0 -> non-member -> no-op).

* :func:`single_chip_step` — all R replica states stacked on one device and
  advanced with ``vmap``; the "gather" is just the stacked blobs.  This is
  the loopback/bench mode on a single TPU chip (the analog of the
  reference's N-nodes-in-one-JVM testing mode, ``PaxosManager.java:108-111``).

Global array convention for SPMD: every state leaf gets a leading replica
axis -> ``[R, G, ...]``; ``spmd_step`` shards ``P('r', 'g')``,
``group_sharded_step`` shards ``P(None, 'g')`` (replica axis device-local).
"""

from __future__ import annotations

from functools import partial
from typing import List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level (replica-check kwarg renamed)
    from jax import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = {"check_vma": False}
except ImportError:  # jax 0.4/0.5: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = {"check_rep": False}

shard_map = _shard_map

from ..ops.engine import EngineConfig, EngineState, StepOutputs, make_blob, step
from .mesh import GROUP_AXIS, REPLICA_AXIS


def stack_states(states: List[EngineState]) -> EngineState:
    """Stack per-replica states into the [R, ...] global layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def build_replica_states(cfg: EngineConfig, coord0=None) -> EngineState:
    """Stacked [R, ...] states with all groups created full-membership.

    The shared state builder for the bench, the driver entry points, and
    tests; ``coord0`` defaults to round-robin by group index."""
    import numpy as np

    from ..ops.engine import init_state
    from ..ops.lifecycle import create_groups

    G, R = cfg.n_groups, cfg.n_replicas
    idx = np.arange(G)
    masks = np.full(G, (1 << R) - 1)
    coord0 = (idx % R).astype(np.int32) if coord0 is None else coord0
    return stack_states([
        create_groups(init_state(cfg), idx, masks, coord0, my_id=rid)
        for rid in range(R)
    ])


def single_chip_step(cfg: EngineConfig, donate: bool = True):
    """vmap-over-replicas step on one device.

    Takes (states [R,...], req_vid [R,G,K], want_coord [R,G]) and returns
    (states', outputs [R,...]).  ``heard`` is an optional [R(recv), R(send)]
    bool delivery matrix for fault injection (the reference drops a crashed
    node's traffic in TESTPaxosConfig.crash/isCrashed,
    ``testing/TESTPaxosConfig.java:563-580``); row i masks which peers'
    blobs replica i consumes this step.  None (the default) means full
    delivery.  A replica always hears itself — the diagonal is forced.

    ``donate=True`` (default) aliases the caller's old stacked states into
    the outputs — halves state HBM (the G=2M capacity lever; a no-op on
    backends that ignore donation) but requires the caller to thread
    states through every call.  Pass ``donate=False`` for a step whose
    input states stay valid across calls (e.g. reusable example args).
    """
    R = cfg.n_replicas
    my_ids = jnp.arange(R, dtype=jnp.int32)

    def _one(state, gathered, heard_row, req, want, my_id):
        return step(state, gathered, heard_row, req, want, my_id, cfg)

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def run(states, req_vid, want_coord, heard=None):
        h = jnp.ones((R, R), bool) if heard is None else (
            jnp.asarray(heard, bool) | jnp.eye(R, dtype=bool)
        )
        blobs = jax.vmap(make_blob)(states)
        return jax.vmap(_one, in_axes=(0, None, 0, 0, 0, 0))(
            states, blobs, h, req_vid, want_coord, my_ids
        )

    return run


def spmd_step(cfg: EngineConfig, mesh: Mesh):
    """shard_map step over the (g, r) mesh.

    Global args: states [R, G, ...] with P('r', 'g'); req_vid [R, G, K];
    want_coord [R, G]; heard (optional) [R(recv), R(send)] bool delivery
    matrix, sharded P('r', None) so each replica shard carries its own
    receive row.  Each shard holds [1, G/gs, ...]; the replica-axis blob
    exchange is one all_gather per step on ICI.  A dropped peer is a heard
    row entry set False: the all_gather still runs (the collective is
    membership-oblivious, like the reference's NIO multicast to a crashed
    node) and the engine masks the dead peer's blob out of every quorum
    (ref fault model: ``testing/TESTPaxosConfig.java:563-580``).  The
    diagonal is forced — a replica always hears itself.
    """
    R = cfg.n_replicas
    rg = P(REPLICA_AXIS, GROUP_AXIS)
    state_spec = EngineState(*([rg] * len(EngineState._fields)))
    out_spec = StepOutputs(*([rg] * len(StepOutputs._fields)))

    n_shards = mesh.shape[GROUP_AXIS]
    if cfg.n_groups % n_shards:
        raise ValueError("n_groups must divide evenly over the group axis")
    local_cfg = cfg._replace(n_groups=cfg.n_groups // n_shards)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            state_spec,
            P(REPLICA_AXIS, GROUP_AXIS, None),
            P(REPLICA_AXIS, GROUP_AXIS),
            P(REPLICA_AXIS, None),
        ),
        out_specs=(state_spec, out_spec),
        **_SHARD_MAP_CHECK_KW,
    )
    def _sharded(states, req_vid, want_coord, heard):
        # local shapes: leaves [1, G_loc, ...]; heard [1, R]
        state = jax.tree.map(lambda x: x[0], states)
        # the exchange payload is the COMPACT blob (4 [G] + 4 [G, W] int32
        # leaves vs the state's 12 + 7): the all_gather moves ~42% fewer
        # ICI bytes per step than the pre-compact layout
        blob = make_blob(state)
        gathered = jax.tree.map(lambda x: lax.all_gather(x, REPLICA_AXIS), blob)
        my_id = lax.axis_index(REPLICA_AXIS).astype(jnp.int32)
        heard_row = heard[0] | (jnp.arange(R) == my_id)
        new_state, out = step(
            state, gathered, heard_row, req_vid[0], want_coord[0], my_id,
            local_cfg,
        )
        expand = lambda x: x[None]
        return jax.tree.map(expand, new_state), jax.tree.map(expand, out)

    # donate the global state shards (see single_chip_step)
    fn = jax.jit(_sharded, donate_argnums=(0,))

    def run(states, req_vid, want_coord, heard=None):
        if heard is None:
            heard = jnp.ones((R, R), bool)
        return fn(states, req_vid, want_coord, jnp.asarray(heard, bool))

    return run


def replicate_inputs(mesh: Mesh, states: EngineState, req_vid, want_coord):
    """Device_put global inputs with the canonical shardings."""
    sh = lambda spec: NamedSharding(mesh, spec)
    states = jax.tree.map(
        lambda x: jax.device_put(x, sh(P(REPLICA_AXIS, GROUP_AXIS))), states
    )
    req_vid = jax.device_put(req_vid, sh(P(REPLICA_AXIS, GROUP_AXIS, None)))
    want_coord = jax.device_put(want_coord, sh(P(REPLICA_AXIS, GROUP_AXIS)))
    return states, req_vid, want_coord


# ---------------------------------------------------------------------------
# Group-sharded SPMD: the G axis partitioned over ALL mesh devices, every
# device holding all R replica rows for its slice — zero cross-device
# collectives (see the module docstring).
# ---------------------------------------------------------------------------


def padded_group_count(n_groups: int, n_shards: int) -> int:
    """Smallest shard-divisible G' >= n_groups (ceil to a multiple)."""
    return -(-n_groups // n_shards) * n_shards


def pad_group_states(cfg: EngineConfig, states: EngineState,
                     n_shards: int) -> EngineState:
    """Pad stacked [R, G, ...] states to a shard-divisible G with INERT
    rows (member_mask 0): the step freezes non-member rows, so padding
    changes no real group's transition and the padded tail stays at its
    init values bit-for-bit."""
    from ..ops.engine import init_state

    Gp = padded_group_count(cfg.n_groups, n_shards)
    if Gp == cfg.n_groups:
        return states
    pad_cfg = cfg._replace(n_groups=Gp - cfg.n_groups)
    pad = stack_states([init_state(pad_cfg) for _ in range(cfg.n_replicas)])
    return jax.tree.map(
        lambda a, b: jnp.concatenate([a, b], axis=1), states, pad
    )


def pad_group_inputs(cfg: EngineConfig, n_shards: int, req_vid, want_coord):
    """Pad [R, G, K] requests (NULL) and [R, G] election pulses (False)
    to the shard-divisible G."""
    from ..ops.engine import NULL as _NULL

    Gp = padded_group_count(cfg.n_groups, n_shards)
    G = cfg.n_groups
    if Gp == G:
        return jnp.asarray(req_vid), jnp.asarray(want_coord)
    R, K = cfg.n_replicas, cfg.req_lanes
    req = jnp.concatenate([
        jnp.asarray(req_vid),
        jnp.full((R, Gp - G, K), _NULL, jnp.int32),
    ], axis=1)
    want = jnp.concatenate([
        jnp.asarray(want_coord),
        jnp.zeros((R, Gp - G), bool),
    ], axis=1)
    return req, want


def strip_group_pad(tree, n_groups: int):
    """Slice the padded G axis (axis 1) back to the real group count —
    host-side readback only; keep the persistent arrays padded."""
    return jax.tree.map(lambda x: x[:, :n_groups], tree)


def shard_group_inputs(mesh: Mesh, cfg: EngineConfig, states: EngineState,
                       req_vid, want_coord):
    """Pad to the mesh's shard count and device_put with the group-sharded
    layout: states/want ``P(None, 'g')``, requests ``P(None, 'g', None)``.
    Returns (states, req_vid, want_coord) ready for group_sharded_step."""
    n_shards = mesh.shape[GROUP_AXIS]
    states = pad_group_states(cfg, states, n_shards)
    req_vid, want_coord = pad_group_inputs(cfg, n_shards, req_vid, want_coord)
    sh = lambda spec: NamedSharding(mesh, spec)
    states = jax.tree.map(
        lambda x: jax.device_put(x, sh(P(None, GROUP_AXIS))), states
    )
    req_vid = jax.device_put(req_vid, sh(P(None, GROUP_AXIS, None)))
    want_coord = jax.device_put(want_coord, sh(P(None, GROUP_AXIS)))
    return states, req_vid, want_coord


def group_sharded_step(cfg: EngineConfig, mesh: Mesh, donate: bool = True):
    """shard_map step over a 1-D ('g',) mesh: G partitioned, R device-local.

    Global args: states [R, Gp, ...] with ``P(None, 'g')`` (Gp = G padded
    up to a multiple of the mesh, ``pad_group_states``); req_vid
    [R, Gp, K]; want_coord [R, Gp]; heard (optional) [R(recv), R(send)]
    bool delivery matrix, replicated (every shard applies the same fault
    pattern — the host FD is per-node, not per-group-shard).

    Each shard runs the single-chip vmap step over its [R, Gp/n, ...]
    slice: the blob "exchange" is the locally stacked blobs, so the body
    contains NO collectives — the compiled step is pure per-device work
    and weak-scales linearly by construction.  ``donate=True`` aliases
    the old state shards into the new ones (per-device HBM stays
    ``bytes_per_group x Gp / n_shards``, one copy)."""
    R = cfg.n_replicas
    n_shards = mesh.shape[GROUP_AXIS]
    Gp = padded_group_count(cfg.n_groups, n_shards)
    local_cfg = cfg._replace(n_groups=Gp // n_shards)
    my_ids = jnp.arange(R, dtype=jnp.int32)

    gspec = P(None, GROUP_AXIS)
    state_spec = EngineState(*([gspec] * len(EngineState._fields)))
    out_spec = StepOutputs(*([gspec] * len(StepOutputs._fields)))

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            state_spec,
            P(None, GROUP_AXIS, None),
            P(None, GROUP_AXIS),
            P(None, None),
        ),
        out_specs=(state_spec, out_spec),
        **_SHARD_MAP_CHECK_KW,
    )
    def _sharded(states, req_vid, want_coord, heard):
        # local shapes: leaves [R, Gp/n, ...]; heard [R, R] (replicated)
        h = heard | jnp.eye(R, dtype=bool)
        blobs = jax.vmap(make_blob)(states)

        def _one(state, heard_row, req, want, my_id):
            return step(state, blobs, heard_row, req, want, my_id, local_cfg)

        return jax.vmap(_one, in_axes=(0, 0, 0, 0, 0))(
            states, h, req_vid, want_coord, my_ids
        )

    fn = jax.jit(_sharded, donate_argnums=(0,) if donate else ())

    def run(states, req_vid, want_coord, heard=None):
        if heard is None:
            heard = jnp.ones((R, R), bool)
        return fn(states, req_vid, want_coord, jnp.asarray(heard, bool))

    return run
