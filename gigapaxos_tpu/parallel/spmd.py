"""The unified, mesh-parameterized consensus step.

ONE factory — :func:`make_step` — builds every execution shape of the pure
:func:`gigapaxos_tpu.ops.engine.step`:

* the **mesh is data**, not a code path: ``None`` runs on one device; a
  ``(g, r)`` mesh shards groups over 'g' and replicas over 'r' (the
  acceptor-per-chip deployment — the cross-replica blob exchange becomes
  an all_gather over 'r' that XLA inserts from the sharding constraints);
  a 1-D ``('g',)`` mesh shards only groups, keeping all R replica rows
  device-local so the step has **zero cross-device collectives** (the
  weak-scaling headline shape).  All three are the same traced program
  under different ``NamedSharding``/``PartitionSpec`` constraints, so the
  engine's all-int32 arithmetic is bit-identical across partitionings.

* ``steps_per_dispatch`` (N >= 1) runs N consensus rounds **per host
  call** over device-resident request/response rings: admission gating,
  dedup lookup, and response selection all happen inside a
  ``lax.fori_loop``, and the host touches one packed request ring
  ``[N, ...]`` going in and one response ring coming out — one Python
  dispatch, one sync, per N engine steps.  N == 1 compiles the exact
  legacy single-step program (no loop machinery), so the default path is
  bit-for-bit the pre-factory step.

Two I/O flavors:

* ``io="stacked"`` — the SPMD/bench face: states are the stacked
  ``[R, G, ...]`` global layout, requests ``[R, G, K]`` (or
  ``[N, R, G, K]`` for N > 1), outputs :class:`StepOutputs` of
  ``[R, ...]`` (or ``[N, R, ...]``) leaves.  Every replica advances each
  substep and the blob exchange is re-read from the advancing states, so
  N stacked substeps are exactly N sequential stacked calls.

* ``io="packed_host"`` — the deployed-runtime face (one replica's state,
  peers' blobs arriving as the packed ``[R, NB]`` gathered matrix == the
  ``D`` wire-frame bodies): returns ``(state', out_rings [N, M],
  blob_vec)``.  Substep 0 consumes the gathered rows exactly as passed;
  substeps >= 1 refresh only MY row from the advancing state while
  peers' rows stay frozen — the semantics of N serial host ticks during
  which no new peer frame lands.

The three pre-factory entry points (``single_chip_step``, ``spmd_step``,
``group_sharded_step``) survive as thin deprecated aliases over the
factory.

Global array convention for SPMD: every state leaf gets a leading replica
axis -> ``[R, G, ...]``; a ``(g, r)`` mesh constrains ``P('r', 'g')``, a
``('g',)`` mesh ``P(None, 'g')`` (replica axis device-local).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.engine import (
    _G_LEAVES,
    EngineConfig,
    EngineState,
    StepOutputs,
    make_blob,
    out_vec_len,
    pack_blob,
    step,
    unpack_gathered,
)
from .mesh import GROUP_AXIS, REPLICA_AXIS


def stack_states(states: List[EngineState]) -> EngineState:
    """Stack per-replica states into the [R, ...] global layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def build_replica_states(cfg: EngineConfig, coord0=None) -> EngineState:
    """Stacked [R, ...] states with all groups created full-membership.

    The shared state builder for the bench, the driver entry points, and
    tests; ``coord0`` defaults to round-robin by group index."""
    import numpy as np

    from ..ops.engine import init_state
    from ..ops.lifecycle import create_groups

    G, R = cfg.n_groups, cfg.n_replicas
    idx = np.arange(G)
    masks = np.full(G, (1 << R) - 1)
    coord0 = (idx % R).astype(np.int32) if coord0 is None else coord0
    return stack_states([
        create_groups(init_state(cfg), idx, masks, coord0, my_id=rid)
        for rid in range(R)
    ])


# ---------------------------------------------------------------------------
# mesh-as-data: sharding constraints instead of per-mesh code paths
# ---------------------------------------------------------------------------


def _mesh_spec(mesh: Mesh, *lead) -> P:
    """PartitionSpec over the leading axes, keeping only names the mesh
    actually has — a ``(g, r)`` mesh yields ``P('r', 'g')`` where a
    ``('g',)`` mesh yields ``P(None, 'g')`` from the same request."""
    return P(*[
        a if (a is not None and a in mesh.axis_names) else None for a in lead
    ])


def _constrain(mesh: Optional[Mesh], tree, *lead):
    """Pin every leaf's leading dims to the mesh (no-op off-mesh).  This
    is the whole mesh parameterization: the traced program is identical;
    only the GSPMD partitioning (and hence the auto-inserted collectives,
    e.g. the 'r' all_gather of the compact blob exchange) changes."""
    if mesh is None:
        return tree
    sh = NamedSharding(mesh, _mesh_spec(mesh, *lead))
    return jax.tree.map(
        lambda x: lax.with_sharding_constraint(x, sh), tree
    )


# ---------------------------------------------------------------------------
# the factory
# ---------------------------------------------------------------------------


def _build_stacked(cfg: EngineConfig, mesh: Optional[Mesh], n_steps: int,
                   donate: bool):
    R = cfg.n_replicas

    def _exchange_step(states, req_vid, want_coord, h):
        # run under the ARRAY group count: padded [R, Gp, ...] states
        # (group-sharded deployments, pad_group_states) step with the
        # engine's internal index planes sized Gp; inert pad rows stay
        # frozen (member_mask 0 -> non-member -> no-op)
        run_cfg = cfg._replace(n_groups=int(states.bal.shape[1]))
        # the exchange payload is the COMPACT blob (4 [G] + 4 [G, W]
        # int32 leaves vs the state's 12 + 7): on a replica-sharded mesh
        # the in_axes=None consumption below is what XLA turns into the
        # all_gather over 'r' — ~42% fewer ICI bytes than pre-compact
        blobs = jax.vmap(make_blob)(states)
        my_ids = jnp.arange(R, dtype=jnp.int32)

        def _one(state, gathered, heard_row, req, want, my_id):
            return step(state, gathered, heard_row, req, want, my_id,
                        run_cfg)

        return jax.vmap(_one, in_axes=(0, None, 0, 0, 0, 0))(
            states, blobs, h, req_vid, want_coord, my_ids
        )

    def _heard(heard):
        # a replica always hears itself — the diagonal is forced (ref
        # fault model: testing/TESTPaxosConfig.java:563-580)
        return jnp.ones((R, R), bool) if heard is None else (
            jnp.asarray(heard, bool) | jnp.eye(R, dtype=bool)
        )

    if n_steps == 1:
        @partial(jax.jit, donate_argnums=(0,) if donate else ())
        def run(states, req_vid, want_coord, heard=None):
            h = _heard(heard)
            states = _constrain(mesh, states, REPLICA_AXIS, GROUP_AXIS)
            new_states, outs = _exchange_step(
                states, req_vid, want_coord, h
            )
            return (
                _constrain(mesh, new_states, REPLICA_AXIS, GROUP_AXIS),
                _constrain(mesh, outs, REPLICA_AXIS, GROUP_AXIS),
            )

        return run

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def run_n(states, req_ring, want_coord, heard=None):
        # req_ring [N, R, G, K]: slab i feeds substep i.  want_coord
        # fires only at substep 0 (an election pulse is a host decision;
        # replaying it every substep would re-bump ballots N times).
        # heard is frozen for the dispatch — the host's delivery view
        # cannot change mid-dispatch by construction.
        h = _heard(heard)
        states = _constrain(mesh, states, REPLICA_AXIS, GROUP_AXIS)
        G = int(states.bal.shape[1])
        W = cfg.window
        outs0 = StepOutputs(*[
            jnp.zeros(
                (n_steps, R) + ((G,) if f in _G_LEAVES else (G, W)),
                jnp.int32,
            )
            for f in StepOutputs._fields
        ])

        def body(i, carry):
            st, outs = carry
            req_i = lax.dynamic_index_in_dim(
                req_ring, i, axis=0, keepdims=False
            )
            want_i = want_coord & (i == 0)
            st, out = _exchange_step(st, req_i, want_i, h)
            outs = jax.tree.map(
                lambda acc, o: lax.dynamic_update_index_in_dim(
                    acc, o, i, axis=0
                ),
                outs, out,
            )
            return st, outs

        new_states, outs = lax.fori_loop(0, n_steps, body, (states, outs0))
        return (
            _constrain(mesh, new_states, REPLICA_AXIS, GROUP_AXIS),
            _constrain(mesh, outs, None, REPLICA_AXIS, GROUP_AXIS),
        )

    return run_n


def _build_packed(cfg: EngineConfig, mesh: Optional[Mesh], n_steps: int,
                  donate: bool, heat: bool):
    R = cfg.n_replicas
    M = out_vec_len(cfg)

    def _pack_out(out):
        return jnp.concatenate([jnp.ravel(leaf) for leaf in out])

    # ONE traced core for both the plain and the heat-carrying entry:
    # the core always folds the [G] activity accumulator (decisions +
    # admissions per group, per substep); the plain entry simply drops
    # that output, and XLA's dead-code elimination strips the adds, so
    # heat=False still compiles the exact legacy program.
    if n_steps == 1:
        # the exact legacy step_host program (plus a trivial [1, M]
        # reshape): one upload, one step, two downloads
        def _core(state, gvec, heard, req_ring, want_coord, my_id,
                  heat_acc):
            state = _constrain(mesh, state, GROUP_AXIS)
            g = unpack_gathered(gvec, cfg)
            new_state, out = step(
                state, g, heard, req_ring[0], want_coord, my_id, cfg=cfg
            )
            heat_acc = _constrain(
                mesh, heat_acc + out.n_committed + out.n_admitted,
                GROUP_AXIS,
            )
            out_rings = _pack_out(out)[None]
            blob_vec = pack_blob(make_blob(new_state))
            return (
                _constrain(mesh, new_state, GROUP_AXIS),
                out_rings, blob_vec, heat_acc,
            )
    else:
        def _core(state, gvec, heard, req_ring, want_coord, my_id,
                  heat_acc):
            state = _constrain(mesh, state, GROUP_AXIS)
            heat_acc = _constrain(mesh, heat_acc, GROUP_AXIS)
            gathered0 = unpack_gathered(gvec, cfg)
            out0 = jnp.zeros((n_steps, M), jnp.int32)

            def body(i, carry):
                st, outs, ht = carry
                # substeps >= 1 refresh MY gathered row from the
                # advancing state; peers' rows stay frozen for the whole
                # dispatch — exactly N serial ticks during which no peer
                # frame lands.  Substep 0 consumes gvec verbatim
                # (bit-parity with N=1 even when the caller's self row
                # is stale).
                g = jax.tree.map(
                    lambda gl, bl: jnp.where(
                        i > 0, gl.at[my_id].set(bl), gl
                    ),
                    gathered0, make_blob(st),
                )
                req_i = lax.dynamic_index_in_dim(
                    req_ring, i, axis=0, keepdims=False
                )
                want_i = want_coord & (i == 0)
                st, out = step(st, g, heard, req_i, want_i, my_id,
                               cfg=cfg)
                outs = lax.dynamic_update_index_in_dim(
                    outs, _pack_out(out), i, axis=0
                )
                ht = ht + out.n_committed + out.n_admitted
                return st, outs, ht

            new_state, out_rings, heat_acc = lax.fori_loop(
                0, n_steps, body, (state, out0, heat_acc)
            )
            blob_vec = pack_blob(make_blob(new_state))
            return (
                _constrain(mesh, new_state, GROUP_AXIS), out_rings,
                blob_vec, _constrain(mesh, heat_acc, GROUP_AXIS),
            )

    if heat:
        # heat-carrying face: the accumulator rides the dispatch like a
        # state leaf (donated alongside it) and is pulled host-side only
        # at the stats cadence — never per tick
        @partial(jax.jit, donate_argnums=(0, 6) if donate else ())
        def run_heat(state, gvec, heard, req_ring, want_coord, my_id,
                     heat_acc):
            return _core(state, gvec, heard, req_ring, want_coord,
                         my_id, heat_acc)

        return run_heat

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def run(state, gvec, heard, req_ring, want_coord, my_id):
        new_state, out_rings, blob_vec, _ = _core(
            state, gvec, heard, req_ring, want_coord, my_id,
            jnp.zeros((cfg.n_groups,), jnp.int32),
        )
        return new_state, out_rings, blob_vec

    return run


@functools.lru_cache(maxsize=None)
def _make_step_cached(cfg, mesh, steps_per_dispatch, donate, io, heat):
    from ..obs.device import StepSentinel

    if steps_per_dispatch < 1:
        raise ValueError("steps_per_dispatch must be >= 1")
    if io == "stacked":
        if heat:
            raise ValueError(
                "heat accumulation is a packed_host feature (the "
                "stacked/SPMD face reads StepOutputs directly)"
            )
        fn = _build_stacked(cfg, mesh, steps_per_dispatch, donate)
    elif io == "packed_host":
        fn = _build_packed(cfg, mesh, steps_per_dispatch, donate, heat)
    else:
        raise ValueError(f"unknown io flavor: {io!r}")
    # every factory instance leaves through the retrace/compile sentinel
    # (obs/device.py): each XLA compile is recorded, and a recompile
    # after warmup is surfaced as engine_retraces instead of vanishing
    # into a silently 100x-slower tick
    mesh_tag = "x".join(
        f"{k}{v}" for k, v in mesh.shape.items()
    ) if mesh is not None else "none"
    label = (
        f"make_step[{io} N={steps_per_dispatch} donate={donate} "
        f"heat={heat} mesh={mesh_tag} G={cfg.n_groups} "
        f"R={cfg.n_replicas} W={cfg.window} K={cfg.req_lanes}]"
    )
    return StepSentinel(fn, label=label)


def make_step(cfg: EngineConfig, mesh: Optional[Mesh] = None,
              steps_per_dispatch: int = 1, *, donate: bool = True,
              io: str = "stacked", heat: bool = False):
    """Build THE consensus step: mesh-parameterized, N-steps-resident.

    Parameters
    ----------
    cfg : EngineConfig (static — one compile per config)
    mesh : None for single-device; a ``(g, r)`` or ``('g',)``
        :class:`jax.sharding.Mesh` to pin the GSPMD partitioning (the
        program is the same; only the auto-partitioning changes, so
        results are bit-identical across meshes — all-int32 arithmetic).
    steps_per_dispatch : N >= 1 consensus rounds per host call over
        device-resident request/response rings (``ENGINE_STEPS_PER_
        DISPATCH``).  N == 1 compiles the exact legacy single-step
        program.
    donate : alias the caller's old state buffers into the new state
        (halves state HBM — the G=2M capacity lever); pass ``False``
        when input states must stay valid across calls.
    io : ``"stacked"`` ([R, ...] SPMD/bench face) or ``"packed_host"``
        (one replica + packed [R, NB] gathered vectors — the deployed
        runtime's face; see the module docstring for signatures).
    heat : (``packed_host`` only) carry a donated ``[G]`` int32
        activity accumulator through the dispatch — the step takes it
        as a trailing argument and returns ``heat + n_committed +
        n_admitted`` folded across every substep inside the device
        loop.  The host pulls it at the STATS cadence (obs/device.py
        heat analysis), never per tick.  ``False`` keeps the exact
        legacy signatures.

    Instances are memoized: the same (cfg, mesh, N, donate, io, heat)
    returns the same callable, so jit caches are shared across
    managers.  Every instance is wrapped in a
    :class:`gigapaxos_tpu.obs.device.StepSentinel`, so compiles and
    retraces are recorded process-wide.
    """
    return _make_step_cached(
        cfg, mesh, int(steps_per_dispatch), bool(donate), str(io),
        bool(heat),
    )


# ---------------------------------------------------------------------------
# deprecated thin aliases over the factory (pre-factory entry points)
# ---------------------------------------------------------------------------


def single_chip_step(cfg: EngineConfig, donate: bool = True):
    """Deprecated alias: ``make_step(cfg, None, 1, donate=donate)``.

    All R replica states stacked on one device and advanced with vmap;
    the "gather" is the stacked blobs (the loopback/bench mode — the
    analog of the reference's N-nodes-in-one-JVM testing mode,
    ``PaxosManager.java:108-111``)."""
    return make_step(cfg, None, 1, donate=donate)


def spmd_step(cfg: EngineConfig, mesh: Mesh):
    """Deprecated alias: ``make_step(cfg, mesh, 1)`` over the (g, r)
    mesh (acceptor-per-chip; blob exchange = all_gather over 'r').

    Keeps the historical divisibility contract: the (g, r) deployment
    pins G/gs groups per chip, so a non-divisible G is a config error
    here (the factory itself accepts any G — GSPMD pads internally)."""
    if cfg.n_groups % mesh.shape[GROUP_AXIS]:
        raise ValueError("n_groups must divide evenly over the group axis")
    return make_step(cfg, mesh, 1)


def group_sharded_step(cfg: EngineConfig, mesh: Mesh, donate: bool = True):
    """Deprecated alias: ``make_step(cfg, mesh, 1, donate=donate)`` over
    the 1-D ('g',) mesh — G partitioned, R device-local, zero
    cross-device collectives (the weak-scaling shape).  Pad G to a mesh
    multiple first (``pad_group_states`` / ``shard_group_inputs``) to
    keep per-device slices even."""
    return make_step(cfg, mesh, 1, donate=donate)


# ---------------------------------------------------------------------------
# input placement helpers (unchanged layouts)
# ---------------------------------------------------------------------------


def replicate_inputs(mesh: Mesh, states: EngineState, req_vid, want_coord):
    """Device_put global inputs with the canonical (g, r) shardings."""
    sh = lambda spec: NamedSharding(mesh, spec)
    states = jax.tree.map(
        lambda x: jax.device_put(x, sh(P(REPLICA_AXIS, GROUP_AXIS))), states
    )
    req_vid = jax.device_put(req_vid, sh(P(REPLICA_AXIS, GROUP_AXIS, None)))
    want_coord = jax.device_put(want_coord, sh(P(REPLICA_AXIS, GROUP_AXIS)))
    return states, req_vid, want_coord


def padded_group_count(n_groups: int, n_shards: int) -> int:
    """Smallest shard-divisible G' >= n_groups (ceil to a multiple)."""
    return -(-n_groups // n_shards) * n_shards


def pad_group_states(cfg: EngineConfig, states: EngineState,
                     n_shards: int) -> EngineState:
    """Pad stacked [R, G, ...] states to a shard-divisible G with INERT
    rows (member_mask 0): the step freezes non-member rows, so padding
    changes no real group's transition and the padded tail stays at its
    init values bit-for-bit."""
    from ..ops.engine import init_state

    Gp = padded_group_count(cfg.n_groups, n_shards)
    if Gp == cfg.n_groups:
        return states
    pad_cfg = cfg._replace(n_groups=Gp - cfg.n_groups)
    pad = stack_states([init_state(pad_cfg) for _ in range(cfg.n_replicas)])
    return jax.tree.map(
        lambda a, b: jnp.concatenate([a, b], axis=1), states, pad
    )


def pad_group_inputs(cfg: EngineConfig, n_shards: int, req_vid, want_coord):
    """Pad [R, G, K] requests (NULL) and [R, G] election pulses (False)
    to the shard-divisible G."""
    from ..ops.engine import NULL as _NULL

    Gp = padded_group_count(cfg.n_groups, n_shards)
    G = cfg.n_groups
    if Gp == G:
        return jnp.asarray(req_vid), jnp.asarray(want_coord)
    R, K = cfg.n_replicas, cfg.req_lanes
    req = jnp.concatenate([
        jnp.asarray(req_vid),
        jnp.full((R, Gp - G, K), _NULL, jnp.int32),
    ], axis=1)
    want = jnp.concatenate([
        jnp.asarray(want_coord),
        jnp.zeros((R, Gp - G), bool),
    ], axis=1)
    return req, want


def strip_group_pad(tree, n_groups: int):
    """Slice the padded G axis (axis 1) back to the real group count —
    host-side readback only; keep the persistent arrays padded."""
    return jax.tree.map(lambda x: x[:, :n_groups], tree)


def shard_group_inputs(mesh: Mesh, cfg: EngineConfig, states: EngineState,
                       req_vid, want_coord):
    """Pad to the mesh's shard count and device_put with the group-sharded
    layout: states/want ``P(None, 'g')``, requests ``P(None, 'g', None)``.
    Returns (states, req_vid, want_coord) ready for the group-sharded
    step."""
    n_shards = mesh.shape[GROUP_AXIS]
    states = pad_group_states(cfg, states, n_shards)
    req_vid, want_coord = pad_group_inputs(cfg, n_shards, req_vid, want_coord)
    sh = lambda spec: NamedSharding(mesh, spec)
    states = jax.tree.map(
        lambda x: jax.device_put(x, sh(P(None, GROUP_AXIS))), states
    )
    req_vid = jax.device_put(req_vid, sh(P(None, GROUP_AXIS, None)))
    want_coord = jax.device_put(want_coord, sh(P(None, GROUP_AXIS)))
    return states, req_vid, want_coord
