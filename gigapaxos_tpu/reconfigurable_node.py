"""ReconfigurableNode — deployable AR/RC roles over real sockets.

API-parity target: ``ReconfigurableNode`` (``ReconfigurableNode.java:59,
223-300``) — the server entry point that reads ``active.NAME=host:port`` /
``reconfigurator.NAME=host:port`` from the properties config, boots an
:class:`ActiveReplicaServer` and/or :class:`ReconfiguratorServer` for the
roles this node name holds, and wires the epoch plane through the same
transport demux as the paxos plane.

Topology: actives form one engine cluster (the app RSMs), reconfigurators
another (the RC-record RSM, ``RepliconfigurableReconfiguratorDB`` analog);
each role runs the full :class:`~gigapaxos_tpu.server.PaxosServer` stack
(engine + journal + FD + blob exchange) plus its layer object
(:class:`~gigapaxos_tpu.reconfiguration.active_replica.ActiveReplica` /
:class:`~gigapaxos_tpu.reconfiguration.reconfigurator.Reconfigurator`).
Epoch-plane messages ride ``J`` frames of kind ``epoch`` with the layer
kind/body nested, addressed via the (role, id) books.

Client replies: a reconfigurator op's ack can fire long after the request
(on COMPLETE / DELETE_FINAL) and possibly at a different RC than the one
the client spoke to (ops forward to the record's primary).  The client
address is therefore ("CLIENT", rc_id, token): the RC that owns `token`
replies on the client's live connection; any other RC relays the reply to
rc_id first (the reference solves this with client-socket messengers,
``ReconfigurableAppClientAsync.java:75``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .net.codec import encode_json
from .net.node_config import NodeConfig
from .ops.engine import EngineConfig
from .paxos_config import PC
from .reconfiguration.active_replica import ActiveReplica
from .reconfiguration.coordinator import PaxosReplicaCoordinator
from .reconfiguration.rc_app import RCRecordsApp
from .reconfiguration.reconfigurator import RC_GROUP, Reconfigurator
from .server import PaxosServer
from .utils.config import Config

# reconfigurator-plane kinds a client may send to an RC
RC_CLIENT_KINDS = (
    "create_service", "create_service_batch", "delete_service",
    "reconfigure", "request_actives", "add_active", "remove_active",
)


class _EpochSender:
    """Routes layer sends to the (role, id) address books over a transport."""

    def __init__(self, server: PaxosServer, ar_nodes: NodeConfig,
                 rc_nodes: NodeConfig):
        self.server = server
        self.ar_nodes = ar_nodes
        self.rc_nodes = rc_nodes

    def __call__(self, dst: Tuple, kind: str, body: Dict) -> None:
        role = dst[0]
        if role == "CLIENT":
            self.server._reply_client(tuple(dst), kind, body)
            return
        book = self.ar_nodes if role == "AR" else self.rc_nodes
        nid = int(dst[1])
        if nid not in book:
            return
        frame = encode_json(
            "epoch", self.server.my_id, {"kind": kind, "body": body}
        )
        # streams oversize frames (epoch_final_state can carry a multi-MB
        # app checkpoint — LargeCheckpointer territory)
        self.server.send_frame_to_address(book.get_node_address(nid), frame)


class ActiveReplicaServer(PaxosServer):
    """A PaxosServer hosting the app engine + the ActiveReplica epoch layer
    (``ActiveReplica.java:128`` behind ``ReconfigurableNode.java:274-282``)."""

    def __init__(self, my_id: int, ar_nodes: NodeConfig, rc_nodes: NodeConfig,
                 app, cfg: EngineConfig, **kw):
        super().__init__(my_id, ar_nodes, app, cfg, **kw)
        self.ar_nodes = ar_nodes
        self.rc_nodes = rc_nodes
        self._layer_lock = threading.RLock()
        self.coordinator = PaxosReplicaCoordinator(app, self.manager)
        self.active_replica = ActiveReplica(
            my_id, self.coordinator,
            _EpochSender(self, ar_nodes, rc_nodes),
            rc_ids=rc_nodes.get_node_ids(),
        )
        # LOCK ORDER: transport threads take layer_lock -> manager lock
        # (handle_message -> coordinate/create), so callbacks fired UNDER
        # the manager lock (stop execution inside manager.tick) must not
        # take the layer lock — they are queued and drained at tick time.
        self._evt_lock = threading.Lock()
        self._stop_events: List[Tuple[str, int, int]] = []

        def deferred_stop(name: str, row: int, epoch: int) -> None:
            with self._evt_lock:
                self._stop_events.append((name, row, epoch))

        self.manager.on_stop_executed = deferred_stop
        # app-request REST (HttpActiveReplica analog) at port + offset
        self._http = None
        try:
            from .http_front import start_ar_http

            self._http = start_ar_http(
                self.transport.listen_host,
                self.transport.listen_port
                + Config.get_int(PC.HTTP_PORT_OFFSET),
                lambda name, value, cb: self.manager.propose(
                    name, value, callback=cb
                ),
                overloaded=self.manager.overloaded,
                metrics=self.manager.metrics.render,
            )
        except OSError:
            pass  # HTTP port taken: binary protocol still fully serves

    def stop(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()  # release the bound listen socket
        super().stop()

    def _reply_client(self, dst, kind, body) -> None:
        pass  # ARs never address clients through the epoch plane

    def _on_json(self, k, sender, body, reply) -> bool:
        if super()._on_json(k, sender, body, reply):
            return True
        if k == "epoch":
            with self._layer_lock:
                self.active_replica.handle_message(body["kind"], body["body"])
            return True
        return False

    def _layer_tick(self) -> None:
        with self._evt_lock:
            events, self._stop_events = self._stop_events, []
        with self._layer_lock:
            for name, row, epoch in events:
                self.active_replica._on_stop_executed(name, row, epoch)
            self.active_replica.tick()

    def _echo_load(self) -> Dict:
        # scalar reads only (no lock): a torn read costs one slightly
        # stale load sample, never a crash
        return self.active_replica.load_summary()


class ReconfiguratorServer(PaxosServer):
    """A PaxosServer whose app is the RC-record RSM, plus the Reconfigurator
    orchestration layer (``Reconfigurator.java:125`` behind
    ``ReconfigurableNode.java:283-296``)."""

    def __init__(self, my_id: int, ar_nodes: NodeConfig, rc_nodes: NodeConfig,
                 rc_cfg: EngineConfig, ar_cfg: EngineConfig, **kw):
        self.rc_app = RCRecordsApp()
        super().__init__(my_id, rc_nodes, self.rc_app, rc_cfg, **kw)
        self.ar_nodes = ar_nodes
        self.rc_nodes = rc_nodes
        self._layer_lock = threading.RLock()
        # client-reply registry: token -> (deadline, reply fn)
        self._client_replies: Dict[str, Tuple[float, Callable]] = {}
        self._client_seq = 0
        rc_ids = rc_nodes.get_node_ids()
        ar_ids = ar_nodes.get_node_ids()
        self.reconfigurator = Reconfigurator(
            my_id, self.manager, self.rc_app, ar_ids, rc_ids,
            _EpochSender(self, ar_nodes, rc_nodes),
            ar_n_groups=ar_cfg.n_groups,
            is_node_up=self.fd.is_node_up,
        )
        # LOCK ORDER (see ActiveReplicaServer): on_applied fires inside
        # manager.tick under the manager lock — queue and drain at tick.
        self._evt_lock = threading.Lock()
        self._applied_events: List[Dict] = []
        layer_on_applied = self.rc_app.on_applied  # Reconfigurator._on_applied

        def deferred_applied(op: Dict) -> None:
            with self._evt_lock:
                self._applied_events.append(op)

        self.rc_app.on_applied = deferred_applied
        self._layer_on_applied = layer_on_applied
        # same deferral for restore (checkpoint transfer installs the app
        # state on a transport thread under the manager lock; the ring
        # refresh must run under the layer lock at tick time)
        layer_on_restored = self.rc_app.on_restored
        self._restored_pending = False

        def deferred_restored() -> None:
            with self._evt_lock:
                self._restored_pending = True

        self.rc_app.on_restored = deferred_restored
        self._layer_on_restored = layer_on_restored
        # bootstrap the RC-record RSM (the AR_RC_NODES-style special group,
        # ReconfigurableNode.java:160-181): deterministic row on every RC
        self.manager.create_paxos_instance(RC_GROUP, rc_ids)
        # REST front-end (HttpReconfigurator analog) at port + offset
        self._http = None
        try:
            from .http_front import start_rc_http

            def submit(kind: str, body: Dict, waiter) -> None:
                op = dict(body)
                op["client"] = self._register_client_fn(waiter)
                with self._layer_lock:
                    self.reconfigurator.handle_message(kind, op)

            self._http = start_rc_http(
                self.transport.listen_host,
                self.transport.listen_port
                + Config.get_int(PC.HTTP_PORT_OFFSET),
                submit,
                metrics=self.manager.metrics.render,
                stats=self._layer_stats,
            )
        except OSError:
            pass  # HTTP port taken: binary protocol still fully serves

    def stop(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()  # release the bound listen socket
        super().stop()

    # ---- client replies -------------------------------------------------
    def _register_client(self, reply) -> List:
        """Socket client: replies re-encode as rc_client_reply frames."""
        return self._register_client_fn(
            lambda kind, body: reply(encode_json(
                "rc_client_reply", self.my_id, {"kind": kind, "body": body}
            ))
        )

    def _register_client_fn(self, fn: Callable[[str, Dict], None]) -> List:
        """Register a decoded-reply sink (HTTP workers use this directly)."""
        with self._layer_lock:
            self._client_seq += 1
            token = str(self._client_seq)
            self._client_replies[token] = (
                time.time() + Config.get_float(PC.REQUEST_TIMEOUT_S) * 8,
                fn,
            )
            # opportunistic GC
            if self._client_seq % 64 == 0:
                now = time.time()
                for t in [t for t, (dl, _) in self._client_replies.items()
                          if dl < now]:
                    del self._client_replies[t]
        return ["CLIENT", self.my_id, token]

    def _reply_client(self, dst, kind, body) -> None:
        _role, rc_id, token = dst[0], int(dst[1]), str(dst[2])
        if rc_id != self.my_id:
            # the token lives at the RC the client spoke to — relay
            frame = encode_json("client_reply", self.my_id, {
                "client": list(dst), "kind": kind, "body": body,
            })
            if rc_id in self.rc_nodes:
                self.transport.send_to_address(
                    self.rc_nodes.get_node_address(rc_id), frame
                )
            return
        with self._layer_lock:
            ent = self._client_replies.pop(token, None)
        if ent is not None:
            ent[1](kind, body)

    # ---- demux ----------------------------------------------------------
    def _on_json(self, k, sender, body, reply) -> bool:
        if super()._on_json(k, sender, body, reply):
            return True
        if k == "epoch":
            with self._layer_lock:
                self.reconfigurator.handle_message(body["kind"], body["body"])
            return True
        if k == "rc_client":
            kind = body["kind"]
            if kind not in RC_CLIENT_KINDS:
                return True
            op = dict(body["body"])
            op["client"] = self._register_client(reply)
            with self._layer_lock:
                self.reconfigurator.handle_message(kind, op)
            return True
        if k == "client_reply":
            self._reply_client(tuple(body["client"]), body["kind"], body["body"])
            return True
        return False

    def _layer_tick(self) -> None:
        with self._evt_lock:
            events, self._applied_events = self._applied_events, []
            restored, self._restored_pending = self._restored_pending, False
        with self._layer_lock:
            if restored and self._layer_on_restored is not None:
                self._layer_on_restored()
            for op in events:
                self._layer_on_applied(op)
            self.reconfigurator.tick()

    def _layer_stats(self) -> Dict:
        # PlacementEngine.snapshot is internally locked — safe from admin
        # and HTTP worker threads without the layer lock
        return {"placement": self.reconfigurator.placement.snapshot()}


class ReconfigurableNode:
    """Boot the roles a node name holds (``ReconfigurableNode.java:223-300``).

    ``active.NAME=host:port`` / ``reconfigurator.NAME=host:port`` config
    entries define the cluster; this node starts a server per role its
    NAME appears in.  ``make_app`` builds the Replicable app instance
    (reflection-ctor analog, ``ReconfigurableNode.java:112-130``).
    """

    def __init__(
        self,
        name: str,
        make_app: Callable[[], Any],
        ar_cfg: Optional[EngineConfig] = None,
        rc_cfg: Optional[EngineConfig] = None,
        log_dir: Optional[str] = None,
        **server_kw,
    ):
        self.name = name
        ar_nodes = NodeConfig.from_properties("active")
        rc_nodes = NodeConfig.from_properties("reconfigurator")
        if ar_cfg is None:
            # ENGINE_ROWS is the allocated row count (RAM/HBM cost), NOT
            # the 2M design ceiling — a default CLI boot must be usable
            ar_cfg = EngineConfig(
                n_groups=min(Config.get_int(PC.ENGINE_ROWS),
                             Config.get_int(PC.PINSTANCES_CAPACITY)),
                window=Config.get_int(PC.SLOT_WINDOW),
                req_lanes=8,
                n_replicas=max(len(ar_nodes), 1),
            )
        if rc_cfg is None:
            rc_cfg = EngineConfig(
                n_groups=64, window=Config.get_int(PC.SLOT_WINDOW),
                req_lanes=8, n_replicas=max(len(rc_nodes), 1),
            )
        self.servers: List[PaxosServer] = []
        ar_id = ar_nodes.id_of_name(name)
        rc_id = rc_nodes.id_of_name(name)
        if ar_id is None and rc_id is None:
            raise ValueError(
                f"{name!r} appears in neither active.* nor reconfigurator.*"
            )
        if ar_id is not None:
            n_workers = Config.get_int(PC.SERVING_WORKERS)
            if n_workers > 1:
                # sharded serving: this process becomes the accept/route
                # parent; worker PROCESSES own the engine/journal per
                # name shard (gigapaxos_tpu/serving/).  The RC role (if
                # this node holds one) stays unsharded below.
                from .serving.router import ShardedActiveNode

                self.servers.append(ShardedActiveNode(name, n_workers))
            else:
                self.servers.append(ActiveReplicaServer(
                    ar_id, ar_nodes, rc_nodes, make_app(), ar_cfg,
                    log_dir=(f"{log_dir}/ar{ar_id}" if log_dir else None),
                    **server_kw,
                ))
        if rc_id is not None:
            self.servers.append(ReconfiguratorServer(
                rc_id, ar_nodes, rc_nodes, rc_cfg, ar_cfg,
                log_dir=(f"{log_dir}/rc{rc_id}" if log_dir else None),
                **server_kw,
            ))

    def start(self) -> None:
        for s in self.servers:
            s.start()

    def stop(self) -> None:
        for s in self.servers:
            s.stop()


def main(argv: Optional[List[str]] = None) -> None:
    """CLI entry: ``python -m gigapaxos_tpu.reconfigurable_node NAME...``
    with flags/addresses from the properties file (``GIGAPAXOS_CONFIG``)
    and ``key=value`` CLI overrides (``PaxosServer.main`` analog)."""
    import importlib
    import os
    import signal
    import sys

    from .utils.config import load_default_config_file

    # honor JAX_PLATFORMS=cpu even when a site hook pinned another backend
    # via jax.config (a control-plane node must not fight the data plane
    # for the accelerator)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    argv = sys.argv[1:] if argv is None else argv
    load_default_config_file()
    rest = list(Config.register_args(argv))
    # -c = clean slate (CMD_OPTIONS=-c parity): wipe this node's durable
    # state before booting
    clean_slate = "-c" in rest
    names = [a for a in rest if a != "-c"]
    app_path = Config.get("APPLICATION") or \
        "gigapaxos_tpu.models.apps.NoopPaxosApp"
    mod, _, cls = app_path.rpartition(".")
    app_cls = getattr(importlib.import_module(mod), cls)
    # the enum default names a relative dir; only an EXPLICIT setting
    # turns on durability for CLI nodes (tests/dev default to memory-only)
    log_root = (
        Config.get_str(PC.PAXOS_LOGS_DIR)
        if Config.is_set(PC.PAXOS_LOGS_DIR) else None
    )
    if clean_slate and log_root:
        import shutil

        # wipe ONLY the booted names' state: other nodes on this machine
        # may share the PAXOS_LOGS_DIR root and be alive right now
        for n in names:
            d = os.path.join(log_root, n)
            if os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)
    nodes = [
        ReconfigurableNode(
            n, app_cls,
            log_dir=(os.path.join(log_root, n) if log_root else None),
        )
        for n in names
    ]
    for n in nodes:
        n.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    for n in nodes:
        n.stop()


if __name__ == "__main__":
    main()
