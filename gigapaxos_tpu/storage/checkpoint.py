"""Engine-state snapshots: one .npz of the batched arrays + a JSON sidecar.

The reference checkpoints *per group* into SQL tables (``checkpoint`` /
``prev_checkpoint``, ``SQLPaxosLogger.java:149-152``) because each group
is an object; here the whole engine is a handful of [G]/[G, W] arrays, so
a checkpoint is a single bulk snapshot and recovery a single bulk load
(the SURVEY §7 hard-part (d) answer).  App-level checkpoint strings
(``Replicable.checkpoint``) ride in the sidecar.

Torn-write protection: every snapshot embeds a **generation id** in both
the .npz (``__generation__`` array) and the sidecar (``"generation"``
key).  Both files of the new pair are fully written and fsynced to temp
names *before* any rename; the loader accepts any (snapshot, sidecar)
combination whose generation ids match, picking the highest generation —
so a crash between any two renames still leaves at least one matched
pair (the previous generation) discoverable.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

SNAP = "checkpoint.npz"
META = "checkpoint.meta.json"
PREV_SNAP = "prev_checkpoint.npz"
PREV_META = "prev_checkpoint.meta.json"

MANIFEST = "manifest.json"
PREV_MANIFEST = "prev_manifest.json"
SHARD_PREFIX = "ckpt_"

GEN_KEY = "__generation__"
ROWS_KEY = "__rows__"      # [lo, hi) row range a shard covers
APPS_KEY = "__apps__"      # uint8 view of the shard's app-state JSON


_LEGACY = -1  # marker for pre-generation files (no embedded id)


def _snap_generation(path: str) -> Optional[int]:
    """Generation embedded in a snapshot; _LEGACY if absent; None if unreadable."""
    try:
        with np.load(path) as z:
            if GEN_KEY in z.files:
                return int(z[GEN_KEY])
            return _LEGACY
    except Exception:
        return None


def _meta_generation(path: str) -> Optional[Tuple[int, Dict[str, Any]]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            meta = json.load(f)
        return int(meta.get("generation", _LEGACY)), meta
    except Exception:
        return None


def save_checkpoint(
    directory: str,
    arrays: Dict[str, np.ndarray],
    meta: Dict[str, Any],
    n_shards: int = 1,
) -> None:
    """Atomically persist (arrays, meta), demoting the current pair to prev.

    Write order (each file fsynced before any rename):
      1. new snapshot  -> checkpoint.npz.tmp
      2. new sidecar   -> checkpoint.meta.json.tmp
      3. demote current pair to prev_*
      4. promote the tmp pair to checkpoint.*
    A crash at any point leaves >= 1 generation-matched pair on disk.
    """
    os.makedirs(directory, exist_ok=True)
    if n_shards > 1:
        save_checkpoint_sharded(directory, arrays, meta, n_shards)
        return
    snap = os.path.join(directory, SNAP)
    metaf = os.path.join(directory, META)

    gen = _next_generation(directory)

    meta = dict(meta)
    meta["generation"] = gen
    payload = dict(arrays)
    payload[GEN_KEY] = np.int64(gen)

    tmp = snap + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    tmpm = metaf + ".tmp"
    with open(tmpm, "w", encoding="utf-8") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())

    # Demote current -> prev ONLY as a generation-matched pair: a crash in
    # a previous save can leave an orphan current file (snapshot without
    # its sidecar or vice versa); demoting an orphan would overwrite half
    # of a still-valid prev pair and can strand the directory with zero
    # loadable checkpoints.  Orphans are deleted instead (they were never
    # loadable on their own).
    sg = _snap_generation(snap) if os.path.exists(snap) else None
    m = _meta_generation(metaf) if os.path.exists(metaf) else None
    mg = m[0] if m is not None else None
    if sg is not None and sg == mg:
        os.replace(snap, os.path.join(directory, PREV_SNAP))
        os.replace(metaf, os.path.join(directory, PREV_META))
    else:
        if os.path.exists(snap):
            os.remove(snap)
        if os.path.exists(metaf):
            os.remove(metaf)
    os.replace(tmp, snap)
    os.replace(tmpm, metaf)


def _load_checkpoint_legacy(
    directory: str,
) -> Optional[Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]]:
    """Load the newest valid generation-matched single-pair checkpoint.

    Tries every (snapshot, sidecar) combination so that a crash between
    the demote/promote renames of :func:`save_checkpoint` (which can pair
    e.g. ``prev_checkpoint.npz`` with ``checkpoint.meta.json``) still
    finds the surviving pair; a sidecar is never silently combined with
    a snapshot from a different generation.  Returns (gen, arrays, meta).
    """
    snaps = {}   # name -> (gen, path)
    for name in (SNAP, PREV_SNAP):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            g = _snap_generation(path)
            if g is not None:
                snaps[name] = (g, path)
    metas = {}   # name -> (gen, meta)
    for name in (META, PREV_META):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            m = _meta_generation(path)
            if m is not None:
                metas[name] = m

    # Candidates: any cross combination whose EXPLICIT generations match;
    # legacy files (no embedded id) only pair name-aligned — current with
    # current, prev with prev — since 'both lack an id' proves nothing
    # about belonging together across names.
    candidates = []  # (gen, snap_path, meta)
    for sname, (sg, spath) in snaps.items():
        for mname, (mg, meta) in metas.items():
            aligned = (sname, mname) in ((SNAP, META), (PREV_SNAP, PREV_META))
            if sg == mg != _LEGACY or (sg == mg == _LEGACY and aligned):
                candidates.append((sg, sname == SNAP, spath, meta))
    # highest generation first; at equal gen prefer the current-named pair
    candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)
    for gen, _cur, spath, meta in candidates:
        try:
            with np.load(spath) as z:
                arrays = {k: z[k] for k in z.files if k != GEN_KEY}
            return gen, arrays, meta
        except Exception:
            continue  # corrupt body despite readable header: try next pair
    return None


# ---------------------------------------------------------------------------
# Sharded checkpoints (the recovery plane's on-disk form).
#
# A snapshot is split into N group-range shards, each a self-contained
# .npz holding the engine-array rows [lo, hi) plus that range's app-state
# strings (as embedded JSON bytes), under a single ``manifest.json``
# naming every shard with its content hash.  Write order: every shard is
# fully written + fsynced under a generation-unique name, THEN the
# manifest lands atomically (tmp + fsync + demote current->prev +
# rename).  A torn shard (crash mid-write, bit rot) fails its manifest
# hash at load and recovery falls back to the previous generation's
# manifest — an earlier journal anchor, never a half-written snapshot.
# ---------------------------------------------------------------------------


def _shard_file(gen: int, idx: int) -> str:
    return f"{SHARD_PREFIX}g{gen:08d}_s{idx:04d}.npz"


def _manifest_at(directory: str, name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(directory, name), "r", encoding="utf-8") as f:
            m = json.load(f)
        return m if isinstance(m, dict) and "shards" in m else None
    except Exception:
        return None


def _next_generation(directory: str) -> int:
    """1 + the highest generation visible in ANY format (legacy pairs and
    sharded manifests share one counter, so toggling the shard knob can
    never resurrect a stale older-format snapshot as 'newest')."""
    gen = 0
    for name in (SNAP, PREV_SNAP):
        g = _snap_generation(os.path.join(directory, name))
        if g is not None:
            gen = max(gen, g)
    for name in (META, PREV_META):
        m = _meta_generation(os.path.join(directory, name))
        if m is not None:
            gen = max(gen, m[0])
    for name in (MANIFEST, PREV_MANIFEST):
        man = _manifest_at(directory, name)
        if man is not None:
            gen = max(gen, int(man.get("generation", _LEGACY)))
    return gen + 1  # _LEGACY is -1, so legacy-only dirs start at 0+1


def _fsync_write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def save_checkpoint_sharded(
    directory: str,
    arrays: Dict[str, np.ndarray],
    meta: Dict[str, Any],
    n_shards: int,
) -> None:
    """Persist (arrays, meta) as group-range shards + a hashed manifest.

    ``meta["app_states"]`` is lifted OUT of the manifest and sharded by
    each name's row (``meta["names"]``), so a lazy loader can parse one
    shard's app states without touching the rest; everything else in
    ``meta`` rides in the manifest verbatim."""
    os.makedirs(directory, exist_ok=True)
    gen = _next_generation(directory)
    meta = dict(meta)
    app_states = meta.pop("app_states", None) or {}
    names = meta.get("names") or {}

    G = 0
    for v in arrays.values():
        G = max(G, int(np.asarray(v).shape[0]))
    n_shards = max(1, min(int(n_shards), G or 1))
    bounds = [
        (G * i // n_shards, G * (i + 1) // n_shards) for i in range(n_shards)
    ]

    los = [lo for lo, _ in bounds]

    def shard_of(row: int) -> int:
        import bisect

        return max(0, bisect.bisect_right(los, int(row)) - 1)

    apps_by_shard: List[Dict[str, Any]] = [{} for _ in range(n_shards)]
    homeless: Dict[str, Any] = {}
    for nm, st in app_states.items():
        row = names.get(nm)
        if row is None:
            homeless[nm] = st  # unmapped state: keep it loadable anyway
        else:
            apps_by_shard[shard_of(int(row))][nm] = st
    if homeless:
        meta["app_states_unmapped"] = homeless

    shard_table = []
    for i, (lo, hi) in enumerate(bounds):
        payload: Dict[str, np.ndarray] = {
            k: np.asarray(v)[lo:hi] for k, v in arrays.items()
        }
        payload[GEN_KEY] = np.int64(gen)
        payload[ROWS_KEY] = np.array([lo, hi], np.int64)
        payload[APPS_KEY] = np.frombuffer(
            json.dumps(apps_by_shard[i], separators=(",", ":")).encode(
                "utf-8"
            ),
            np.uint8,
        )
        buf = io.BytesIO()
        np.savez(buf, **payload)
        data = buf.getvalue()
        fname = _shard_file(gen, i)
        tmp = os.path.join(directory, fname + ".tmp")
        _fsync_write(tmp, data)
        os.replace(tmp, os.path.join(directory, fname))
        shard_table.append({
            "file": fname, "lo": lo, "hi": hi,
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data),
        })

    manifest = {
        "generation": gen,
        "rows": G,
        "n_shards": n_shards,
        "journal_pos": meta.get("journal_pos"),
        "shards": shard_table,
        "meta": meta,
    }
    man_path = os.path.join(directory, MANIFEST)
    tmp = man_path + ".tmp"
    _fsync_write(tmp, json.dumps(manifest, separators=(",", ":")).encode(
        "utf-8"
    ))
    prev_gen = None
    cur = _manifest_at(directory, MANIFEST)
    if cur is not None:
        prev_gen = int(cur.get("generation", _LEGACY))
        os.replace(man_path, os.path.join(directory, PREV_MANIFEST))
    else:
        # a crash between the demote and promote renames leaves only
        # PREV_MANIFEST on disk — its generation must survive the GC
        # below, or the torn-shard fallback would point at deleted files
        prev = _manifest_at(directory, PREV_MANIFEST)
        if prev is not None:
            prev_gen = int(prev.get("generation", _LEGACY))
    os.replace(tmp, man_path)

    # GC shard files of generations older than the retained pair
    keep = {gen}
    if prev_gen is not None:
        keep.add(prev_gen)
    for fname in os.listdir(directory):
        # ".npz.tmp" too: a crash between write and rename orphans a
        # generation-unique tmp that no later save would ever overwrite
        if not (fname.startswith(SHARD_PREFIX)
                and fname.endswith((".npz", ".npz.tmp"))):
            continue
        try:
            g = int(fname[len(SHARD_PREFIX) + 1:len(SHARD_PREFIX) + 9])
        except ValueError:
            continue
        if g not in keep:
            try:
                os.remove(os.path.join(directory, fname))
            except OSError:
                pass


class CheckpointView:
    """A loaded checkpoint with per-shard lazy app-state parsing.

    ``arrays`` (the reassembled engine leaves) and ``meta`` are eager —
    they are needed before serving anything; the per-shard app-state
    JSON stays as raw bytes until :meth:`app_states` is asked for that
    shard (the recovery plane hydrates cold shards in the background)."""

    def __init__(
        self,
        generation: int,
        arrays: Dict[str, np.ndarray],
        meta: Dict[str, Any],
        shard_ranges: List[Tuple[int, int]],
        apps_raw: List[Optional[bytes]],
    ):
        self.generation = generation
        self.arrays = arrays
        self.meta = meta
        self.shard_ranges = shard_ranges
        self._apps_raw = apps_raw
        self._apps: List[Optional[Dict[str, Any]]] = [None] * len(apps_raw)

    @property
    def n_shards(self) -> int:
        return len(self.shard_ranges)

    def shard_of_row(self, row: int) -> int:
        for i, (lo, hi) in enumerate(self.shard_ranges):
            if lo <= int(row) < hi:
                return i
        return max(0, self.n_shards - 1)

    def app_states(self, shard: int) -> Dict[str, Any]:
        """Parse (once) and return one shard's {name: app_state}."""
        got = self._apps[shard]
        if got is None:
            raw = self._apps_raw[shard]
            got = json.loads(raw.decode("utf-8")) if raw else {}
            unmapped = self.meta.get("app_states_unmapped")
            if unmapped and shard == 0:
                got = {**unmapped, **got}
            self._apps[shard] = got
            self._apps_raw[shard] = None  # parsed: drop the raw bytes
        return got

    def all_app_states(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for i in range(self.n_shards):
            out.update(self.app_states(i))
        return out


def _open_manifest(
    directory: str, name: str
) -> Optional[CheckpointView]:
    """Build a view from one manifest, verifying every shard's content
    hash; None when the manifest or ANY shard is missing/torn/mismatched
    (the caller falls back to the previous generation)."""
    man = _manifest_at(directory, name)
    if man is None:
        return None
    gen = int(man.get("generation", _LEGACY))
    per_shard_arrays: List[Dict[str, np.ndarray]] = []
    ranges: List[Tuple[int, int]] = []
    apps_raw: List[Optional[bytes]] = []
    try:
        for ent in man["shards"]:
            path = os.path.join(directory, ent["file"])
            with open(path, "rb") as f:
                data = f.read()
            if hashlib.sha256(data).hexdigest() != ent["sha256"]:
                return None  # torn/corrupt shard write
            with np.load(io.BytesIO(data)) as z:
                if GEN_KEY not in z.files or int(z[GEN_KEY]) != gen:
                    return None
                lo, hi = (int(x) for x in z[ROWS_KEY])
                apps_raw.append(
                    z[APPS_KEY].tobytes() if APPS_KEY in z.files else None
                )
                per_shard_arrays.append({
                    k: z[k] for k in z.files
                    if k not in (GEN_KEY, ROWS_KEY, APPS_KEY)
                })
            ranges.append((lo, hi))
    except Exception:
        return None
    if not per_shard_arrays:
        return None
    arrays = {
        k: np.concatenate([s[k] for s in per_shard_arrays], axis=0)
        for k in per_shard_arrays[0]
    }
    meta = dict(man.get("meta") or {})
    meta.setdefault("generation", gen)
    meta.setdefault("journal_pos", man.get("journal_pos") or [0, 0])
    return CheckpointView(gen, arrays, meta, ranges, apps_raw)


def load_checkpoint_view(directory: str) -> Optional[CheckpointView]:
    """Newest loadable checkpoint in ANY format, as a lazy view.

    Candidates: the current sharded manifest, its prev fallback, and the
    legacy single-pair chain — the highest generation that fully
    verifies wins (a torn shard write disqualifies its whole
    generation, falling back to the previous anchor)."""
    view = _open_manifest(directory, MANIFEST)
    if view is None:
        view = _open_manifest(directory, PREV_MANIFEST)
    legacy = _load_checkpoint_legacy(directory)
    if legacy is not None:
        lgen, arrays, meta = legacy
        if view is None or lgen > view.generation:
            meta = dict(meta)
            apps = meta.pop("app_states", None) or {}
            G = 0
            for v in arrays.values():
                G = max(G, int(np.asarray(v).shape[0]))
            raw = json.dumps(apps, separators=(",", ":")).encode("utf-8")
            return CheckpointView(lgen, arrays, meta, [(0, G)], [raw])
    return view


def load_checkpoint(
    directory: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]]:
    """Eager (arrays, meta) load — meta includes ``app_states`` merged
    back in, whatever the on-disk format (legacy pair or shards)."""
    view = load_checkpoint_view(directory)
    if view is None:
        return None
    meta = dict(view.meta)
    meta.pop("app_states_unmapped", None)
    meta["app_states"] = view.all_app_states()
    return view.arrays, meta
