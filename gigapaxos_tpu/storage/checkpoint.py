"""Engine-state snapshots: one .npz of the batched arrays + a JSON sidecar.

The reference checkpoints *per group* into SQL tables (``checkpoint`` /
``prev_checkpoint``, ``SQLPaxosLogger.java:149-152``) because each group
is an object; here the whole engine is a handful of [G]/[G, W] arrays, so
a checkpoint is a single bulk snapshot and recovery a single bulk load
(the SURVEY §7 hard-part (d) answer).  App-level checkpoint strings
(``Replicable.checkpoint``) ride in the sidecar.

Torn-write protection: every snapshot embeds a **generation id** in both
the .npz (``__generation__`` array) and the sidecar (``"generation"``
key).  Both files of the new pair are fully written and fsynced to temp
names *before* any rename; the loader accepts any (snapshot, sidecar)
combination whose generation ids match, picking the highest generation —
so a crash between any two renames still leaves at least one matched
pair (the previous generation) discoverable.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

SNAP = "checkpoint.npz"
META = "checkpoint.meta.json"
PREV_SNAP = "prev_checkpoint.npz"
PREV_META = "prev_checkpoint.meta.json"

GEN_KEY = "__generation__"


_LEGACY = -1  # marker for pre-generation files (no embedded id)


def _snap_generation(path: str) -> Optional[int]:
    """Generation embedded in a snapshot; _LEGACY if absent; None if unreadable."""
    try:
        with np.load(path) as z:
            if GEN_KEY in z.files:
                return int(z[GEN_KEY])
            return _LEGACY
    except Exception:
        return None


def _meta_generation(path: str) -> Optional[Tuple[int, Dict[str, Any]]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            meta = json.load(f)
        return int(meta.get("generation", _LEGACY)), meta
    except Exception:
        return None


def save_checkpoint(
    directory: str,
    arrays: Dict[str, np.ndarray],
    meta: Dict[str, Any],
) -> None:
    """Atomically persist (arrays, meta), demoting the current pair to prev.

    Write order (each file fsynced before any rename):
      1. new snapshot  -> checkpoint.npz.tmp
      2. new sidecar   -> checkpoint.meta.json.tmp
      3. demote current pair to prev_*
      4. promote the tmp pair to checkpoint.*
    A crash at any point leaves >= 1 generation-matched pair on disk.
    """
    os.makedirs(directory, exist_ok=True)
    snap = os.path.join(directory, SNAP)
    metaf = os.path.join(directory, META)

    # next generation = 1 + highest generation visible on disk
    gen = 0
    for name in (SNAP, PREV_SNAP):
        g = _snap_generation(os.path.join(directory, name))
        if g is not None:
            gen = max(gen, g)
    for name in (META, PREV_META):
        m = _meta_generation(os.path.join(directory, name))
        if m is not None:
            gen = max(gen, m[0])
    gen += 1  # _LEGACY is -1, so legacy-only dirs start at generation 0+1

    meta = dict(meta)
    meta["generation"] = gen
    payload = dict(arrays)
    payload[GEN_KEY] = np.int64(gen)

    tmp = snap + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    tmpm = metaf + ".tmp"
    with open(tmpm, "w", encoding="utf-8") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())

    # Demote current -> prev ONLY as a generation-matched pair: a crash in
    # a previous save can leave an orphan current file (snapshot without
    # its sidecar or vice versa); demoting an orphan would overwrite half
    # of a still-valid prev pair and can strand the directory with zero
    # loadable checkpoints.  Orphans are deleted instead (they were never
    # loadable on their own).
    sg = _snap_generation(snap) if os.path.exists(snap) else None
    m = _meta_generation(metaf) if os.path.exists(metaf) else None
    mg = m[0] if m is not None else None
    if sg is not None and sg == mg:
        os.replace(snap, os.path.join(directory, PREV_SNAP))
        os.replace(metaf, os.path.join(directory, PREV_META))
    else:
        if os.path.exists(snap):
            os.remove(snap)
        if os.path.exists(metaf):
            os.remove(metaf)
    os.replace(tmp, snap)
    os.replace(tmpm, metaf)


def load_checkpoint(
    directory: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]]:
    """Load the newest valid generation-matched (arrays, meta) pair.

    Tries every (snapshot, sidecar) combination so that a crash between
    the demote/promote renames of :func:`save_checkpoint` (which can pair
    e.g. ``prev_checkpoint.npz`` with ``checkpoint.meta.json``) still
    finds the surviving pair; a sidecar is never silently combined with
    a snapshot from a different generation.
    """
    snaps = {}   # name -> (gen, path)
    for name in (SNAP, PREV_SNAP):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            g = _snap_generation(path)
            if g is not None:
                snaps[name] = (g, path)
    metas = {}   # name -> (gen, meta)
    for name in (META, PREV_META):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            m = _meta_generation(path)
            if m is not None:
                metas[name] = m

    # Candidates: any cross combination whose EXPLICIT generations match;
    # legacy files (no embedded id) only pair name-aligned — current with
    # current, prev with prev — since 'both lack an id' proves nothing
    # about belonging together across names.
    candidates = []  # (gen, snap_path, meta)
    for sname, (sg, spath) in snaps.items():
        for mname, (mg, meta) in metas.items():
            aligned = (sname, mname) in ((SNAP, META), (PREV_SNAP, PREV_META))
            if sg == mg != _LEGACY or (sg == mg == _LEGACY and aligned):
                candidates.append((sg, sname == SNAP, spath, meta))
    # highest generation first; at equal gen prefer the current-named pair
    candidates.sort(key=lambda c: (c[0], c[1]), reverse=True)
    for _gen, _cur, spath, meta in candidates:
        try:
            with np.load(spath) as z:
                arrays = {k: z[k] for k in z.files if k != GEN_KEY}
            return arrays, meta
        except Exception:
            continue  # corrupt body despite readable header: try next pair
    return None
