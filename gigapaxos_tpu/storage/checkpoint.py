"""Engine-state snapshots: one .npz of the batched arrays + a JSON sidecar.

The reference checkpoints *per group* into SQL tables (``checkpoint`` /
``prev_checkpoint``, ``SQLPaxosLogger.java:149-152``) because each group
is an object; here the whole engine is a handful of [G]/[G, W] arrays, so
a checkpoint is a single bulk snapshot and recovery a single bulk load
(the SURVEY §7 hard-part (d) answer).  App-level checkpoint strings
(``Replicable.checkpoint``) ride in the sidecar.  The previous snapshot
is kept (prev_checkpoint analog) and a torn write is detected via the
atomic rename of the sidecar — the sidecar is written LAST, so a
snapshot without a valid sidecar is ignored.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

SNAP = "checkpoint.npz"
META = "checkpoint.meta.json"
PREV_SNAP = "prev_checkpoint.npz"
PREV_META = "prev_checkpoint.meta.json"


def save_checkpoint(
    directory: str,
    arrays: Dict[str, np.ndarray],
    meta: Dict[str, Any],
) -> None:
    """Atomically persist (arrays, meta), demoting the current pair to prev."""
    os.makedirs(directory, exist_ok=True)
    snap = os.path.join(directory, SNAP)
    metaf = os.path.join(directory, META)
    # demote current -> prev (both files, meta last so prev stays valid)
    if os.path.exists(snap) and os.path.exists(metaf):
        os.replace(snap, os.path.join(directory, PREV_SNAP))
        os.replace(metaf, os.path.join(directory, PREV_META))
    tmp = snap + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, snap)
    tmpm = metaf + ".tmp"
    with open(tmpm, "w", encoding="utf-8") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmpm, metaf)


def load_checkpoint(
    directory: str,
) -> Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]]:
    """Load the newest valid (arrays, meta) pair; falls back to prev."""
    for snap_name, meta_name in ((SNAP, META), (PREV_SNAP, PREV_META)):
        snap = os.path.join(directory, snap_name)
        metaf = os.path.join(directory, meta_name)
        if not (os.path.exists(snap) and os.path.exists(metaf)):
            continue
        try:
            with open(metaf, "r", encoding="utf-8") as f:
                meta = json.load(f)
            with np.load(snap) as z:
                arrays = {k: z[k] for k in z.files}
            return arrays, meta
        except Exception:
            continue  # torn/corrupt: try prev
    return None
