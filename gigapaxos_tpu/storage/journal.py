"""Append-only CRC-framed block journal with rotation and GC.

Plays the role of the reference's journal files
(``SQLPaxosLogger.Journaler``, ``SQLPaxosLogger.java:685-711``: dir
``paxos_journal.*``, 64MB rotation, GC below the checkpoint) — but the
record unit is a *block of packed int32 columns* covering many groups at
once (one ``np.ndarray.tobytes`` per engine step), not one serialized
message per paxos instance.

Wire format per block (little-endian):
    magic:u32  type:u8  n_rows:u32  payload_len:u32  crc32(payload):u32
    payload bytes
A torn tail (partial header/payload or CRC mismatch) terminates a scan
cleanly — everything before it is valid (append-only + single writer).
"""

from __future__ import annotations

import enum
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

MAGIC = 0x47504A4C  # "GPJL"
_HDR = struct.Struct("<IBIII")

FILE_PREFIX = "journal_"
FILE_SUFFIX = ".bin"


class BlockType(enum.IntEnum):
    ACCEPTS = 1     # cols: group, slot, ballot, vid
    DECISIONS = 2   # cols: group, slot, vid
    CREATE = 3      # cols: group, member_mask, version, coord0
    PAYLOADS = 4    # raw bytes (host arena spill: vid -> request payloads)
    PAUSE = 5       # raw bytes (packed rows of paused groups)
    KILL = 6        # cols: group
    CHECKPOINT = 7  # raw bytes (json marker: snapshot name + journal pos)
    NAMES = 8       # raw bytes (json [{row, name, version, init}] — the
    #                 name->row map + initial app state of CREATE blocks;
    #                 names are host-side strings so they can't ride the
    #                 packed int32 CREATE columns)
    PROMISES = 9    # cols: group, ballot — a bare promise (ballot rose with
    #                 no accompanying accept); ref: handlePrepare's
    #                 log-before-send of promise-upgrading prepare replies
    UNPEND = 10     # cols: group — a pending (pre-COMPLETE) row confirmed
    #                 by the reconfigurator's epoch_commit; clears the
    #                 propose-refusal gate durably


def _file_name(idx: int) -> str:
    return f"{FILE_PREFIX}{idx:08d}{FILE_SUFFIX}"


def _file_idx(name: str) -> Optional[int]:
    if name.startswith(FILE_PREFIX) and name.endswith(FILE_SUFFIX):
        try:
            return int(name[len(FILE_PREFIX):-len(FILE_SUFFIX)])
        except ValueError:
            return None
    return None


# payloads at least this large CRC-check through the native library when
# available (the ctypes call releases the GIL, so segmented replay's
# scanner threads verify concurrently); small blocks stay on zlib, whose
# call overhead is lower
_NATIVE_CRC_MIN = 4096


def _crc_fn():
    """(crc(payload) -> int) using gp_journal.so for large payloads when
    loaded (GP_NO_NATIVE / no compiler => pure zlib)."""
    from ..native import journal_lib

    lib = journal_lib()
    if lib is None:
        return zlib.crc32

    def crc(payload: bytes) -> int:
        if len(payload) >= _NATIVE_CRC_MIN:
            return lib.gpj_crc32(payload, len(payload))
        return zlib.crc32(payload)

    return crc


def read_file_blocks(
    path: str, from_offset: int = 0
) -> Tuple[List[Tuple[BlockType, bytes, int, int]], bool]:
    """Read one journal file's valid blocks from ``from_offset``.

    Returns ``([(type, payload, n_rows, end_offset), ...], clean)`` —
    ``clean`` is False when the file ends in a torn/corrupt block, in
    which case everything PAST this file is unreachable (single-writer
    append order) and the caller must stop the whole scan.  This is the
    per-segment unit of the recovery plane's parallel replay: framing
    and CRC verification happen here, concurrently across files, while
    block APPLICATION stays in journal order."""
    crc_of = _crc_fn()
    blocks: List[Tuple[BlockType, bytes, int, int]] = []
    # an unreadable file raises (loud recovery failure) — only torn
    # CONTENT truncates the scan; mapping open() errors to clean=False
    # would silently drop every decision from this file onward
    with open(path, "rb") as f:
        if from_offset:
            f.seek(from_offset)
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                # partial header = benign EOF (scan parity: only payload
                # tears and magic/CRC mismatches stop the WHOLE scan)
                return blocks, True
            magic, btype, n_rows, plen, crc = _HDR.unpack(hdr)
            if magic != MAGIC:
                return blocks, False
            payload = f.read(plen)
            if len(payload) < plen or crc_of(payload) != crc:
                return blocks, False
            blocks.append(
                (BlockType(btype), payload, n_rows, f.tell())
            )


class Journal:
    """Single-writer append-only journal over rotating files in a dir."""

    def __init__(
        self,
        directory: str,
        max_file_size: int = 64 * 1024 * 1024,  # MAX_LOG_FILE_SIZE analog
        sync: bool = False,                      # FLUSH/SYNC flag analog
    ):
        self.dir = directory
        self.max_file_size = max_file_size
        self.sync = sync
        # append/position/gc are serialized: the async checkpoint
        # writer appends its marker and GCs covered files from a
        # background thread while the tick thread keeps appending
        self._lock = threading.RLock()
        os.makedirs(directory, exist_ok=True)
        existing = self.file_indices()
        self._cur_idx = existing[-1] if existing else 0
        path = os.path.join(self.dir, _file_name(self._cur_idx))
        # A crash can leave a torn block at the tail; appending after it
        # would orphan every later block (scans stop at the tear), so cut
        # back to the last valid block boundary before appending.
        self._truncate_torn_tail(path)
        self._fh = open(path, "ab")
        # authoritative write offset: native appends bypass the buffered
        # object, whose tell() only tracks its own writes (O_APPEND keeps
        # all writes at EOF either way; Python-path writes flush inline,
        # so the two never interleave unflushed)
        self._pos = os.path.getsize(path)
        from ..native import journal_lib

        self._native = journal_lib()  # None -> pure-Python appends

    @staticmethod
    def _truncate_torn_tail(path: str) -> None:
        if not os.path.exists(path):
            return
        valid_end = 0
        with open(path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break
                magic, _btype, _n, plen, crc = _HDR.unpack(hdr)
                if magic != MAGIC:
                    break
                payload = f.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    break
                valid_end = f.tell()
        if valid_end < os.path.getsize(path):
            with open(path, "r+b") as f:
                f.truncate(valid_end)

    # ---- write ---------------------------------------------------------
    def append(self, btype: BlockType, payload: bytes, n_rows: int = 0) -> Tuple[int, int]:
        """Append one block; returns (file_idx, end_offset) after the write.

        Uses the native appender (header + CRC + write [+fsync] as one C
        call, ``native/gp_journal.cc``) when available; the pure-Python
        path writes the identical bytes."""
        with self._lock:
            return self._append_locked(btype, payload, n_rows)

    def _append_locked(self, btype: BlockType, payload: bytes,
                       n_rows: int = 0) -> Tuple[int, int]:
        lib = self._native
        if lib is not None:
            wrote = lib.gpj_append(
                self._fh.fileno(), int(btype), n_rows,
                payload, len(payload), 1 if self.sync else 0,
            )
            if wrote >= 0:
                self._pos += int(wrote)
                if self._pos >= self.max_file_size:
                    self._rotate()
                    return (self._cur_idx, 0)
                return (self._cur_idx, self._pos)
            # a failed native write may have landed PARTIAL bytes —
            # appending after them would tear the stream (scans stop at
            # the corrupt header).  Cut back to the last good boundary and
            # retire the native path for this journal (the disk condition
            # will recur); the Python retry below starts clean.
            self._repair_to_pos()
        hdr = _HDR.pack(MAGIC, int(btype), n_rows, len(payload), zlib.crc32(payload))
        self._fh.write(hdr)
        self._fh.write(payload)
        self._fh.flush()
        self._pos += len(hdr) + len(payload)
        if self.sync:
            os.fsync(self._fh.fileno())
        if self._pos >= self.max_file_size:
            self._rotate()
            return (self._cur_idx, 0)
        return (self._cur_idx, self._pos)

    def _repair_to_pos(self) -> None:
        """Truncate torn partial bytes back to the last good block
        boundary (self._pos) and stop using the native appender."""
        self._native = None
        try:
            self._fh.flush()
            os.ftruncate(self._fh.fileno(), self._pos)
        except OSError:
            pass  # truncate failing leaves the tear; scans still stop
            # cleanly at it and recovery sees everything before _pos

    @staticmethod
    def pack_columns(cols: List[np.ndarray]) -> Tuple[bytes, int]:
        """THE packed-column wire encoding (kept in one place: the direct
        and batched append paths must never diverge from the scanner)."""
        n = len(cols[0])
        mat = np.stack([np.asarray(c, np.int32) for c in cols], axis=1)
        return mat.tobytes(), n

    def append_columns(self, btype: BlockType, cols: List[np.ndarray]) -> Tuple[int, int]:
        """Append equal-length int32 columns as one packed block."""
        payload, n = self.pack_columns(cols)
        return self.append(btype, payload, n_rows=n)

    def append_many(
        self, blocks: List[Tuple[BlockType, bytes, int]]
    ) -> Tuple[int, int]:
        """Group commit: all blocks leave in one writev + at most one
        fsync (``BatchedLogger`` analog, ``AbstractPaxosLogger.java:656``
        — the durability cost of a tick is one syscall, not one per
        block type).  Pure-Python fallback appends sequentially."""
        with self._lock:
            return self._append_many_locked(blocks)

    def _append_many_locked(
        self, blocks: List[Tuple[BlockType, bytes, int]]
    ) -> Tuple[int, int]:
        import ctypes

        lib = self._native
        if lib is None or not blocks:
            out = self.position
            for btype, payload, n_rows in blocks:
                out = self._append_locked(btype, payload, n_rows)
            return out
        pos = self.position
        for start in range(0, len(blocks), 64):  # native batch cap
            chunk = blocks[start:start + 64]
            if lib is None or self._native is None:
                # native path retired mid-batch (repair): finish via Python
                for btype, payload, n_rows in chunk:
                    pos = self.append(btype, payload, n_rows)
                continue
            n = len(chunk)
            btypes = (ctypes.c_uint8 * n)(*[int(b) for b, _, _ in chunk])
            rows = (ctypes.c_uint32 * n)(*[r for _, _, r in chunk])
            lens = (ctypes.c_uint32 * n)(*[len(p) for _, p, _ in chunk])
            bufs = (ctypes.c_char_p * n)(*[p for _, p, _ in chunk])
            wrote = lib.gpj_append_batch(
                self._fh.fileno(), btypes, rows,
                ctypes.cast(bufs, ctypes.POINTER(ctypes.c_char_p)),
                lens, n, 1 if self.sync else 0,
            )
            if wrote < 0:
                # possible torn partial write: cut back to the last good
                # boundary, then redo this chunk via the Python path
                self._repair_to_pos()
                out = self.position
                for btype, payload, n_rows in chunk:
                    out = self.append(btype, payload, n_rows)
                pos = out
                lib = None  # retired by _repair_to_pos
                continue
            self._pos += int(wrote)
            if self._pos >= self.max_file_size:
                self._rotate()
            pos = self.position
        return pos

    def _rotate(self) -> None:
        self._fh.close()
        self._cur_idx += 1
        path = os.path.join(self.dir, _file_name(self._cur_idx))
        self._fh = open(path, "ab")
        self._pos = 0

    @property
    def position(self) -> Tuple[int, int]:
        # locked: a concurrent rotation (background checkpoint writer's
        # marker append) updates _cur_idx and _pos non-atomically — a
        # torn pair persisted as a snapshot's journal_pos would skip
        # every post-checkpoint block on recovery
        with self._lock:
            return (self._cur_idx, self._pos)

    # ---- read ----------------------------------------------------------
    def file_indices(self) -> List[int]:
        idxs = sorted(
            i for n in os.listdir(self.dir)
            if (i := _file_idx(n)) is not None
        )
        return idxs

    def scan(
        self, from_file: int = 0, from_offset: int = 0
    ) -> Iterator[Tuple[BlockType, bytes, int, Tuple[int, int]]]:
        """Yield (type, payload, n_rows, (file_idx, end_offset)) from the
        given position; stops cleanly at a torn/corrupt tail."""
        self._fh.flush()
        for idx in self.file_indices():
            if idx < from_file:
                continue
            path = os.path.join(self.dir, _file_name(idx))
            blocks, clean = read_file_blocks(
                path, from_offset if idx == from_file else 0
            )
            for btype, payload, n_rows, end in blocks:
                yield btype, payload, n_rows, (idx, end)
            if not clean:
                return  # torn/corrupt: everything past it is unreachable

    @staticmethod
    def columns(payload: bytes, n_rows: int, n_cols: int) -> np.ndarray:
        """Decode a packed column block back to an [n_rows, n_cols] array."""
        return np.frombuffer(payload, np.int32).reshape(n_rows, n_cols)

    # ---- GC ------------------------------------------------------------
    def gc_below(self, file_idx: int) -> int:
        """Delete whole files strictly below file_idx (all their blocks are
        covered by a checkpoint).  Returns #files removed."""
        with self._lock:
            return self._gc_below_locked(file_idx)

    def _gc_below_locked(self, file_idx: int) -> int:
        removed = 0
        for idx in self.file_indices():
            if idx >= file_idx or idx == self._cur_idx:
                continue
            os.remove(os.path.join(self.dir, _file_name(idx)))
            removed += 1
        return removed

    def close(self) -> None:
        with self._lock:
            self._fh.close()
