"""PaxosLogger — the durability facade: journal + checkpoints + recovery.

API-parity target: ``AbstractPaxosLogger`` (``AbstractPaxosLogger.java:63``
— log/logBatch, checkpoint, pause/unpause, recovery cursors) re-shaped for
array state:

* ``log_*`` appends packed column blocks (the log-before-send delta the
  engine emits per step, ``StepOutputs.acc_new``);
* ``checkpoint`` snapshots the engine arrays + app states, drops a marker
  block, and GCs journal files wholly below the snapshot
  (``SQLPaxosLogger`` journal GC analog);
* ``recover`` = bulk snapshot load + vectorized rollforward of every
  block after the snapshot position (vs the reference's per-group cursor
  walk, ``PaxosManager.initiateRecovery:1832-2035``).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .checkpoint import CheckpointView, load_checkpoint_view, save_checkpoint
from .journal import BlockType, Journal

NULL = -1


class RecoveredState:
    """Result of recovery: engine arrays + host-side maps, ready to be
    device_put into an EngineState by the manager."""

    def __init__(
        self,
        arrays: Optional[Dict[str, np.ndarray]],
        meta: Dict[str, Any],
        payloads: Dict[int, str],
        names: Dict[str, Dict[str, Any]],
        pending_rows: Optional[set] = None,
        pause_records: Optional[Dict] = None,
        decisions: Optional[Dict[int, Dict[int, int]]] = None,
    ):
        self.arrays = arrays          # None => fresh start
        self.meta = meta
        self.payloads = payloads      # vid -> request string (host arena)
        # name -> [{row, version, init}, ...] in journal order (a name can
        # appear once per epoch: reconfiguration re-creates it at a new row)
        self.names = names
        # rows still awaiting the reconfigurator's epoch_commit (the
        # propose-refusal gate survives a restart)
        self.pending_rows = pending_rows or set()
        # (name, epoch) -> last pause record (still-paused groups resume
        # from these; resumed groups fold them under replayed progress)
        self.pause_records = pause_records or {}
        # group -> {slot -> vid}: EVERY journaled decision after the
        # checkpoint.  The [G, W] rings only retain the last W decisions
        # per group (lane reuse), so a group that decided more than W slots
        # since its last checkpoint can only roll forward through these.
        self.decisions = decisions or {}
        # vid -> (entry_replica, request_id) journaled alongside payloads
        self.payload_meta: Dict[int, Tuple[int, int]] = {}
        # the (possibly sharded) checkpoint this recovery loaded, kept
        # for lazy per-shard app-state hydration; None = no checkpoint
        # or the caller asked for eager app states
        self.view: Optional[CheckpointView] = None
        # replay accounting for the recovery_* metrics / bench surface
        self.stats: Dict[str, Any] = {}


class PaxosLogger:
    def __init__(
        self,
        node_id: Any,
        directory: str,
        sync: bool = False,
        max_file_size: int = 64 * 1024 * 1024,
    ):
        self.node_id = node_id
        self.dir = directory
        self.journal = Journal(directory, max_file_size=max_file_size, sync=sync)
        # open group-commit batch (BatchedLogger analog): log_* calls
        # buffer here and leave in ONE writev/fsync at scope exit
        self._batch: Optional[List] = None
        # journal GC runs every Nth checkpoint (JOURNAL_GC_FREQUENCY
        # analog; default 1 = GC at every checkpoint — raise to amortize
        # the file scan on checkpoint-heavy deployments)
        from ..paxos_config import PC
        from ..utils.config import Config

        self.gc_every = max(1, Config.get_int(PC.JOURNAL_GC_FREQUENCY))
        self._ckpts_since_gc = 0
        # recovery plane: checkpoint sharding + segmented-replay width
        self.ckpt_shards = max(
            1, Config.get_int(PC.RECOVERY_CHECKPOINT_SHARDS)
        )
        self.replay_workers = max(
            1, Config.get_int(PC.RECOVERY_REPLAY_WORKERS)
        )
        # async checkpoint writer (newest pending snapshot wins)
        self._ck_lock = threading.Lock()
        self._ck_pending = None
        self._ck_thread: Optional[threading.Thread] = None

    @contextlib.contextmanager
    def batch(self):
        """Group-commit scope: all log_* appends inside leave together
        (one writev + at most one fsync).  The scope must close before
        the tick's blob is published (log-before-send)."""
        if self._batch is not None:
            yield  # nested scopes share the outer batch
            return
        self._batch = []
        try:
            yield
        finally:
            blocks, self._batch = self._batch, None
            if blocks:
                self.journal.append_many(blocks)

    def _append(self, btype: BlockType, payload: bytes, n_rows: int = 0) -> None:
        if self._batch is not None:
            self._batch.append((btype, payload, n_rows))
        else:
            self.journal.append(btype, payload, n_rows)

    def _append_columns(self, btype: BlockType, cols) -> None:
        payload, n = Journal.pack_columns(cols)
        self._append(btype, payload, n_rows=n)

    # ---- log-before-send appends --------------------------------------
    def log_accepts(self, groups, slots, bals, vids) -> None:
        if len(groups):
            self._append_columns(BlockType.ACCEPTS, [groups, slots, bals, vids])

    def log_decisions(self, groups, slots, vids) -> None:
        if len(groups):
            self._append_columns(BlockType.DECISIONS, [groups, slots, vids])

    def log_promises(self, groups, bals) -> None:
        """Bare promise upgrades (ballot rose without an accept) — must be
        durable before the blob is published, or a restarted acceptor could
        accept an older-ballot proposal it had promised against."""
        if len(groups):
            self._append_columns(BlockType.PROMISES, [groups, bals])

    def log_create(
        self, groups, masks, versions, coords, names=None, inits=None,
        pendings=None,
    ) -> None:
        if len(groups):
            self._append_columns(
                BlockType.CREATE, [groups, masks, versions, coords]
            )
            if names is not None:
                rows = [
                    {"row": int(g), "name": n, "version": int(v),
                     "init": (None if inits is None else inits[i]),
                     "pending": bool(pendings[i]) if pendings else False}
                    for i, (g, n, v) in enumerate(zip(groups, names, versions))
                ]
                self._append(
                    BlockType.NAMES,
                    json.dumps(rows, separators=(",", ":")).encode("utf-8"),
                )

    def log_unpend(self, groups) -> None:
        """A pending (pre-COMPLETE) row was confirmed — durably clear the
        propose-refusal gate so recovery doesn't resurrect it."""
        if len(groups):
            self._append_columns(BlockType.UNPEND, [groups])

    def log_pause(self, record: Dict[str, Any]) -> None:
        """Residency pause record: the group's consensus/app snapshot at
        the moment its row was freed (HotRestoreInfo -> pause table analog,
        ``PaxosManager.java:2307-2348``).  JSON — the window remnants are a
        handful of ints and the app state is a string."""
        self._append(
            BlockType.PAUSE,
            json.dumps(record, separators=(",", ":")).encode("utf-8"),
        )

    def log_kill(self, groups) -> None:
        if len(groups):
            self._append_columns(BlockType.KILL, [groups])

    def log_payloads(
        self, payloads: Dict[int, str], meta: Optional[Dict] = None
    ) -> None:
        """Persist request payloads (and their (entry, request_id) meta so
        exactly-once dedup survives a restart).  Every replica journals
        payloads it learns — locally admitted AND peer-replicated — or a
        coordinator-only crash could lose decided-but-unexecuted values."""
        if payloads:
            env = {"p": payloads}
            if meta:
                env["m"] = {str(k): list(v) for k, v in meta.items()}
            body = json.dumps(env, separators=(",", ":")).encode("utf-8")
            self._append(BlockType.PAYLOADS, body)

    # ---- checkpoint ----------------------------------------------------
    def checkpoint(
        self,
        engine_arrays: Dict[str, np.ndarray],
        app_states: Dict[str, Optional[str]],
        extra_meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        pos, meta = self._checkpoint_prepare(app_states, extra_meta)
        self._checkpoint_write(engine_arrays, meta, pos)

    def checkpoint_async(
        self,
        engine_arrays: Dict[str, np.ndarray],
        app_states: Dict[str, Optional[str]],
        extra_meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Journal-side work NOW (on the caller's thread, under its
        locks); the slow file serialization on a background writer.

        Serializing a loaded node's snapshot — a 64k-entry dedup cache,
        the live payload arena, npz + two fsyncs + renames — costs
        ~0.5s, and paying it inside the tick stalls the whole node (the
        measured latency spikes that failed the capacity gate).  The
        writer keeps only the NEWEST pending snapshot (an older one is
        subsumed); a crash before the write lands just means recovery
        rolls forward from the previous snapshot through the journal,
        exactly as if the crash had hit moments before the checkpoint.
        The caller must pass SNAPSHOTTED containers (no live dicts)."""
        pos, meta = self._checkpoint_prepare(app_states, extra_meta)
        with self._ck_lock:
            self._ck_pending = (engine_arrays, meta, pos)
            if self._ck_thread is None or not self._ck_thread.is_alive():
                self._ck_thread = threading.Thread(
                    target=self._ck_drain, daemon=True,
                    name="gp-checkpoint-writer",
                )
                self._ck_thread.start()

    def _checkpoint_prepare(self, app_states, extra_meta):
        if self._batch:
            # the snapshot position must cover every buffered block
            blocks, self._batch = self._batch, []
            self.journal.append_many(blocks)
        pos = self.journal.position
        meta = dict(extra_meta or {})
        meta["journal_pos"] = list(pos)
        meta["app_states"] = app_states
        return pos, meta

    def _checkpoint_write(self, engine_arrays, meta, pos) -> None:
        save_checkpoint(self.dir, engine_arrays, meta,
                        n_shards=self.ckpt_shards)
        self.journal.append(
            BlockType.CHECKPOINT,
            json.dumps({"journal_pos": list(pos)}).encode("utf-8"),
        )
        self._ckpts_since_gc += 1
        if self._ckpts_since_gc >= self.gc_every:
            self._ckpts_since_gc = 0
            self.journal.gc_below(pos[0])

    def _ck_drain(self) -> None:
        while True:
            with self._ck_lock:
                item, self._ck_pending = self._ck_pending, None
                if item is None:
                    self._ck_thread = None
                    return
            try:
                self._checkpoint_write(*item)
            except Exception:
                from ..obs import gplog

                # next cadence point retries; the failure must be visible
                gplog.node_logger("storage", self.node_id).exception(
                    "async checkpoint write failed (next cadence retries)"
                )

    def drain_checkpoints(self, timeout: float = 30.0) -> None:
        """Block until any pending async snapshot is on disk (close/final
        checkpoint path)."""
        with self._ck_lock:
            t = self._ck_thread
        if t is not None:
            t.join(timeout)

    # ---- recovery ------------------------------------------------------
    def recover(
        self,
        window: int,
        seed_arrays: Optional[Dict[str, np.ndarray]] = None,
        my_id: Optional[int] = None,
        defer_app_states: bool = False,
    ) -> RecoveredState:
        """Load newest snapshot, then roll every later block forward into
        the arrays.  ``seed_arrays`` (a fresh init_state as numpy, from the
        manager) is the base when no checkpoint exists but the journal has
        blocks; arrays=None means nothing durable at all.

        ``defer_app_states=True`` leaves ``meta["app_states"]`` empty and
        hands the checkpoint back as ``RecoveredState.view`` instead: the
        caller hydrates app states per shard (the lazy-hydration path —
        parsing 256k app-state strings up front is most of a cold
        restart).  Journal files after the anchor scan on
        ``RECOVERY_REPLAY_WORKERS`` threads; application stays in order."""
        from ..recovery.replay import scan_segments

        t_recover = time.monotonic()
        view = load_checkpoint_view(self.dir)
        if view is None:
            arrays: Optional[Dict[str, np.ndarray]] = None
            meta: Dict[str, Any] = {}
            from_file, from_off = 0, 0
        else:
            # the view's arrays are freshly materialized (npz load /
            # concatenate) — safe to roll forward in place, no copy
            arrays = view.arrays
            meta = dict(view.meta)
            meta.pop("app_states_unmapped", None)
            meta["app_states"] = (
                {} if defer_app_states else view.all_app_states()
            )
            from_file, from_off = meta.get("journal_pos", [0, 0])
        n_blocks = 0
        files_before = len([
            i for i in self.journal.file_indices() if i >= from_file
        ])
        payloads: Dict[int, str] = {}
        names: Dict[str, List[Dict[str, Any]]] = {}
        # chronological pending-row tracking: checkpoint seed, then NAMES
        # adds (pending creates), UNPEND/KILL clears, in scan order
        pending: set = set(int(r) for r in meta.get("pending_rows") or [])
        pause_records: Dict[Any, Dict[str, Any]] = {
            (str(r["name"]), int(r["epoch"])): r
            for r in (meta.get("paused") or {}).values()
        }
        decisions: Dict[int, Dict[int, int]] = {}
        payload_meta: Dict[int, Tuple[int, int]] = {}
        for btype, payload, n_rows, _pos in scan_segments(
            self.journal, from_file, from_off, workers=self.replay_workers
        ):
            n_blocks += 1
            if btype == BlockType.PAUSE:
                rec = json.loads(payload.decode("utf-8"))
                key = (str(rec["name"]), int(rec["epoch"]))
                if rec.get("dropped"):
                    pause_records.pop(key, None)  # deleted-while-paused
                else:
                    pause_records[key] = rec
                continue
            if btype == BlockType.DECISIONS:
                m = Journal.columns(payload, n_rows, 3)
                for g_, slot_, vid_ in m:
                    decisions.setdefault(int(g_), {})[int(slot_)] = int(vid_)
            elif btype in (BlockType.KILL, BlockType.CREATE):
                m = Journal.columns(
                    payload, n_rows, 1 if btype == BlockType.KILL else 4
                )
                for g_ in m[:, 0]:
                    decisions.pop(int(g_), None)  # row reused: old log void
            if btype == BlockType.PAYLOADS:
                env = json.loads(payload.decode("utf-8"))
                # pre-envelope journals stored the flat {vid: payload} map
                # ("p" can't collide: real keys are numeric strings)
                flat = env["p"] if "p" in env else env
                payloads.update({int(k): v for k, v in flat.items()})
                for k, m_ in (env.get("m") or {}).items():
                    payload_meta[int(k)] = (int(m_[0]), int(m_[1]))
                continue
            if btype == BlockType.NAMES:
                for ent in json.loads(payload.decode("utf-8")):
                    names.setdefault(ent["name"], []).append(ent)
                    if ent.get("pending"):
                        pending.add(int(ent["row"]))
                    else:
                        pending.discard(int(ent["row"]))
                continue
            if btype == BlockType.UNPEND:
                for g in Journal.columns(payload, n_rows, 1)[:, 0]:
                    pending.discard(int(g))
                continue
            if btype == BlockType.CHECKPOINT:
                continue
            if btype == BlockType.KILL:
                for g in Journal.columns(payload, n_rows, 1)[:, 0]:
                    pending.discard(int(g))
            if arrays is None:
                if seed_arrays is None:
                    raise ValueError(
                        "journal has blocks but no checkpoint and no seed_arrays"
                    )
                arrays = {k: v.copy() for k, v in seed_arrays.items()}
            self._apply(arrays, btype, payload, n_rows, window, my_id)
        out = RecoveredState(
            arrays, meta, payloads, names, pending, pause_records, decisions
        )
        out.payload_meta = payload_meta
        if defer_app_states:
            out.view = view
        out.stats = {
            "segments": files_before,
            "blocks": n_blocks,
            "replay_s": time.monotonic() - t_recover,
            "checkpoint_generation": (
                view.generation if view is not None else None
            ),
            "checkpoint_shards": view.n_shards if view is not None else 0,
        }
        return out

    @staticmethod
    def _apply(
        arrays: Dict[str, np.ndarray],
        btype: BlockType,
        payload: bytes,
        n_rows: int,
        window: int,
        my_id: Optional[int] = None,
    ) -> None:
        """Vectorized rollforward of one block into the state arrays.

        The arrays dict must already contain the engine leaves (a fresh
        node journals CREATE before anything else, and the manager seeds
        the dict from init_state before calling recover via ``seed``)."""
        W = window
        if btype == BlockType.CREATE:
            m = Journal.columns(payload, n_rows, 4)
            g, mask, ver, coord0 = m.T
            arrays["member_mask"][g] = mask
            arrays["majority"][g] = np.bitwise_count(
                mask.astype(np.uint32)
            ).astype(np.int32) // 2 + 1
            arrays["version"][g] = ver
            arrays["stopped"][g] = 0
            arrays["bal"][g] = coord0  # encode_ballot(0, coord) == coord
            arrays["exec_slot"][g] = 0
            for name in ("acc_bal", "acc_vid", "acc_slot", "dec_vid", "dec_slot"):
                arrays[name][g] = NULL
            arrays["app_hash"][g] = 0
            arrays["n_execd"][g] = 0
            # the initial coordinator must resume ACTIVE (create_groups
            # semantics) — otherwise nobody proposes and the failure
            # detector never fires (the coordinator is alive, just idle)
            if my_id is not None and "c_phase" in arrays:
                im_coord = coord0 == my_id
                arrays["c_phase"][g] = np.where(im_coord, 2, 0)  # ACTIVE/IDLE
                arrays["c_bal"][g] = np.where(im_coord, coord0, NULL)
                arrays["c_next_slot"][g] = 0
                arrays["c_prop_vid"][g] = NULL
                arrays["c_prop_slot"][g] = NULL
        elif btype == BlockType.ACCEPTS:
            m = Journal.columns(payload, n_rows, 4)
            g, slot, bal, vid = m.T
            lane = slot % W
            # One engine step accepts each (group, lane) at most once, so a
            # block never carries duplicate (g, lane) pairs and plain fancy
            # indexing is safe for the window scatter; the ballot fold uses
            # maximum.at so duplicate groups within a block (several lanes
            # of one group) still take a running max, not last-write-wins.
            arrays["acc_bal"][g, lane] = bal
            arrays["acc_vid"][g, lane] = vid
            arrays["acc_slot"][g, lane] = slot
            np.maximum.at(arrays["bal"], g, bal)
        elif btype == BlockType.PROMISES:
            m = Journal.columns(payload, n_rows, 2)
            g, bal = m.T
            np.maximum.at(arrays["bal"], g, bal)
        elif btype == BlockType.DECISIONS:
            m = Journal.columns(payload, n_rows, 3)
            g, slot, vid = m.T
            lane = slot % W
            newer = slot >= arrays["dec_slot"][g, lane]
            arrays["dec_vid"][g, lane] = np.where(newer, vid, arrays["dec_vid"][g, lane])
            arrays["dec_slot"][g, lane] = np.where(
                newer, slot, arrays["dec_slot"][g, lane]
            )
        elif btype == BlockType.KILL:
            m = Journal.columns(payload, n_rows, 1)
            g = m[:, 0]
            arrays["member_mask"][g] = 0
            arrays["bal"][g] = NULL

    def close(self) -> None:
        self.drain_checkpoints()
        self.journal.close()
