"""Durability: append-only journal + engine-state snapshots + recovery.

The TPU-native replacement for the reference's ``SQLPaxosLogger``
(``gigapaxos/SQLPaxosLogger.java:123`` — embedded SQL tables for
checkpoint/pause plus append-only journal files): here ALL durable state
is array-shaped, so the journal holds packed int32 column blocks (bulk
``tobytes`` appends, CRC-framed) and a checkpoint is one ``.npz``
snapshot of the engine arrays — recovery is a bulk array load plus a
vectorized rollforward, not a per-group cursor walk.
"""

from .journal import BlockType, Journal
from .checkpoint import load_checkpoint, save_checkpoint
from .logger import PaxosLogger

__all__ = [
    "BlockType",
    "Journal",
    "PaxosLogger",
    "load_checkpoint",
    "save_checkpoint",
]
