"""Example Replicable apps (ref: ``gigapaxos/examples/`` — NoopPaxosApp,
StatefulAdderApp) plus the hash-chain test fixture app."""

from .apps import HashChainApp, NoopPaxosApp, StatefulAdderApp

__all__ = ["HashChainApp", "NoopPaxosApp", "StatefulAdderApp"]
