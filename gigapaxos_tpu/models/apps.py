"""Example apps implementing the ``Replicable`` SPI.

* :class:`NoopPaxosApp` — echo app (ref: ``examples/noop/NoopPaxosApp.java``).
* :class:`StatefulAdderApp` — checkpointable counter
  (ref: ``examples/adder/StatefulAdderApp.java:1``).
* :class:`HashChainApp` — test fixture chaining a SHA-256 over every
  executed request so any ordering/duplication divergence changes the
  state hash (ref: ``testing/TESTPaxosApp.java:60,104,174``).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional

from ..interfaces.app import Replicable, Request


class NoopPaxosApp(Replicable):
    """Stateless echo: every request 'executes' trivially."""

    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        if hasattr(request, "response_value"):
            request.response_value = "noop-ack"
        return True

    def checkpoint(self, name: str) -> Optional[str]:
        return ""

    def restore(self, name: str, state: Optional[str]) -> bool:
        return True

    def get_request(self, stringified: str) -> Request:
        from ..packets.paxos_packets import RequestPacket

        return RequestPacket(request_value=stringified)


class StatefulAdderApp(Replicable):
    """Per-name integer accumulator; request value is the delta."""

    def __init__(self):
        self.totals: Dict[str, int] = {}

    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        name = request.get_service_name()
        try:
            delta = int(getattr(request, "request_value", "0") or 0)
        except ValueError:
            delta = 0
        self.totals[name] = self.totals.get(name, 0) + delta
        if hasattr(request, "response_value"):
            request.response_value = str(self.totals[name])
        return True

    def checkpoint(self, name: str) -> Optional[str]:
        return str(self.totals.get(name, 0))

    def restore(self, name: str, state: Optional[str]) -> bool:
        if state is None or state == "":
            self.totals.pop(name, None)
        else:
            self.totals[name] = int(state)
        return True

    def get_request(self, stringified: str) -> Request:
        from ..packets.paxos_packets import RequestPacket

        return RequestPacket(request_value=stringified)


class LinWritesLocReadsApp(StatefulAdderApp):
    """Linearizable writes, local reads (ref:
    ``examples/linwrites/LinWritesLocReadsApp.java:23`` over
    ``SimpleAppRequest.java:32`` COORDINATED_WRITE/LOCAL_READ): delta
    values coordinate through consensus like the adder; the ``"read"``
    request executes UNCOORDINATED against this replica's local state —
    sequentially-consistent reads at zero consensus cost.  The
    coordinator consults :meth:`is_coordinated` to route."""

    READ = "read"

    def is_coordinated(self, value: str) -> bool:
        return value != self.READ

    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        if getattr(request, "request_value", "") == self.READ:
            name = request.get_service_name()
            if hasattr(request, "response_value"):
                request.response_value = str(self.totals.get(name, 0))
            return True
        return super().execute(request, do_not_reply_to_client)


class HashChainApp(Replicable):
    """SHA-chained state: state' = sha256(state || request_value)."""

    def __init__(self):
        self.state: Dict[str, str] = {}
        self.n_executed: Dict[str, int] = {}

    def execute(self, request: Request, do_not_reply_to_client: bool = False) -> bool:
        name = request.get_service_name()
        prev = self.state.get(name, "")
        val = getattr(request, "request_value", "")
        h = hashlib.sha256((prev + val).encode("utf-8")).hexdigest()
        self.state[name] = h
        self.n_executed[name] = self.n_executed.get(name, 0) + 1
        if hasattr(request, "response_value"):
            request.response_value = h[:16]
        return True

    def checkpoint(self, name: str) -> Optional[str]:
        import json

        return json.dumps(
            {"h": self.state.get(name, ""), "n": self.n_executed.get(name, 0)}
        )

    def restore(self, name: str, state: Optional[str]) -> bool:
        import json

        if not state:
            self.state.pop(name, None)
            self.n_executed.pop(name, None)
            return True
        d = json.loads(state)
        if not d["h"]:
            # an untouched chain's checkpoint: normalize to ABSENT so a
            # member that restored it and one that never touched the name
            # compare equal (the RSM checks compare state.get(name))
            self.state.pop(name, None)
            self.n_executed.pop(name, None)
            return True
        self.state[name] = d["h"]
        self.n_executed[name] = d["n"]
        return True

    def get_request(self, stringified: str) -> Request:
        from ..packets.paxos_packets import RequestPacket

        return RequestPacket(request_value=stringified)
