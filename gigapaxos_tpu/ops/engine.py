"""The batched vectorized Paxos engine — the heart of the framework.

This replaces the reference's object-per-group event machines
(``PaxosInstanceStateMachine.java:117`` dispatching per-packet at 486-550,
``PaxosAcceptor.java:59``, ``PaxosCoordinatorState.java:57``) with a single
pure jitted transition over struct-of-array state for *all* G groups at once:

  * Acceptor state (``PaxosAcceptor.java:82-103``: ``_slot``, ``ballotNum``,
    ``ballotCoord``, accepted/committed maps) becomes int32 arrays ``[G]``
    plus fixed ``[G, W]`` slot-ring windows (W = in-flight slot cap, the
    ``SYNC_THRESHOLD``/out-of-order analog).
  * Coordinator state (``PaxosCoordinatorState.java:68-143``: ballot,
    prepare waitfor, myProposals slot map) becomes ``[G]`` phase/ballot
    arrays plus a ``[G, W]`` proposal ring.
  * Message passing (the reference's per-group NIO unicast/multicast of
    PREPARE/ACCEPT/ACCEPT_REPLY/DECISION packets) becomes ONE exchange per
    step of each replica's packed **state blob** — on real hardware an
    ``all_gather`` over the 'replica' mesh axis (ICI); in host-simulation a
    list of blobs with a ``heard`` mask for fault injection.

Protocol formulation ("state-exchange Paxos"): each replica publishes an
atomic snapshot (promised ballot, accepted window, learned decisions,
coordinator proposals, prepare intent).  Every replica can then *locally*:

  * promise: fold the max gathered prepare/proposal ballot into its own
    (``PaxosAcceptor.handlePrepare``/``acceptAndUpdateBallot`` analog);
  * accept: adopt the highest-ballot proposal per window lane
    (phase-2a/2b collapse: publishing the accepted window IS the
    accept-reply);
  * learn: a slot is decided when >= majority of gathered windows show the
    same (slot, ballot) accepted — every replica is a learner, so no
    separate DECISION/COMMIT message is needed (the gathered windows double
    as ``BatchedAcceptReply``+``BatchedCommit``);
  * elect: prepare quorum = count of gathered promises at my ballot;
    carryover = max-ballot accepted pvalue per lane among promisers' atomic
    (ballot, window) snapshots — the ``handlePrepareReply`` carryover rule
    (``PaxosInstanceStateMachine.java:945-975``).

Safety notes (why time-skewed snapshots are sound): every (slot, ballot,
value) shown in a window was genuinely accepted at some time; "a majority
ever accepted (b, v) for slot s" is exactly the Paxos chosen-value
condition, and the phase-1 carryover rule preserves it for higher ballots.
Within one ballot only that ballot's unique coordinator proposes, so a
majority at equal ballots implies equal values.

Ring convention: window lane ``j`` always holds slot ``s`` with
``s % W == j``.  All rings (accepted, decided, proposals) share it, so
windows align lane-for-lane across replicas and the whole step is
element-wise + [R]-axis reductions — no scatters, no dynamic shapes.

TPU lowering note: the step deliberately contains NO gathers — no
``argmax``+``take_along_axis`` row selection.  Measured on a v5e chip,
each such gather inside the fused step cost ~50-100ms at G=1M (vs ~10ms
for the rest of the step combined).  Every row/lane select is instead a
masked max, which is sound by Paxos value-uniqueness: rows agreeing on
(slot, ballot) necessarily hold the same value (one coordinator per
ballot proposes one value per slot), so "pick any matching row" ==
"masked max over matching rows".  Likewise the majority-rank frontier
uses an O(R^2) rank count instead of a sort, and ``% W`` is a bitmask
(W is required to be a power of two).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from .ballot import NULL, ballot_num, encode_ballot

# Coordinator phases (``PaxosCoordinator`` null / PaxosCoordinatorState
# preparing-vs-active distinction, ``PaxosCoordinatorState.java:68-143``).
IDLE = 0
PREPARING = 1
ACTIVE = 2

# Value-id space: NULL (-1) = empty lane; NOOP_VID (0) = hole-filling no-op
# (not folded into app state); real request vids are > 0.  Bit 30 marks an
# epoch-final stop request (``RequestPacket.stop``).
NOOP_VID = 0
STOP_BIT = 1 << 30

# numpy scalar, NOT jnp: a module-scope jnp constant initializes the JAX
# backend at import time — deadly when a site hook pins a remote backend
# whose init can hang (the process never reaches the code that pins cpu)
_BIG = np.int32(2 ** 30)


class EngineConfig(NamedTuple):
    """Static engine shape (all python ints — closed over by jit).

    ``window`` must be a power of two: lane residue (slot % W) compiles to
    a bitmask, which matters on TPU where integer modulo is ~10x an AND.
    """

    n_groups: int          # G: group capacity (PINSTANCES_CAPACITY analog)
    window: int = 16       # W: in-flight slots per group (ring size)
    req_lanes: int = 8     # K: new client requests admitted per group per step
    n_replicas: int = 3    # R: replica-axis size (mesh dim / gather width)


class EngineState(NamedTuple):
    """Per-replica engine state; every leaf int32 of shape [G] or [G, W]."""

    # --- group metadata ---
    member_mask: jnp.ndarray   # [G] bitmask of replica ids in the group (0 = inert)
    majority: jnp.ndarray      # [G] popcount(member_mask)//2 + 1
    version: jnp.ndarray       # [G] epoch number (reconfiguration)
    stopped: jnp.ndarray       # [G] 1 after an epoch-final stop executed
    tag: jnp.ndarray           # [G] instance identity (hash of name:epoch).
    #   Rows are REUSED across instances (paxosID+version keying is by row
    #   here, by string in the reference) — a stale holdout still running
    #   the previous tenant of a row would otherwise merge its acceptor /
    #   decision columns into the new tenant's consensus (a decided stop
    #   of name A executing inside name B's RSM — chaos-soak find).  The
    #   blob ships the tag and step() ignores peers whose tag differs.
    # --- acceptor (ref: PaxosAcceptor.java:82-103) ---
    bal: jnp.ndarray           # [G] promised ballot (packed)
    exec_slot: jnp.ndarray     # [G] first un-executed slot (frontier)
    acc_bal: jnp.ndarray       # [G, W] accepted ballot per lane
    acc_vid: jnp.ndarray       # [G, W] accepted value id
    acc_slot: jnp.ndarray      # [G, W] absolute slot of the lane (NULL empty)
    # --- learner ---
    dec_vid: jnp.ndarray       # [G, W] learned decision value
    dec_slot: jnp.ndarray      # [G, W] learned decision slot (NULL empty)
    app_hash: jnp.ndarray      # [G] device-side hash-chain of executed vids
    n_execd: jnp.ndarray       # [G] total executed (== exec_slot minus noops... stats)
    # --- coordinator (ref: PaxosCoordinatorState.java:68-143) ---
    c_phase: jnp.ndarray       # [G] IDLE / PREPARING / ACTIVE
    c_bal: jnp.ndarray         # [G] my coordinator ballot
    c_next_slot: jnp.ndarray   # [G] next proposal slot to assign
    c_prop_vid: jnp.ndarray    # [G, W] my outstanding proposals (value)
    c_prop_slot: jnp.ndarray   # [G, W] my outstanding proposals (slot)


class Blob(NamedTuple):
    """What one replica publishes per step (the all_gather payload)."""

    tag: jnp.ndarray         # [G] sender's instance tag (cross-instance guard)
    bal: jnp.ndarray         # [G]
    exec_slot: jnp.ndarray   # [G]
    acc_bal: jnp.ndarray     # [G, W]
    acc_vid: jnp.ndarray     # [G, W]
    acc_slot: jnp.ndarray    # [G, W]
    dec_vid: jnp.ndarray     # [G, W]
    dec_slot: jnp.ndarray    # [G, W]
    prep_bal: jnp.ndarray    # [G]  my prepare intent (NULL if not PREPARING)
    prop_bal: jnp.ndarray    # [G]  my active ballot (NULL if not ACTIVE)
    prop_vid: jnp.ndarray    # [G, W]
    prop_slot: jnp.ndarray   # [G, W]


class StepOutputs(NamedTuple):
    """Per-step results surfaced to the host."""

    n_committed: jnp.ndarray   # [G] slots newly executed this step
    exec_base: jnp.ndarray     # [G] frontier before this step's advance
    exec_vid: jnp.ndarray      # [G, W] executed vids in slot order (NULL pad)
    n_admitted: jnp.ndarray    # [G] client reqs consumed from req_vid lanes
    maj_exec: jnp.ndarray      # [G] majority-rank execute frontier (GC mark)
    app_hash: jnp.ndarray      # [G] post-step app hash (RSM invariant probe)
    acc_new: jnp.ndarray       # [G, W] lanes newly accepted this step — the
    #   journal's log-before-send delta (AbstractPaxosLogger.logAndMessage
    #   rule: these rows must be durable before the blob is published)
    bal_new: jnp.ndarray       # [G] 1 where the promised ballot rose this
    #   step — must also be durable before the blob is published, even when
    #   no accept carries it (the reference logs promise-upgrading prepare
    #   replies before sending, PaxosInstanceStateMachine.handlePrepare);
    #   otherwise a crashed acceptor forgets a bare promise and can accept
    #   an older-ballot proposal it had promised against
    preempted_vid: jnp.ndarray  # [G, W] my proposals that lost their slot to
    #   another value (host re-proposes them; NULL elsewhere)


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def init_state(cfg: EngineConfig) -> EngineState:
    """All groups inert (member_mask 0) — the MultiArrayMap-of-capacity analog."""
    G, W = cfg.n_groups, cfg.window
    g = lambda fill: jnp.full((G,), fill, jnp.int32)
    gw = lambda fill: jnp.full((G, W), fill, jnp.int32)
    return EngineState(
        member_mask=g(0), majority=g(_BIG), version=g(0), stopped=g(0),
        tag=g(0),
        bal=g(NULL), exec_slot=g(0),
        acc_bal=gw(NULL), acc_vid=gw(NULL), acc_slot=gw(NULL),
        dec_vid=gw(NULL), dec_slot=gw(NULL),
        app_hash=g(0), n_execd=g(0),
        c_phase=g(IDLE), c_bal=g(NULL), c_next_slot=g(0),
        c_prop_vid=gw(NULL), c_prop_slot=gw(NULL),
    )


def make_blob(state: EngineState) -> Blob:
    """Atomic snapshot of what peers need; masked by coordinator phase."""
    preparing = state.c_phase == PREPARING
    active = state.c_phase == ACTIVE
    act2 = active[:, None]
    return Blob(
        tag=state.tag,
        bal=state.bal,
        exec_slot=state.exec_slot,
        acc_bal=state.acc_bal,
        acc_vid=state.acc_vid,
        acc_slot=state.acc_slot,
        dec_vid=state.dec_vid,
        dec_slot=state.dec_slot,
        prep_bal=jnp.where(preparing, state.c_bal, NULL),
        prop_bal=jnp.where(active, state.c_bal, NULL),
        prop_vid=jnp.where(act2, state.c_prop_vid, NULL),
        prop_slot=jnp.where(act2, state.c_prop_slot, NULL),
    )


def _mix(h, vid):
    """Deterministic app-hash fold (int32 wraparound is defined in XLA)."""
    return (h * jnp.int32(31) + vid) ^ (vid << 7)


def step(
    state: EngineState,
    g: Blob,                 # gathered blobs, every leaf with leading [R] axis
    heard: jnp.ndarray,      # [R] bool — which peers' blobs are live
    req_vid: jnp.ndarray,    # [G, K] new request value-ids (left-packed, NULL pad)
    want_coord: jnp.ndarray, # [G] bool — host FD election trigger
    my_id,                   # python int or traced scalar (replica-axis index)
    cfg: EngineConfig,
):
    """One vectorized consensus step for all G groups. Pure function.

    Returns (state', StepOutputs).  The caller journals the accepted-window
    delta of state' *before* publishing blob(state') — that preserves the
    reference's log-before-send rule (``AbstractPaxosLogger.logAndMessage``,
    ``AbstractPaxosLogger.java:157``).
    """
    G, W, K, R = cfg.n_groups, cfg.window, cfg.req_lanes, cfg.n_replicas
    if W <= 0 or W & (W - 1):
        # hard error (not an assert): under python -O a silent bitmask with
        # a non-power-of-two W would map slots to wrong ring lanes
        raise ValueError(f"window must be a power of two, got {W}")
    my_id = _i32(my_id)
    rids = jnp.arange(R, dtype=jnp.int32)
    lanes = jnp.arange(W, dtype=jnp.int32)
    lane_of = lambda s: s & jnp.int32(W - 1)  # slot -> ring lane (W = 2^k)

    # [R, G] — which gathered rows are valid senders for each group:
    # heard and a member of the group (per-group replica subsets,
    # ``groupMembers[]`` analog, PaxosInstanceStateMachine.java:176-188).
    in_group = ((state.member_mask[None, :] >> rids[:, None]) & 1) == 1
    # instance guard: a peer row speaking for a DIFFERENT tenant of this
    # row index (stale holdout after row reuse, or a not-yet-caught-up
    # joiner) is not part of this instance's consensus
    same_inst = g.tag == state.tag[None, :]               # [R, G]
    live = heard[:, None] & in_group & same_inst          # [R, G]
    live3 = live[:, :, None]                              # [R, G, 1]

    inert = state.member_mask == 0
    maj = state.majority
    # Am I a member of each group?  A replica holds rows for groups it does
    # not belong to (the [G] arrays are capacity, not membership); it must
    # neither mutate nor act on those rows (the reference simply has no
    # PaxosInstanceStateMachine object for such groups).
    i_member = ((state.member_mask >> my_id) & 1) == 1

    # ---- 1. promise update (handlePrepare / acceptAndUpdateBallot) ----
    in_prep = jnp.where(live, g.prep_bal, NULL)
    in_prop = jnp.where(live, g.prop_bal, NULL)
    max_prop = in_prop.max(axis=0)                        # [G]
    new_bal = jnp.maximum(state.bal, jnp.maximum(in_prep.max(axis=0), max_prop))

    # ---- 2. accept (handleAccept, PaxosAcceptor.acceptAndUpdateBallot) ----
    # Highest-ballot proposer wins; its ballot must equal the new promise.
    # Ballots encode the coordinator id, so at most ONE live row publishes
    # max_prop — the masked max over winning rows IS that row's window
    # (no argmax+gather; see the TPU lowering note in the module docstring).
    win3 = ((in_prop == max_prop[None, :]) & (max_prop[None, :] != NULL))[:, :, None]
    p_slot = jnp.where(win3, g.prop_slot, NULL).max(axis=0)   # [G, W]
    p_vid = jnp.where(win3, g.prop_vid, NULL).max(axis=0)
    acc_ok = (max_prop == new_bal) & (max_prop != NULL) & (state.stopped == 0)
    exec2 = state.exec_slot[:, None]
    in_win = (
        (p_slot >= exec2) & (p_slot < exec2 + W) & (p_vid != NULL)
        & (lane_of(p_slot) == lanes[None, :])             # ring-residue sanity
    )
    do_acc = acc_ok[:, None] & in_win
    acc_bal = jnp.where(do_acc, max_prop[:, None], state.acc_bal)
    acc_vid = jnp.where(do_acc, p_vid, state.acc_vid)
    acc_slot = jnp.where(do_acc, p_slot, state.acc_slot)
    # True journal delta: an unchanged in-flight proposal re-fires do_acc
    # every step until it decides — only a changed lane needs durability.
    acc_changed = do_acc & (
        (acc_bal != state.acc_bal) | (acc_vid != state.acc_vid)
        | (acc_slot != state.acc_slot)
    )

    # ---- 3. learn (the BatchedAcceptReply->DECISION collapse) ----
    ga_slot = jnp.where(live3, g.acc_slot, NULL)          # [R, G, W]
    ga_bal = jnp.where(live3, g.acc_bal, NULL)
    s_c = ga_slot.max(axis=0)                             # [G, W] newest slot per lane
    match_s = (ga_slot == s_c[None]) & (s_c[None] != NULL) & live3
    b_c = jnp.where(match_s, ga_bal, NULL).max(axis=0)    # [G, W]
    match = match_s & (ga_bal == b_c[None])
    n_match = match.sum(axis=0)                           # [G, W]
    detected = (n_match >= maj[:, None]) & (s_c != NULL)
    # matching rows agree on (slot, ballot) => same value (one coordinator
    # per ballot): masked max == "any matching row"
    det_vid = jnp.where(match, g.acc_vid, NULL).max(axis=0)

    # Decision candidates per lane: keep the SMALLEST undecided-needed slot
    # >= my frontier (so a lane never skips past an unexecuted decision).
    def cand(slot, vid, valid):
        ok = valid & (slot != NULL) & (slot >= exec2)
        return jnp.where(ok, slot, _BIG), vid

    c0_s, c0_v = cand(state.dec_slot, state.dec_vid, True)
    gd_slot = jnp.where(live3, g.dec_slot, NULL)
    gd_ok = (gd_slot != NULL) & (gd_slot >= exec2[None])
    gd_s = jnp.where(gd_ok, gd_slot, _BIG)
    c1_s = gd_s.min(axis=0)                               # [G, W]
    # rows at the min slot decided the SAME slot => same decided value
    c1_v = jnp.where(gd_s == c1_s[None], g.dec_vid, NULL).max(axis=0)
    c2_s, c2_v = cand(s_c, det_vid, detected)

    best = jnp.minimum(jnp.minimum(c0_s, c1_s), c2_s)
    have = best < _BIG
    dec_vid = jnp.where(
        have,
        jnp.where(best == c0_s, c0_v, jnp.where(best == c1_s, c1_v, c2_v)),
        state.dec_vid,
    )
    dec_slot = jnp.where(have, best, state.dec_slot)

    # ---- 4. execute: advance the in-order frontier (EEC analog,
    # PaxosInstanceStateMachine.extractExecuteAndCheckpoint:1511-1593) ----
    # A lane holds frontier+o exactly when its decided slot equals it, so
    # the lane->offset rotation is a [W, W] one-hot match, not a gather.
    slot_o = exec2 + lanes[None, :]                       # [G, W] frontier..+W
    eq_o = dec_slot[:, :, None] == slot_o[:, None, :]     # [G, Wlane, Woff]
    d_hit = eq_o.any(axis=1)                              # [G, Woff]
    d_vid_at = jnp.where(eq_o, dec_vid[:, :, None], NULL).max(axis=1)
    run = jnp.cumprod(d_hit.astype(jnp.int32), axis=1)
    n_adv = run.sum(axis=1)                               # [G]
    exec_new = state.exec_slot + n_adv

    h = state.app_hash
    n_execd = state.n_execd
    stop_seen = jnp.zeros((G,), bool)
    for o in range(W):  # static unroll; W small
        take = run[:, o] > 0
        vid_o = d_vid_at[:, o]
        real = take & (vid_o > 0)
        h = jnp.where(real, _mix(h, vid_o), h)
        n_execd = n_execd + real.astype(jnp.int32)
        stop_seen = stop_seen | (take & ((vid_o & STOP_BIT) != 0))
    stopped = jnp.maximum(state.stopped, stop_seen.astype(jnp.int32))

    # Majority-rank execute frontier: the slot that >= majority of replicas
    # have executed past (the medianCheckpointedSlot GC watermark analog,
    # PValuePacket.medianCheckpointedSlot / nodeSlotNumbers piggybacking).
    # k-th largest via O(R^2) rank count (no sort/gather): v is the maj-th
    # largest iff #{rows >= v} >= maj, and the largest such v is exact.
    ge = jnp.where(live, g.exec_slot, NULL)
    rank = (ge[:, None, :] <= ge[None, :, :]).sum(axis=1)  # [R, G]
    maj_exec = jnp.where(rank >= maj[None, :], ge, NULL).max(axis=0)
    maj_exec = jnp.maximum(maj_exec, jnp.int32(0))

    # ---- 5. coordinator ----
    me_coord = state.c_bal
    phase = state.c_phase
    # Preempted by a strictly higher ballot in the system (-> resign,
    # handlePrepareReply preemption, PaxosInstanceStateMachine.java:955-965).
    preempt = (phase != IDLE) & (new_bal > me_coord)
    phase = jnp.where(preempt, IDLE, phase)

    # Election start (checkRunForCoordinator, :1962-2072): host FD says go,
    # OR the promise ballot names ME as coordinator while I hold no
    # coordinator state — the "I'm ballot-coordinator but not running"
    # eligibility clause (:1992-2006).  This happens after crash recovery:
    # replayed accepts restore the promise ballot, but coordinator state is
    # volatile (HotRestore-only in the reference too), so without this rule
    # the group wedges — the failure detector sees the named coordinator
    # alive and never fires.
    from .ballot import COORD_MASK

    orphaned = ((new_bal & COORD_MASK) == my_id) & (new_bal != NULL)
    start = (want_coord | orphaned) & (phase == IDLE) & (~inert) & (stopped == 0)
    start_bal = encode_ballot(ballot_num(new_bal) + 1, my_id)
    c_bal = jnp.where(start, start_bal, me_coord)
    phase = jnp.where(start, PREPARING, phase)
    # Self-promise to my own prepare.
    new_bal = jnp.where(phase == PREPARING, jnp.maximum(new_bal, c_bal), new_bal)

    # Prepare quorum: peers whose published promise equals my ballot, +1 self.
    not_me = rids != my_id
    promised = (g.bal == c_bal[None, :]) & live & not_me[:, None]
    n_promise = promised.sum(axis=0) + 1
    quorum = (phase == PREPARING) & (n_promise >= maj)

    # Carryover (the one genuinely sparse flow in the reference — here a
    # lane-wise lexicographic max over promisers' atomic (ballot, window)
    # snapshots, two-stage to stay in int32: max slot per lane first, then
    # max ballot among rows showing that slot.  My own post-accept window
    # joins as the self-promise row.
    pa_ok = promised[:, :, None] & (ga_slot != NULL) & (ga_slot >= exec2[None])
    my_ok = (acc_slot != NULL) & (acc_slot >= exec2)
    all_ok = jnp.concatenate([pa_ok, my_ok[None]], axis=0)        # [R+1, G, W]
    all_slot = jnp.where(all_ok, jnp.concatenate([g.acc_slot, acc_slot[None]], 0), NULL)
    all_bal = jnp.where(all_ok, jnp.concatenate([g.acc_bal, acc_bal[None]], 0), NULL)
    all_vid = jnp.concatenate([g.acc_vid, acc_vid[None]], axis=0)
    co_slot = all_slot.max(axis=0)                                # [G, W]
    at_max = all_ok & (all_slot == co_slot[None])
    co_bal = jnp.where(at_max, all_bal, NULL).max(axis=0)
    pick = at_max & (all_bal == co_bal[None])
    co_has = co_slot != NULL
    # picked rows agree on (slot, ballot) => same accepted value
    co_vid = jnp.where(pick, all_vid, NULL).max(axis=0)

    won = quorum
    phase = jnp.where(won, ACTIVE, phase)
    # Safety bound for NEW proposals after an election: a promiser whose
    # execute frontier passed slot s has executed a decision for s that may
    # no longer appear in any window (its lane was reused).  So never invent
    # proposals (hole no-ops / fresh requests) below the promise set's max
    # frontier; those slots are learned via decision rings or sync instead.
    # (Carryover re-proposals below it are safe: synod rules guarantee the
    # carried value equals any chosen value.)
    prom_exec = jnp.where(promised, g.exec_slot, NULL).max(axis=0)  # [G]
    floor = jnp.maximum(exec_new, prom_exec)

    # Adopt carryovers into my proposal ring on victory.
    won2 = won[:, None]
    c_prop_vid = jnp.where(won2, jnp.where(co_has, co_vid, NULL), state.c_prop_vid)
    c_prop_slot = jnp.where(won2, jnp.where(co_has, co_slot, NULL), state.c_prop_slot)
    max_co_slot = co_slot.max(axis=1)                             # [G] (NULL if none)
    next_on_win = jnp.maximum(floor, max_co_slot + 1)
    c_next = jnp.where(won, next_on_win, state.c_next_slot)

    # Hole-filling no-ops: undecided slots in [floor, next) with no carryover
    # must be proposed as no-ops to unblock the frontier.
    exp_slot = exec_new[:, None] + lane_of(lanes[None, :] - exec_new[:, None])
    hole = (
        won2 & (exp_slot >= floor[:, None]) & (exp_slot < c_next[:, None])
        & (c_prop_slot != exp_slot) & (dec_slot != exp_slot)
    )
    c_prop_vid = jnp.where(hole, NOOP_VID, c_prop_vid)
    c_prop_slot = jnp.where(hole, exp_slot, c_prop_slot)

    # Retire proposals once their decision is learned (waitfor retirement,
    # PaxosCoordinatorState myProposals) or they fell below the frontier.
    # A retired lane whose decided value differs from my proposal was
    # PREEMPTED (another ballot chose a different value there) — surface
    # those vids so the host can re-propose them at a fresh slot (the
    # reference's PREEMPTED packet -> re-propose path, PValuePacket
    # PREEMPTED / PaxosInstanceStateMachine.java:955-965).
    is_active = phase == ACTIVE
    dec_at_prop = dec_slot == c_prop_slot                 # lane-aligned
    retire = (c_prop_slot != NULL) & (dec_at_prop | (c_prop_slot < exec2))
    preempted_vid = jnp.where(
        retire & (dec_vid != c_prop_vid) & (c_prop_vid > 0),  # >0: no NOOPs
        c_prop_vid, NULL,
    )
    c_prop_vid = jnp.where(retire, NULL, c_prop_vid)
    c_prop_slot = jnp.where(retire, NULL, c_prop_slot)

    # Stop-request ordering (proposeStop semantics, PaxosManager.java:1269-
    # 1390): once a stop is proposed or decided, admit nothing more.
    stopping = ((c_prop_vid != NULL) & ((c_prop_vid & STOP_BIT) != 0)).any(axis=1)
    dec_stop = (
        (dec_slot != NULL) & (dec_slot >= exec2) & ((dec_vid & STOP_BIT) != 0)
    ).any(axis=1)
    may_admit = is_active & (stopped == 0) & (~stopping) & (~dec_stop)
    # ...and within this step's batch, nothing after a stop lane.
    req_stop = (req_vid != NULL) & ((req_vid & STOP_BIT) != 0)
    no_stop_before = jnp.cumprod(1 - req_stop.astype(jnp.int32), axis=1)
    no_stop_before = jnp.concatenate(
        [jnp.ones((G, 1), jnp.int32), no_stop_before[:, :-1]], axis=1
    )

    # Admit new client requests: consecutive slots from c_next, bounded by
    # the majority window (don't outrun a majority's rings) and free lanes.
    # c_next must never lag the frontier (a recovered snapshot can be a few
    # slots behind the replayed decisions — proposing at an already-decided
    # slot would silently lose the request).
    c_next = jnp.where(is_active, jnp.maximum(c_next, exec_new), c_next)
    ks = jnp.arange(K, dtype=jnp.int32)
    bound = maj_exec + W
    cand_slot_k = c_next[:, None] + ks[None, :]           # [G, K]
    cand_lane = lane_of(cand_slot_k)
    oh_k = cand_lane[:, :, None] == lanes[None, None, :]  # [G, K, W] one-hot
    lane_busy = (oh_k & (c_prop_slot != NULL)[:, None, :]).any(axis=2)
    dec_at_cand = jnp.where(oh_k, dec_slot[:, None, :], NULL).max(axis=2)
    can_k = (
        may_admit[:, None] & (no_stop_before > 0)
        & (req_vid != NULL) & (cand_slot_k < bound[:, None]) & (~lane_busy)
        & (dec_at_cand != cand_slot_k)   # never re-propose a decided slot
    )
    admit = jnp.cumprod(can_k.astype(jnp.int32), axis=1)  # contiguous prefix
    n_admit = admit.sum(axis=1)                           # [G]
    onehot = oh_k & (admit[:, :, None] > 0)
    add_vid = jnp.where(onehot, req_vid[:, :, None], 0).sum(axis=1)
    add_slot = jnp.where(onehot, cand_slot_k[:, :, None], 0).sum(axis=1)
    newly = onehot.any(axis=1)
    c_prop_vid = jnp.where(newly, add_vid, c_prop_vid)
    c_prop_slot = jnp.where(newly, add_slot, c_prop_slot)
    c_next = c_next + n_admit

    new_state = EngineState(
        member_mask=state.member_mask, majority=state.majority,
        version=state.version, stopped=stopped, tag=state.tag,
        bal=new_bal, exec_slot=exec_new,
        acc_bal=acc_bal, acc_vid=acc_vid, acc_slot=acc_slot,
        dec_vid=dec_vid, dec_slot=dec_slot,
        app_hash=h, n_execd=n_execd,
        c_phase=phase, c_bal=c_bal, c_next_slot=c_next,
        c_prop_vid=c_prop_vid, c_prop_slot=c_prop_slot,
    )
    # Non-member rows stay frozen (and report nothing).
    m1 = i_member
    m2 = i_member[:, None]
    keep = lambda new, old: jnp.where(m1 if new.ndim == 1 else m2, new, old)
    new_state = EngineState(*(keep(n, o) for n, o in zip(new_state, state)))
    outputs = StepOutputs(
        n_committed=jnp.where(m1, n_adv, 0),
        exec_base=state.exec_slot,
        exec_vid=jnp.where(m2 & (run > 0), d_vid_at, NULL),
        n_admitted=jnp.where(m1, n_admit, 0),
        maj_exec=jnp.where(m1, maj_exec, 0),
        app_hash=new_state.app_hash,
        acc_new=(m2 & acc_changed).astype(jnp.int32),
        bal_new=(new_state.bal != state.bal).astype(jnp.int32),
        preempted_vid=jnp.where(m2, preempted_vid, NULL),
    )
    return new_state, outputs


# ---------------------------------------------------------------------------
# Packed host-exchange interface.
#
# The deployed (socket/loopback) runtime moves every blob leaf host<->device
# each tick.  Doing that as ~50 per-leaf jnp.asarray / device_put / asarray
# dispatches costs far more than the engine step itself at loopback scale
# (it was ~70% of a node's tick on a 1-core host).  These helpers move each
# direction as ONE int32 vector: the gathered peer blobs upload as a single
# [R, N] array (sliced back into Blob leaves INSIDE the jitted step, where
# the slices fuse for free), and the step's outputs + fresh publish blob
# come back as single vectors split into numpy views on the host.
#
# The vector layout intentionally equals the ``C`` wire frame body
# (Blob._fields order, C-order ravel): a received frame's payload IS the
# packed row, byte-for-byte, so the transport needs no re-packing either.
# ---------------------------------------------------------------------------

def _leaf_shapes(fields, cfg: EngineConfig):
    G, W = cfg.n_groups, cfg.window
    return [
        (name, (G,) if name in _G_LEAVES else (G, W)) for name in fields
    ]


# [G]-shaped leaves across Blob and StepOutputs (everything else is [G, W])
_G_LEAVES = frozenset((
    "tag", "bal", "exec_slot", "prep_bal", "prop_bal",
    "n_committed", "exec_base", "n_admitted", "maj_exec", "app_hash",
    "bal_new",
))


import functools


@functools.lru_cache(maxsize=None)
def blob_vec_len(cfg: EngineConfig) -> int:
    # memoized: recomputing the shape walk on every received frame would
    # tax the exact hot path the packed codec exists to relieve
    return sum(
        int(np.prod(s)) for _n, s in _leaf_shapes(Blob._fields, cfg)
    )


@functools.lru_cache(maxsize=None)
def out_vec_len(cfg: EngineConfig) -> int:
    return sum(
        int(np.prod(s)) for _n, s in _leaf_shapes(StepOutputs._fields, cfg)
    )


def pack_blob(blob: Blob) -> jnp.ndarray:
    """[N] device vector in Blob._fields order (== wire frame body)."""
    return jnp.concatenate([jnp.ravel(leaf) for leaf in blob])


def _unpack(vec, fields, cfg: EngineConfig, cls, batched: bool):
    leaves = []
    off = 0
    for name, shape in _leaf_shapes(fields, cfg):
        n = int(np.prod(shape))
        chunk = vec[..., off:off + n]
        off += n
        full = (vec.shape[0],) + shape if batched else shape
        leaves.append(chunk.reshape(full))
    return cls(*leaves)


def unpack_gathered(gvec: jnp.ndarray, cfg: EngineConfig) -> Blob:
    """[R, N] packed peer blobs -> Blob of [R, ...] leaves (inside jit)."""
    return _unpack(gvec, Blob._fields, cfg, Blob, batched=True)


def split_out_vec(vec: np.ndarray, cfg: EngineConfig) -> StepOutputs:
    """Host-side: one transferred [M] vector -> StepOutputs of np views."""
    return _unpack(
        np.asarray(vec), StepOutputs._fields, cfg, StepOutputs, batched=False
    )


def split_blob_vec(vec: np.ndarray, cfg: EngineConfig) -> Blob:
    return _unpack(
        np.asarray(vec), Blob._fields, cfg, Blob, batched=False
    )


def step_host(
    state: EngineState,
    gvec: jnp.ndarray,       # [R, N] packed gathered blobs
    heard: jnp.ndarray,
    req_vid: jnp.ndarray,
    want_coord: jnp.ndarray,
    my_id: jnp.ndarray,
    *,
    cfg: EngineConfig,
):
    """One step over packed I/O: returns (state', out_vec, blob_vec)."""
    g = unpack_gathered(gvec, cfg)
    new_state, out = step(state, g, heard, req_vid, want_coord, my_id, cfg=cfg)
    out_vec = jnp.concatenate([jnp.ravel(leaf) for leaf in out])
    blob_vec = pack_blob(make_blob(new_state))
    return new_state, out_vec, blob_vec
